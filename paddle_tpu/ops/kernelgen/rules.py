"""KERNEL_RULES: the per-op lowering table for the Pallas codegen tier.

Mirrors the ``register_emit`` pattern (core/emit/rules.py) one level
down: where an emit rule replaces a kernel's *tracing*, a KERNEL_RULE
describes how a fused sub-op lowers *inside* one generated Pallas kernel
body, operating on flat 1-D block values instead of logical arrays.

Three rule kinds:

``ew``
    Elementwise compute (activations, binaries, comparisons, optimizer
    updates, fills).  The default body is the op's own registered kernel
    impl applied to the flat block values — elementwise jnp expressions
    are shape-agnostic lane-for-lane, so reusing the impl verbatim makes
    bitwise parity with the replay path *by construction* rather than by
    transcription.  Only ops whose impl reads a logical shape
    (``label_smooth``'s class count, the ``fill_*`` lane counts) carry a
    custom body.

``layout``
    Zero-flop glue (reshape/squeeze/unsqueeze/flatten/transpose/assign-
    like).  No body: the plan builder either treats them as flat-order
    identities inside the kernel or hoists order-changing transposes out
    as XLA glue between kernel segments (see builder docstring).

``rng``
    Sub-ops that draw from ctx.rng.  The *draw* happens outside the
    kernel (``draw(key, ins_avals, attrs)``) with exactly the impl's key
    discipline, and the drawn array rides into the kernel as one more
    tiled ref — bitwise identical to the replay path because the draw IS
    the replay path's draw; only the surrounding arithmetic moves into
    the kernel.

Optimizer rules additionally declare ``aliases`` (output slot -> input
slot) so the builder can donate Param/Moment refs through
``input_output_aliases`` — the fused-Adam in-place update.
"""
import jax
import jax.numpy as jnp

from ...core.dtypes import jax_dtype
from ...core.registry import get_op

__all__ = ['KERNEL_RULES', 'KRule', 'rule_names']


class KRule(object):
    __slots__ = ('kind', 'body', 'draw', 'aliases', 'bcast_y',
                 'shape_only')

    def __init__(self, kind='ew', body=None, draw=None, aliases=None,
                 bcast_y=False, shape_only=()):
        self.kind = kind              # 'ew' | 'layout' | 'rng'
        self.body = body              # None => op impl on flat blocks
        self.draw = draw              # rng only: (key, avals, attrs) ->
        self.aliases = aliases or {}  # out slot -> in slot (donation)
        self.bcast_y = bcast_y        # binary op with _bcast_y(Y, axis)
        self.shape_only = shape_only  # slots read for shape, not data


KERNEL_RULES = {}


def rule_names():
    return tuple(sorted(KERNEL_RULES))


def _r(name, **kw):
    KERNEL_RULES[name] = KRule(**kw)


class _NoRngCtx(object):
    """ctx handed to passthrough impl bodies inside a kernel: any rng
    draw at this point is a rule-table bug (rng ops must be kind='rng'
    so their draw happens outside the kernel)."""
    amp = False
    mesh = None
    is_infer = False

    def rng(self, n=0):
        raise RuntimeError('KERNEL_RULES bug: in-kernel ctx.rng draw — '
                           'register the op as an rng rule')

    def sub_ctx(self, sub):
        return self


NO_RNG_CTX = _NoRngCtx()


class _FixedKeyCtx(object):
    """ctx for out-of-kernel rng draws: .rng() returns the stream key the
    caller derived (OpCtx.sub_ctx fold-in on the kernel path, EmitCtx
    stream fold-in on the emit path) — same discipline as the replay."""
    amp = False
    mesh = None
    is_infer = False

    def __init__(self, key):
        self._key = key

    def rng(self, n=0):
        return self._key


# --------------------------------------------------- elementwise compute
# Default bodies (impl passthrough).  _bcast_y binaries are flagged so the
# builder can align Y through the same axis/reshape semantics the impl
# would apply before the values reach the kernel.
for _name in ('elementwise_add', 'elementwise_sub', 'elementwise_mul',
              'elementwise_div', 'elementwise_pow', 'elementwise_max',
              'elementwise_min', 'elementwise_mod',
              'elementwise_floordiv', 'equal', 'not_equal', 'less_than',
              'less_equal', 'greater_than', 'greater_equal'):
    _r(_name, bcast_y=True)

for _name in ('scale', 'cast', 'clip', 'relu', 'relu6', 'sigmoid',
              'tanh', 'exp', 'log', 'sqrt', 'rsqrt', 'abs', 'square',
              'sign', 'floor', 'ceil', 'round', 'reciprocal', 'pow',
              'leaky_relu', 'elu', 'selu', 'softplus', 'softsign',
              'brelu', 'hard_sigmoid', 'swish', 'stanh', 'logsigmoid',
              'soft_relu', 'hard_shrink', 'softshrink', 'tanh_shrink',
              'thresholded_relu', 'erf', 'sin', 'cos', 'increment',
              'logical_and', 'logical_or', 'logical_not', 'logical_xor',
              'assign', 'fill_zeros_like'):
    _r(_name)


def _label_smooth_body(ins, attrs, info):
    # ops/tensor.py label_smooth, with the class count taken from the
    # LOGICAL input shape (the flat block lost it)
    x = ins['X']
    eps = attrs.get('epsilon', 0.0)
    if 'PriorDist' in ins:
        return {'Out': (1 - eps) * x + eps * ins['PriorDist']}
    return {'Out': (1 - eps) * x + eps / info.in_shape('X')[-1]}


_r('label_smooth', body=_label_smooth_body)


def _fill_constant_body(ins, attrs, info):
    # ops/tensor.py fill_constant over this value's in-kernel lane count
    from ..tensor import _fill_value
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    return {'Out': jnp.full((info.lanes,),
                            _fill_value(attrs['value'], dtype),
                            dtype=dtype)}


_r('fill_constant', body=_fill_constant_body)


def _fill_bsl_body(ins, attrs, info):
    from ..tensor import _fill_value
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    return {'Out': jnp.full((info.lanes,),
                            _fill_value(attrs['value'], dtype),
                            dtype=dtype)}


_r('fill_constant_batch_size_like', body=_fill_bsl_body,
   shape_only=('Input',))

# ------------------------------------------------------------ layout glue
for _name in ('reshape', 'squeeze', 'unsqueeze', 'flatten', 'transpose'):
    _r(_name, kind='layout')


# ------------------------------------------------------------- rng rules
def _dropout_draw(key, avals, attrs):
    # exactly ops/nn.py dropout's mask derivation (keep.astype(x.dtype))
    if attrs.get('is_test', False):
        return None                      # no draw: pure ew on this path
    p = attrs.get('dropout_prob', 0.5)
    shape, dtype = avals.in_aval('X')
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    return keep.astype(dtype)


def _dropout_body(ins, attrs, info, draw):
    x = ins['X']
    p = attrs.get('dropout_prob', 0.5)
    impl = attrs.get('dropout_implementation', 'downgrade_in_infer')
    if draw is None:                     # is_test: impl passthrough
        out = get_op('dropout').impl(NO_RNG_CTX, ins, attrs)
        return out
    mask = draw
    out = x * mask
    if impl == 'upscale_in_train' and p < 1.0:
        out = out / (1.0 - p)
    return {'Out': out, 'Mask': mask}


_r('dropout', kind='rng', draw=_dropout_draw, body=_dropout_body)


def _impl_draw(name):
    # whole-op draw: the generator IS the op; in-kernel body is identity
    def draw(key, avals, attrs):
        return get_op(name).impl(_FixedKeyCtx(key), {}, attrs)['Out']
    return draw


for _name in ('uniform_random', 'gaussian_random',
              'truncated_gaussian_random'):
    _r(_name, kind='rng', draw=_impl_draw(_name), body=None)

# ------------------------------------------------- optimizer updates
# impl passthrough + donation aliases (the fused-Adam in-place story)
_r('sgd', aliases={'ParamOut': 'Param'})
_r('momentum', aliases={'ParamOut': 'Param', 'VelocityOut': 'Velocity'})
_r('adam', aliases={'ParamOut': 'Param', 'Moment1Out': 'Moment1',
                    'Moment2Out': 'Moment2', 'Beta1PowOut': 'Beta1Pow',
                    'Beta2PowOut': 'Beta2Pow'})
_r('adamax', aliases={'ParamOut': 'Param', 'MomentOut': 'Moment',
                      'InfNormOut': 'InfNorm'})
_r('adagrad', aliases={'ParamOut': 'Param', 'MomentOut': 'Moment'})
_r('decayed_adagrad', aliases={'ParamOut': 'Param',
                               'MomentOut': 'Moment'})
_r('adadelta', aliases={'ParamOut': 'Param',
                        'AvgSquaredGradOut': 'AvgSquaredGrad',
                        'AvgSquaredUpdateOut': 'AvgSquaredUpdate'})
_r('rmsprop', aliases={'ParamOut': 'Param', 'MeanSquareOut': 'MeanSquare',
                       'MomentOut': 'Moment', 'MeanGradOut': 'MeanGrad'})
_r('ftrl', aliases={'ParamOut': 'Param',
                    'SquaredAccumOut': 'SquaredAccumulator',
                    'LinearAccumOut': 'LinearAccumulator'})
