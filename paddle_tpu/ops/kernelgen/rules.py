"""KERNEL_RULES: the per-op lowering table for the Pallas codegen tier.

Mirrors the ``register_emit`` pattern (core/emit/rules.py) one level
down: where an emit rule replaces a kernel's *tracing*, a KERNEL_RULE
describes how a fused sub-op lowers *inside* one generated Pallas kernel
body, operating on flat 1-D block values instead of logical arrays.

Three rule kinds:

``ew``
    Elementwise compute (activations, binaries, comparisons, optimizer
    updates, fills).  The default body is the op's own registered kernel
    impl applied to the flat block values — elementwise jnp expressions
    are shape-agnostic lane-for-lane, so reusing the impl verbatim makes
    bitwise parity with the replay path *by construction* rather than by
    transcription.  Only ops whose impl reads a logical shape
    (``label_smooth``'s class count, the ``fill_*`` lane counts) carry a
    custom body.

``layout``
    Zero-flop glue (reshape/squeeze/unsqueeze/flatten/transpose/assign-
    like).  No body: the plan builder either treats them as flat-order
    identities inside the kernel or hoists order-changing transposes out
    as XLA glue between kernel segments (see builder docstring).

``rng``
    Sub-ops that draw from ctx.rng.  The *draw* happens outside the
    kernel (``draw(key, ins_avals, attrs)``) with exactly the impl's key
    discipline, and the drawn array rides into the kernel as one more
    tiled ref — bitwise identical to the replay path because the draw IS
    the replay path's draw; only the surrounding arithmetic moves into
    the kernel.

``row`` / ``attention``
    Dedicated whole-op kernels for the cross-element reductions the flat
    1-D tier can't express: single-pass row reductions (softmax,
    layer_norm) and online-softmax tiled attention (flash_attention).
    Instead of joining an elementwise segment, the op owns one
    ``step(ins, attrs, info, tune, interpret)`` call that takes the
    op's logical (un-flattened) inputs and returns its outputs — the
    builder runs it between kernel segments like glue, but it IS a
    generated Pallas kernel inside.  An optional
    ``tune(attrs, avals, interpret)`` hook returns the autotune spec
    (signature / candidates / default / make_ins) that
    kernelgen/autotune.py searches and persists; its winner arrives back
    as ``step``'s ``tune`` argument.  Row bodies replicate the
    registered impls' exact f32 jnp sequences so the kernel stays
    bitwise vs the replay on every backend; flash_attention reuses
    ops/attention.py's own routing (which composes below its Pallas
    thresholds — bitwise on CPU smoke shapes, fused-Pallas on TPU).

Optimizer rules additionally declare ``aliases`` (output slot -> input
slot) so the builder can donate Param/Moment refs through
``input_output_aliases`` — the fused-Adam in-place update.
"""
import os

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dtypes import jax_dtype
from ...core.registry import get_op

__all__ = ['KERNEL_RULES', 'KRule', 'rule_names']


class KRule(object):
    __slots__ = ('kind', 'body', 'draw', 'aliases', 'bcast_y',
                 'shape_only', 'step', 'tune')

    def __init__(self, kind='ew', body=None, draw=None, aliases=None,
                 bcast_y=False, shape_only=(), step=None, tune=None):
        self.kind = kind              # 'ew'|'layout'|'rng'|'row'|
                                      # 'attention'
        self.body = body              # None => op impl on flat blocks
        self.draw = draw              # rng only: (key, avals, attrs) ->
        self.aliases = aliases or {}  # out slot -> in slot (donation)
        self.bcast_y = bcast_y        # binary op with _bcast_y(Y, axis)
        self.shape_only = shape_only  # slots read for shape, not data
        self.step = step              # row/attention: whole-op kernel
        self.tune = tune              # row/attention: autotune spec


KERNEL_RULES = {}


def rule_names():
    return tuple(sorted(KERNEL_RULES))


def _r(name, **kw):
    KERNEL_RULES[name] = KRule(**kw)


class _NoRngCtx(object):
    """ctx handed to passthrough impl bodies inside a kernel: any rng
    draw at this point is a rule-table bug (rng ops must be kind='rng'
    so their draw happens outside the kernel)."""
    amp = False
    mesh = None
    is_infer = False

    def rng(self, n=0):
        raise RuntimeError('KERNEL_RULES bug: in-kernel ctx.rng draw — '
                           'register the op as an rng rule')

    def sub_ctx(self, sub):
        return self


NO_RNG_CTX = _NoRngCtx()


class _FixedKeyCtx(object):
    """ctx for out-of-kernel rng draws: .rng() returns the stream key the
    caller derived (OpCtx.sub_ctx fold-in on the kernel path, EmitCtx
    stream fold-in on the emit path) — same discipline as the replay."""
    amp = False
    mesh = None
    is_infer = False

    def __init__(self, key):
        self._key = key

    def rng(self, n=0):
        return self._key


# --------------------------------------------------- elementwise compute
# Default bodies (impl passthrough).  _bcast_y binaries are flagged so the
# builder can align Y through the same axis/reshape semantics the impl
# would apply before the values reach the kernel.
for _name in ('elementwise_add', 'elementwise_sub', 'elementwise_mul',
              'elementwise_div', 'elementwise_pow', 'elementwise_max',
              'elementwise_min', 'elementwise_mod',
              'elementwise_floordiv', 'equal', 'not_equal', 'less_than',
              'less_equal', 'greater_than', 'greater_equal'):
    _r(_name, bcast_y=True)

for _name in ('scale', 'cast', 'clip', 'relu', 'relu6', 'sigmoid',
              'tanh', 'exp', 'log', 'sqrt', 'rsqrt', 'abs', 'square',
              'sign', 'floor', 'ceil', 'round', 'reciprocal', 'pow',
              'leaky_relu', 'elu', 'selu', 'softplus', 'softsign',
              'brelu', 'hard_sigmoid', 'swish', 'stanh', 'logsigmoid',
              'soft_relu', 'hard_shrink', 'softshrink', 'tanh_shrink',
              'thresholded_relu', 'erf', 'sin', 'cos', 'increment',
              'logical_and', 'logical_or', 'logical_not', 'logical_xor',
              'assign', 'fill_zeros_like'):
    _r(_name)


def _label_smooth_body(ins, attrs, info):
    # ops/tensor.py label_smooth, with the class count taken from the
    # LOGICAL input shape (the flat block lost it)
    x = ins['X']
    eps = attrs.get('epsilon', 0.0)
    if 'PriorDist' in ins:
        return {'Out': (1 - eps) * x + eps * ins['PriorDist']}
    return {'Out': (1 - eps) * x + eps / info.in_shape('X')[-1]}


_r('label_smooth', body=_label_smooth_body)


def _fill_constant_body(ins, attrs, info):
    # ops/tensor.py fill_constant over this value's in-kernel lane count
    from ..tensor import _fill_value
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    return {'Out': jnp.full((info.lanes,),
                            _fill_value(attrs['value'], dtype),
                            dtype=dtype)}


_r('fill_constant', body=_fill_constant_body)


def _fill_bsl_body(ins, attrs, info):
    from ..tensor import _fill_value
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    return {'Out': jnp.full((info.lanes,),
                            _fill_value(attrs['value'], dtype),
                            dtype=dtype)}


_r('fill_constant_batch_size_like', body=_fill_bsl_body,
   shape_only=('Input',))

# ------------------------------------------------------------ layout glue
for _name in ('reshape', 'squeeze', 'unsqueeze', 'flatten', 'transpose'):
    _r(_name, kind='layout')


# ------------------------------------------------------------- rng rules
def _dropout_draw(key, avals, attrs):
    # exactly ops/nn.py dropout's mask derivation (keep.astype(x.dtype))
    if attrs.get('is_test', False):
        return None                      # no draw: pure ew on this path
    p = attrs.get('dropout_prob', 0.5)
    shape, dtype = avals.in_aval('X')
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    return keep.astype(dtype)


def _dropout_body(ins, attrs, info, draw):
    x = ins['X']
    p = attrs.get('dropout_prob', 0.5)
    impl = attrs.get('dropout_implementation', 'downgrade_in_infer')
    if draw is None:                     # is_test: impl passthrough
        out = get_op('dropout').impl(NO_RNG_CTX, ins, attrs)
        return out
    mask = draw
    out = x * mask
    if impl == 'upscale_in_train' and p < 1.0:
        out = out / (1.0 - p)
    return {'Out': out, 'Mask': mask}


_r('dropout', kind='rng', draw=_dropout_draw, body=_dropout_body)


def _impl_draw(name):
    # whole-op draw: the generator IS the op; in-kernel body is identity
    def draw(key, avals, attrs):
        return get_op(name).impl(_FixedKeyCtx(key), {}, attrs)['Out']
    return draw


for _name in ('uniform_random', 'gaussian_random',
              'truncated_gaussian_random'):
    _r(_name, kind='rng', draw=_impl_draw(_name), body=None)

# ----------------------------------- dedicated row-reduction kernels
# softmax / layer_norm lower to single-pass row kernels: the logical
# array reshapes to (rows, cols), the grid tiles rows, and each kernel
# invocation reduces its rows' trailing axis in one VMEM-resident pass.
# The bodies replicate the registered impls' exact f32 jnp sequences
# (ops/nn.py) so the kernel is bitwise vs the replay — rows are
# independent, so partial trailing blocks are safe (Pallas masks the
# out-of-range stores).

_ROW_BLOCK_DEFAULT = 128
_ROW_BLOCK_CANDS = (8, 32, 128, 512)


def _row_view(shape, begin):
    """(rows, cols) of reducing a logical shape's trailing dims from
    ``begin``; both at least 1."""
    rows = cols = 1
    for d in shape[:begin]:
        rows *= int(d)
    for d in shape[begin:]:
        cols *= int(d)
    return max(rows, 1), max(cols, 1)


def _row_candidates(rows):
    cands, seen = [], set()
    for c in _ROW_BLOCK_CANDS:
        eff = min(c, rows)
        if eff in seen:
            continue
        seen.add(eff)
        cands.append({'block_rows': eff})
    return cands


def _row_tune_spec(stype, rows, cols, dt, extra_sig, make_ins,
                   interpret):
    from . import autotune
    if interpret and rows * cols > autotune.interpret_size_cap():
        return None
    return {
        'signature': (stype, rows, cols, dt, extra_sig, bool(interpret)),
        'candidates': _row_candidates(rows),
        'default': {'block_rows': min(_ROW_BLOCK_DEFAULT, rows)},
        'make_ins': make_ins,
    }


def _row_block(tune, rows):
    br = (tune or {}).get('block_rows', _ROW_BLOCK_DEFAULT)
    return max(min(int(br), rows), 1)


def _softmax_axis(attrs, ndim):
    ax = attrs.get('axis', -1)
    return ax + ndim if ax < 0 else ax


def _softmax_step(ins, attrs, info, tune, interpret):
    from jax.experimental import pallas as pl
    x = ins['X']
    if _softmax_axis(attrs, x.ndim) != x.ndim - 1:
        from .builder import KernelgenUnsupported
        raise KernelgenUnsupported(
            'softmax', 'axis %r is not the trailing dim (the row kernel '
            'reduces the last axis)' % (attrs.get('axis', -1),))
    rows, cols = _row_view(x.shape, x.ndim - 1)
    br = _row_block(tune, rows)

    def kernel(x_ref, o_ref):
        # jax.nn.softmax's forward sequence on f32 (ops/nn.py casts in):
        # max-subtract, exp, sum-normalize — per row
        xf = x_ref[...].astype(jnp.float32)
        m = jnp.max(xf, axis=-1, initial=-jnp.inf, keepdims=True)
        u = jnp.exp(xf - m)
        o_ref[...] = (u / jnp.sum(u, axis=-1, keepdims=True)).astype(
            o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=[pl.BlockSpec((br, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, cols), x.dtype),
        interpret=interpret,
    )(x.reshape(rows, cols))
    return {'Out': out.reshape(x.shape)}


def _softmax_tune(attrs, avals, interpret):
    from . import autotune
    shape, dt = avals.in_aval('X')
    if _softmax_axis(attrs, len(shape)) != len(shape) - 1:
        return None                  # step will raise; nothing to tune
    rows, cols = _row_view(shape, len(shape) - 1)

    def make_ins():
        return {'X': autotune.synth_value(shape, dt)}

    return _row_tune_spec('softmax', rows, cols, str(dt), (), make_ins,
                          interpret)


def _layer_norm_step(ins, attrs, info, tune, interpret):
    from jax.experimental import pallas as pl
    x = ins['X']
    begin = attrs.get('begin_norm_axis', 1)
    eps = attrs.get('epsilon', 1e-5)
    rows, cols = _row_view(x.shape, begin)
    scale, bias = ins.get('Scale'), ins.get('Bias')
    two_pass = os.environ.get('PT_TWO_PASS_NORM', '0') == '1'
    br = _row_block(tune, rows)

    def kernel(*refs):
        it = iter(refs)
        x_ref = next(it)
        s_ref = next(it) if scale is not None else None
        b_ref = next(it) if bias is not None else None
        y_ref, m_ref, v_ref = next(it), next(it), next(it)
        # ops/nn.py layer_norm's exact f32 statistics, per row
        xf = x_ref[...].astype(jnp.float32)
        if two_pass:
            m = jnp.mean(xf, axis=-1, keepdims=True)
            v = jnp.mean(jnp.square(xf - m), axis=-1, keepdims=True)
            y = (xf - m) * lax.rsqrt(v + eps)
        else:
            c = lax.stop_gradient(xf[:, :1])
            d = xf - c
            md = jnp.mean(d, axis=-1, keepdims=True)
            v = jnp.maximum(
                jnp.mean(jnp.square(d), axis=-1, keepdims=True)
                - jnp.square(md), 0.0)
            m = md + c
            y = (d - md) * lax.rsqrt(v + eps)
        if s_ref is not None:
            y = y * s_ref[...].reshape(1, cols)
        if b_ref is not None:
            y = y + b_ref[...].reshape(1, cols)
        y_ref[...] = y.astype(y_ref.dtype)
        m_ref[...] = m.reshape(-1)
        v_ref[...] = v.reshape(-1)

    in_specs = [pl.BlockSpec((br, cols), lambda i: (i, 0))]
    args = [x.reshape(rows, cols)]
    for p in (scale, bias):
        if p is not None:
            in_specs.append(pl.BlockSpec((cols,), lambda i: (0,)))
            args.append(p.reshape(cols))
    y2, m1, v1 = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((br, cols), lambda i: (i, 0)),
                   pl.BlockSpec((br,), lambda i: (i,)),
                   pl.BlockSpec((br,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((rows, cols), x.dtype),
                   jax.ShapeDtypeStruct((rows,), jnp.float32),
                   jax.ShapeDtypeStruct((rows,), jnp.float32)],
        interpret=interpret,
    )(*args)
    lead = tuple(x.shape[:begin])
    return {'Y': y2.reshape(x.shape), 'Mean': m1.reshape(lead),
            'Variance': v1.reshape(lead)}


def _layer_norm_tune(attrs, avals, interpret):
    from . import autotune
    shape, dt = avals.in_aval('X')
    begin = attrs.get('begin_norm_axis', 1)
    rows, cols = _row_view(shape, begin)
    slots = [s for s in ('X', 'Scale', 'Bias')
             if s == 'X' or _has_slot(avals, s)]

    def make_ins():
        return {s: autotune.synth_value(*avals.in_aval(s))
                for s in slots}

    return _row_tune_spec('layer_norm', rows, cols, str(dt),
                          (begin, len(slots)), make_ins, interpret)


def _has_slot(avals, slot):
    try:
        avals.in_aval(slot)
        return True
    except KeyError:
        return False


_r('softmax', kind='row', step=_softmax_step, tune=_softmax_tune)
_r('layer_norm', kind='row', step=_layer_norm_step,
   tune=_layer_norm_tune)


# ------------------------------------------ flash-attention dispatch
def _flash_step(ins, attrs, info, tune, interpret):
    # ops/attention.py owns the online-softmax Pallas kernel, its causal
    # + k_len masking, and its composed fallback below the Pallas
    # thresholds; the rule forwards the tuned block sizes and nothing
    # else, so fused and unfused launches share one routing (and are
    # bitwise on the composed route).
    from .. import attention as _att
    q, k, v = ins['Q'], ins['K'], ins['V']
    k_len = ins.get('KLength')
    if k_len is not None and getattr(k_len, 'ndim', 0) > 1:
        k_len = k_len.reshape(-1)
    kw = {}
    if tune:
        kw = {'block_q': int(tune['block_q']),
              'block_k': int(tune['block_k'])}
    return {'Out': _att.flash_attention(
        q, k, v, causal=attrs.get('causal', False),
        scale=attrs.get('scale'), k_len=k_len, **kw)}


def _flash_tune(attrs, avals, interpret):
    from . import autotune
    from .. import attention as _att
    if interpret:
        # no TPU: flash_attention composes (or interprets) — emulated
        # timings say nothing about Mosaic block behavior
        return None
    qs, qdt = avals.in_aval('Q')
    ks, _ = avals.in_aval('K')
    if len(qs) != 4 or len(ks) != 4:
        return None
    Tq, D = int(qs[2]), int(qs[3])
    Tk = int(ks[2])
    if D % 8 or Tk < _att._FWD_PALLAS_MIN_T:
        return None                  # composed route: blocks unused
    bqs = [b for b in (128, 256, 512) if Tq % b == 0]
    bks = [b for b in (128, 256, 512) if Tk % b == 0]
    cands = [{'block_q': bq, 'block_k': bk}
             for bq in bqs for bk in bks]
    if not cands:
        return None

    def make_ins():
        out = {s: autotune.synth_value(*avals.in_aval(s))
               for s in ('Q', 'K', 'V')}
        if _has_slot(avals, 'KLength'):
            import numpy as np
            ls, ldt = avals.in_aval('KLength')
            out['KLength'] = jnp.asarray(np.full(ls, Tk, ldt))
        return out

    return {
        'signature': ('flash_attention', tuple(qs), tuple(ks), str(qdt),
                      bool(attrs.get('causal', False)),
                      attrs.get('scale'), _has_slot(avals, 'KLength')),
        'candidates': cands,
        'default': None,             # impl's own 128/128 defaults
        'make_ins': make_ins,
    }


_r('flash_attention', kind='attention', step=_flash_step,
   tune=_flash_tune)

# ------------------------------------------------- optimizer updates
# impl passthrough + donation aliases (the fused-Adam in-place story)
_r('sgd', aliases={'ParamOut': 'Param'})
_r('momentum', aliases={'ParamOut': 'Param', 'VelocityOut': 'Velocity'})
_r('adam', aliases={'ParamOut': 'Param', 'Moment1Out': 'Moment1',
                    'Moment2Out': 'Moment2', 'Beta1PowOut': 'Beta1Pow',
                    'Beta2PowOut': 'Beta2Pow'})
_r('adamax', aliases={'ParamOut': 'Param', 'MomentOut': 'Moment',
                      'InfNormOut': 'InfNorm'})
_r('adagrad', aliases={'ParamOut': 'Param', 'MomentOut': 'Moment'})
_r('decayed_adagrad', aliases={'ParamOut': 'Param',
                               'MomentOut': 'Moment'})
_r('adadelta', aliases={'ParamOut': 'Param',
                        'AvgSquaredGradOut': 'AvgSquaredGrad',
                        'AvgSquaredUpdateOut': 'AvgSquaredUpdate'})
_r('rmsprop', aliases={'ParamOut': 'Param', 'MeanSquareOut': 'MeanSquare',
                       'MomentOut': 'Moment', 'MeanGradOut': 'MeanGrad'})
_r('ftrl', aliases={'ParamOut': 'Param',
                    'SquaredAccumOut': 'SquaredAccumulator',
                    'LinearAccumOut': 'LinearAccumulator'})
