"""Pallas codegen tier: lower fused_elementwise sub-programs to
generated kernels (docs/kernels.md).

Entry points:

* ``run_fused(ctx, ins, attrs)`` — kernel path (ops/fused.py tries this
  first when ``PT_KERNELGEN=1``), RNG keys from the executor OpCtx's
  ``sub_ctx`` fold-in.
* the ``register_emit('fused_elementwise')`` rule — emit path: the
  PR-12 memoized emitter dispatches fused groups here so generated
  kernels key into the same per-signature memo, RNG keys from the
  traced ``(base_key, stream)`` pair.

Both paths fall back LOUDLY through ops/_fallback.py on any failure
(``kernelgen.fallbacks`` counter, warn-once, ``PT_STRICT_KERNELS=1``
raises naming the unsupported sub-op) to the bitwise-reference replay.

Env vars (docs/kernels.md has the full table): ``PT_KERNELGEN``
(default: ON when the backend is TPU, OFF elsewhere — an explicit 0/1
always wins; the interpret-mode tier is a CPU test vehicle, ~9x slower
than XLA fusion), ``PT_KERNELGEN_BLOCK`` (static base block size,
default 1024), ``PT_KERNELGEN_INTERPRET`` (force/forbid interpret
mode; default: interpret unless the backend is TPU), ``PT_AUTOTUNE``
(0/1/cached — kernelgen/autotune.py block-size search + persistence).
"""
import os

from .rules import KERNEL_RULES, rule_names
from .builder import (KernelgenUnsupported, clear_plans, plan_for,
                      rng_rule_types)
from ...core.registry import register_emit

__all__ = ['KERNEL_RULES', 'KernelgenUnsupported', 'KERNELGEN_VERSION',
           'enabled', 'config_token', 'fingerprint_extra', 'rule_names',
           'run_fused', 'run_fused_emit', 'plan_for',
           'clear_plan_cache', 'note_fallback', 'unsupported_sub_ops']

# bump on any change to plan building / kernel emission semantics: it
# feeds the compile-cache fingerprint and the emitter memo key
KERNELGEN_VERSION = 2


def enabled():
    """Default ON when the backend is TPU (the tier IS the compute path
    there); default OFF elsewhere, where kernels would run under the
    Pallas interpreter — a bitwise test vehicle, not a fast path.  An
    explicit PT_KERNELGEN always wins, both directions."""
    v = os.environ.get('PT_KERNELGEN')
    if v is None:
        import jax
        return jax.default_backend() == 'tpu'
    return v in ('1', 'true', 'True')


def config_token():
    """Launch-signature / emitter-memo component: is the tier on, which
    codegen generation, and the autotune mode (a mode flip can change
    every kernel's block shapes, so memoized traces must not survive
    it)."""
    from . import autotune
    return ('kernelgen', 1 if enabled() else 0, KERNELGEN_VERSION,
            autotune.mode())


def fingerprint_extra():
    """AOT disk-cache fingerprint component: version + rule coverage
    (a new rule changes which sub-programs lower, so cached executables
    from an older table must not be reused) + autotune mode (tuned and
    untuned builds compile different block shapes)."""
    return ('kernelgen', KERNELGEN_VERSION, rule_names(),
            _autotune_mode())


def _autotune_mode():
    from . import autotune
    return autotune.mode()


def unsupported_sub_ops(attrs):
    """Sub-op types of one fused_elementwise op with no KERNEL_RULES
    entry (deduped, first-seen order) — the D016 lint surface."""
    out, seen = [], set()
    for sub in attrs.get('sub_ops') or ():
        t = sub['type']
        if t not in KERNEL_RULES and t not in seen:
            seen.add(t)
            out.append(t)
    return out


def clear_plan_cache():
    clear_plans()


def note_fallback(exc):
    """Count + route one kernelgen failure through the PR-6 loud
    fallback contract (raises under PT_STRICT_KERNELS=1)."""
    from .. import _fallback
    from ...observability import metrics
    metrics.counter('kernelgen.fallbacks').inc()
    detail = ''
    if isinstance(exc, KernelgenUnsupported):
        detail = "unsupported sub-op '%s' (%s)" % (exc.sub_op, exc.why)
    _fallback.kernel_fallback('kernelgen', exc, detail)


def _in_avals(xs):
    import numpy as np
    import jax.numpy as jnp
    return tuple((tuple(np.shape(x)), str(jnp.result_type(x)))
                 for x in xs)


def _keys_for(attrs, keyfn):
    """One key per rng-kind sub-op, in sub-op order.  A pinned seed attr
    overrides the stream key exactly as the impls themselves do."""
    import jax
    keys, si = [], 0
    for sub in attrs['sub_ops']:
        if sub['type'] in rng_rule_types():
            seed = sub['attrs'].get('seed', 0)
            keys.append(jax.random.key(seed) if seed
                        else keyfn(si, sub))
            si += 1
    return tuple(keys)


def _note_ok(plan):
    from ...observability import metrics
    metrics.counter('kernelgen.ops').inc()
    metrics.counter('kernelgen.kernels').inc(
        plan.n_kernels + plan.n_dsteps)


def _xs_of(ins):
    xs = ins.get('X', [])
    return list(xs) if isinstance(xs, (list, tuple)) else [xs]


def run_fused(ctx, ins, attrs):
    """Kernel-path entry: executor OpCtx RNG discipline
    (ctx.sub_ctx(sub).rng() — the replay path's exact keys).  Ctxs
    without sub-op streams (the lint abstract interpreter's InferCtx)
    draw from ctx.rng() directly, exactly like the replay path's
    hasattr guard — shapes are all that survive eval_shape anyway, so
    they also must never trigger a timed autotune search."""
    xs = _xs_of(ins)
    amp = bool(getattr(ctx, 'amp', False))
    plan = plan_for(attrs, _in_avals(xs), amp,
                    allow_search=hasattr(ctx, 'sub_ctx'))
    keys = _keys_for(
        attrs,
        lambda si, sub: (ctx.sub_ctx(sub) if hasattr(ctx, 'sub_ctx')
                         else ctx).rng())
    outs = plan.fn(tuple(xs), keys)
    _note_ok(plan)
    return {'Out': list(outs)}


def run_fused_emit(key, streams, amp, ins, attrs):
    """Emit-path entry: EmitCtx RNG discipline (fold_in of the traced
    base key with each sub-op's pinned stream — core/emit/emitter's
    _op_streams order)."""
    import jax
    xs = _xs_of(ins)
    plan = plan_for(attrs, _in_avals(xs), bool(amp))
    streams = list(streams or ())
    keys = _keys_for(
        attrs, lambda si, sub: jax.random.fold_in(key, streams[si]))
    outs = plan.fn(tuple(xs), keys)
    _note_ok(plan)
    return {'Out': list(outs)}


def _fctx_parts(fctx):
    """(key, streams, amp, mesh) from either the emitter's _FusedEmitCtx
    (key/streams attrs) or a plain EmitCtx (_key/_stream slots)."""
    key = getattr(fctx, 'key', None)
    if key is None:
        key = getattr(fctx, '_key', None)
    streams = getattr(fctx, 'streams', None)
    if streams is None:
        st = getattr(fctx, '_stream', None)
        streams = () if st is None else (st,)
    return (key, tuple(streams), bool(getattr(fctx, 'amp', False)),
            getattr(fctx, 'mesh', None))


@register_emit('fused_elementwise')
def _emit_fused(fctx, ins, attrs):
    """Emitter dispatch: generated kernels when the tier is on, else
    (or on loud fallback) the inline reference replay."""
    key, streams, amp, mesh = _fctx_parts(fctx)
    if enabled():
        try:
            return run_fused_emit(key, streams, amp, ins, attrs)
        except Exception as e:        # noqa: BLE001 — loud by contract
            note_fallback(e)          # raises under PT_STRICT_KERNELS
    from ...core.emit.emitter import _replay_fused
    return _replay_fused(ins, attrs, amp, mesh, key, streams)
