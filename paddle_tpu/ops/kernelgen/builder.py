"""Compile a fused_elementwise sub-program into generated Pallas kernels.

The plan builder walks the serialized sub-ops once (per canonical
sub-program signature — the same alpha-renamed key the emitter memo
uses, so every transformer layer's identical fused group shares one
plan) and partitions them into three step kinds:

``draw``
    RNG sub-ops' draws, computed OUTSIDE the kernel with exactly the
    replay path's key (impl seed attr / ctx stream fold-in, threaded in
    by the caller), so RNG parity is bitwise by construction.
``glue``
    Order-changing layout (a real transpose) and non-suffix broadcasts.
    These are zero-flop data-movement XLA ops; elementwise math commutes
    with them lane-for-lane, so hoisting them BETWEEN kernels preserves
    bitwise parity while keeping every compute op inside a kernel.
``dstep``
    A dedicated whole-op kernel (rule kinds ``row``/``attention``:
    softmax, layer_norm, flash_attention).  The op's logical inputs are
    materialized, its ``rule.step`` runs one generated kernel (a row
    reduction or the flash-attention call), and its outputs re-enter the
    plan as materialized values — with the executor's per-sub-op AMP
    cast policy (core/executor._amp_sub_ins/_amp_sub_outs) applied
    around the step exactly as the replay path applies it.  Block
    shapes come from kernelgen/autotune.py (searched + persisted per
    signature; ``rule.tune`` declares the candidates).
``kernel``
    A maximal run of elementwise/optimizer/rng-body sub-ops lowered into
    ONE ``pl.pallas_call``.  Every tensor is flattened to 1-D and tiled
    over a single grid axis (the base block size is autotuned per
    segment signature, static ``PT_KERNELGEN_BLOCK`` under
    ``PT_AUTOTUNE=0``):

    * values are grouped by flat element count; each group g gets block
      ``b_g = min(BLOCK, N_g)`` (lcm-lifted over any broadcast divisors)
      and ``tiles_g = ceil(N_g / b_g)``; the grid is ``max_g tiles_g``;
    * a group that exhausts its tiles early keeps a CLAMPED index map
      (``min(i, tiles_g - 1)``) — the fetch degenerates to a re-read of
      the last block and every store is guarded by
      ``pl.when(pid < tiles_g)``, so short groups neither read out of
      bounds nor double-apply updates even with donated (aliased) refs;
    * size-1 values ride as whole ``(1,)`` refs (stored once at pid 0);
      suffix-broadcast operands (the MLP bias-add shape) ride as whole
      ``(D,)`` refs tiled in-kernel, so the chain stays ONE kernel;
    * flat-order-preserving glue (reshape/squeeze/unsqueeze/flatten and
      unit-dim transposes) is a symbolic alias inside the kernel — zero
      data movement, zero flushes.

Optimizer sub-ops donate Param/Moment refs through
``input_output_aliases`` (rule-declared, single-reader checked): the
fused Adam update runs as ONE generated kernel updating its params,
moments and beta pows in place.

Differentiation: ``pallas_call`` has no general VJP, so each plan is a
``jax.custom_vjp`` whose backward replays the sub-program through the
registered kernels (ops/fused.py's ``_run_sub_op`` — the exact function
the forward is bitwise-equal to) with the drawn keys as residuals;
per-output stop_gradient therefore applies exactly as on the replay
path.

On CPU the generated calls run under ``interpret=True``
(``PT_KERNELGEN_INTERPRET`` overrides); there is no silent fallback
between the test and the kernel (the PR-6 gather lesson).
"""
import os

__all__ = ['KernelgenUnsupported', 'plan_for', 'clear_plans',
           'rng_rule_types']


class KernelgenUnsupported(Exception):
    """A sub-op (or shape pattern) the rule table can't lower; carries
    the sub-op name for PT_STRICT_KERNELS' loud raise and D016."""

    def __init__(self, sub_op, why):
        self.sub_op = sub_op
        self.why = why
        super(KernelgenUnsupported, self).__init__(
            "sub-op '%s': %s" % (sub_op, why))


_FULL_CAP = 8192      # max flat size for a whole-array broadcast ref
_BLOCK_CAP = 65536    # refuse lcm-lifted block sizes past this (VMEM)


def _block_base():
    return int(os.environ.get('PT_KERNELGEN_BLOCK', '1024'))


def _interpret():
    import jax
    on_tpu = jax.default_backend() == 'tpu'
    v = os.environ.get('PT_KERNELGEN_INTERPRET')
    if v is None:
        return not on_tpu
    want = v in ('1', 'true', 'True')
    if not want and not on_tpu:
        # an explicit =0 means "real Mosaic lowering" — impossible off
        # TPU; raising here (not deep inside a Mosaic error) keeps the
        # misconfiguration loud instead of silently interpreting
        raise KernelgenUnsupported(
            'kernelgen',
            'PT_KERNELGEN_INTERPRET=0 but the backend is %r — no TPU, '
            'interpret disabled' % jax.default_backend())
    return want


_RNG_TYPES = None


def rng_rule_types():
    global _RNG_TYPES
    if _RNG_TYPES is None:
        from .rules import KERNEL_RULES
        _RNG_TYPES = frozenset(
            n for n, r in KERNEL_RULES.items() if r.kind == 'rng')
    return _RNG_TYPES


def _size(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _lcm(a, b):
    x, y = a, b
    while y:
        x, y = y, x % y
    return a // x * b


def _bcast_y_shape(xs, ys, axis):
    """ops/math.py _bcast_y, on shapes only."""
    xs, ys = tuple(xs), tuple(ys)
    if xs == ys or len(ys) == 0:
        return ys
    ax = axis if axis >= 0 else len(xs) - len(ys)
    yshape = list(ys)
    while len(yshape) > 1 and yshape[-1] == 1 and ax + len(yshape) > len(xs):
        yshape = yshape[:-1]
    return tuple([1] * ax + yshape + [1] * (len(xs) - ax - len(yshape)))


def _flat_compatible(eff, out):
    """True when broadcasting eff -> out is pure leading-dim expansion,
    i.e. flat(broadcast(v)) == tile(flat(v)) — the only pattern a kernel
    can serve from a whole-array ref without a gather."""
    e = list(eff)
    while e and e[0] == 1:
        e.pop(0)
    if len(e) > len(out):
        return False
    return list(out[len(out) - len(e):]) == e


class _AbstractCtx(object):
    """eval_shape ctx: constant key (output shapes don't depend on it).
    No sub_ctx attr — _run_sub_op then uses the ctx for every sub-op."""
    amp = False
    mesh = None
    is_infer = False

    def rng(self, n=0):
        import jax
        return jax.random.key(0)


class _OneKeyCtx(object):
    """Replay ctx handing one fixed key: .rng() returns the key this rng
    sub-op drew with in the forward (impls with a seed attr ignore it,
    exactly as they did on the kernel path)."""
    amp = False
    mesh = None
    is_infer = False

    def __init__(self, key):
        self._key = key

    def rng(self, n=0):
        return self._key


def _abstract_replay(attrs, in_avals, amp):
    """Per-step {name: ShapeDtypeStruct} of every env write, via the
    REAL replay (ops/fused._run_sub_op) under jax.eval_shape — amp
    matching, _bcast_y, dtype promotion all come from the one true
    implementation instead of a transcription."""
    import jax
    from .. import fused as _fused
    sds = [jax.ShapeDtypeStruct(tuple(s), d) for s, d in in_avals]

    def run(*xs):
        env = dict(zip(attrs['arg_names'], list(xs)))
        ctx = _AbstractCtx()
        recs = []
        missing = object()
        for sub in attrs['sub_ops']:
            onames = [n for ns in sub['outputs'].values() for n in ns]
            before = {n: env.get(n, missing) for n in onames}
            _fused._run_sub_op(ctx, sub, env, amp)
            recs.append({n: env[n] for n in onames
                         if env.get(n, missing) is not before[n]})
        return recs

    return jax.eval_shape(run, *sds)


class _OpInfo(object):
    """Rule-body metadata: the logical shapes the flat block values
    lost, plus this op's in-kernel lane count."""

    def __init__(self, lanes, in_avals):
        self.lanes = lanes
        self._in = in_avals

    def in_shape(self, slot):
        return self._in[slot][0]

    def in_aval(self, slot):
        return self._in[slot]


class _AvalsView(object):
    def __init__(self, avals):
        self._a = avals or {}

    def in_aval(self, slot):
        return self._a[slot]

    def in_shape(self, slot):
        return self._a[slot][0]


class _Seg(object):
    """One open kernel segment under construction."""

    def __init__(self):
        self.ops = []          # (sub, rule, in_bind, out_bind, g, info,
                               #  draw_bind)
        self.entries = []      # kernel input refs: (mid, kind, size)
        self.entry_ix = {}     # (mid, kind) -> index
        self.entry_key = {}    # index -> (name, ver) | None
        self.entry_dt = {}     # index -> dtype str
        self.keys = {}         # value key -> root key (layout aliasing)
        self.key_aval = {}     # value key -> (shape, dtype str)
        self.groups = {}       # flat size -> set of bcast divisors

    def entry(self, mid, kind, size, key, dt):
        ek = (mid, kind)
        ix = self.entry_ix.get(ek)
        if ix is None:
            ix = len(self.entries)
            self.entry_ix[ek] = ix
            self.entries.append((mid, kind, size))
            self.entry_key[ix] = key
            self.entry_dt[ix] = dt
        return ix


class _Plan(object):
    __slots__ = ('fn', 'n_rng', 'n_kernels', 'n_glue', 'kernel_ops',
                 'groups', 'n_donated', 'n_dsteps', 'tuned')


_PLANS = {}


def clear_plans():
    _PLANS.clear()


def plan_for(attrs, in_avals, amp, allow_search=True):
    """Build-or-fetch the plan for one canonical fused signature.

    ``allow_search=False`` callers (the lint abstract interpreter, which
    reaches here under eval_shape) get a plan built on cached/default
    autotune choices — never a timed search."""
    from ...core.emit.emitter import _canon_attrs
    from . import autotune
    key = (_canon_attrs('fused_elementwise', attrs), tuple(in_avals),
           bool(amp), _interpret(), _block_base(), autotune.mode(),
           bool(allow_search))
    plan = _PLANS.get(key)
    if plan is None:
        plan = _build_plan(attrs, tuple(in_avals), bool(amp),
                           bool(allow_search))
        _PLANS[key] = plan
    return plan


def _blocks_for(base, groups):
    """Effective per-group block map for a candidate base block size
    (None when some lcm lift would exceed the VMEM cap)."""
    blocks = {}
    for g, ds in sorted(groups.items()):
        b = base
        for D in sorted(ds):
            b = _lcm(b, D)
            if b > _BLOCK_CAP:
                return None
        if g <= b:
            b = g              # g is a multiple of every D by compat
        blocks[g] = b
    return blocks


def _tuned_base(s, esc, amp, reads, final_keys, allow_search):
    """Autotuned base block size for one elementwise segment: candidate
    bases are deduped by the *effective* per-group block map, each is
    compiled + timed on synthesized inputs, the winner persists per
    segment signature (kernelgen/autotune.py).  Degenerate segments
    (scalar-only, one effective config, or giant interpret-mode groups)
    keep the static default with zero overhead."""
    from . import autotune
    static = _block_base()
    sizes = [g for g in s.groups if g > 1]
    if autotune.mode() == '0' or not sizes:
        return static
    if _interpret() and max(sizes) > autotune.interpret_size_cap():
        return static
    cands, seen = [], set()
    for b in dict.fromkeys((static, 256, 1024, 4096)):
        eff = _blocks_for(b, s.groups)
        if eff is None:
            continue
        ek = tuple(sorted(eff.items()))
        if ek in seen:
            continue
        seen.add(ek)
        cands.append({'base': b})
    if len(cands) <= 1:
        return static
    sig = ('ew',
           tuple(op[0]['type'] for op in s.ops),
           tuple((kind, size, s.entry_dt[ix])
                 for ix, (mid, kind, size) in enumerate(s.entries)),
           tuple((g, tuple(sorted(ds)))
                 for g, ds in sorted(s.groups.items())),
           tuple((_size(s.key_aval[k][0]), s.key_aval[k][1])
                 for k in esc),
           bool(amp), _interpret())

    def timer(cand):
        scratch = {'donated': 0}
        kspec = _compile_segment(s, esc, amp, reads, final_keys,
                                 scratch, cand['base'])

        def thunk():
            args = [autotune.synth_value((size,), s.entry_dt[ix])
                    for ix, (mid, kind, size)
                    in enumerate(kspec['entries'])]
            return kspec['call'](*args)

        return autotune.time_thunk(thunk)

    choice = autotune.choose('ew', sig, cands, timer, {'base': static},
                             allow_search)
    return int(choice['base'])


def _tune_step(stype, rule, sattrs, avals_d, allow_search):
    """Resolve one dedicated step's autotune choice (None = rule has no
    tuner / nothing viable: step uses its own defaults)."""
    from . import autotune
    if rule.tune is None or autotune.mode() == '0':
        return None
    interp = _interpret()
    spec = rule.tune(sattrs, _AvalsView(avals_d), interp)
    if not spec:
        return None

    def timer(cand):
        def thunk():
            return rule.step(spec['make_ins'](), sattrs,
                             _AvalsView(avals_d), cand, interp)
        return autotune.time_thunk(thunk)

    return autotune.choose(stype, spec['signature'], spec['candidates'],
                           timer, spec.get('default'), allow_search)


def _build_plan(attrs, in_avals, amp, allow_search=True):
    import jax
    import jax.numpy as jnp
    from .rules import KERNEL_RULES

    sub_ops = attrs['sub_ops']
    arg_names = list(attrs['arg_names'])
    out_names = list(attrs['out_names'])
    rng_types = rng_rule_types()

    for sub in sub_ops:
        if sub['type'] not in KERNEL_RULES:
            raise KernelgenUnsupported(sub['type'],
                                       'no KERNEL_RULES entry')

    recs = _abstract_replay(attrs, in_avals, amp)

    # ---- usage pre-pass: versions, read counts, last consumers
    cur = {n: 0 for n in arg_names}
    reads, lastuse = {}, {}
    for i, sub in enumerate(sub_ops):
        for slot, names in sub['inputs'].items():
            for n in names:
                k = (n, cur.get(n, 0))
                reads[k] = reads.get(k, 0) + 1
                lastuse[k] = i
        for n in recs[i]:
            cur[n] = cur.get(n, 0) + 1
    final_keys = set()
    for n in out_names:
        k = (n, cur.get(n, 0))
        final_keys.add(k)
        reads[k] = reads.get(k, 0) + 1

    # ---- walk state
    cur = {n: 0 for n in arg_names}
    loc = {}                   # key -> ('mat', mid) | ('sym', seg)
    aval = {}                  # key -> (shape, dtype str)
    for i, n in enumerate(arg_names):
        loc[(n, 0)] = ('mat', i)
        aval[(n, 0)] = (tuple(in_avals[i][0]), str(in_avals[i][1]))
    mid_next = [len(arg_names)]
    steps = []
    seg = [None]
    stats = {'kernels': 0, 'kernel_ops': 0, 'glue': 0, 'donated': 0,
             'dsteps': 0}
    all_groups = []
    tuned = []

    def new_mid():
        mid_next[0] += 1
        return mid_next[0] - 1

    def key_of(n):
        return (n, cur.get(n, 0))

    def _flush(upto):
        s = seg[0]
        seg[0] = None
        if s is None or not s.ops:
            return
        esc = [k for k in s.keys
               if lastuse.get(k, -1) >= upto or k in final_keys]
        if not esc:
            return             # fully dead segment: drop it
        sbase = _tuned_base(s, esc, amp, reads, final_keys,
                            allow_search)
        tuned.append(sbase)
        kspec = _compile_segment(s, esc, amp, reads, final_keys, stats,
                                 sbase)
        for k in esc:
            mid = new_mid()
            loc[k] = ('mat', mid)
            kspec['out_mids'].append(mid)
        steps.append(('kernel', kspec))
        stats['kernels'] += 1
        stats['kernel_ops'] += len(s.ops)
        all_groups.append(sorted(s.groups))

    def _as_mat(k):
        where = loc[k]
        if where[0] != 'mat':
            raise KernelgenUnsupported(
                '?', 'internal: %r not materialized' % (k,))
        return where[1]

    base = _block_base()
    rng_si = 0
    for i, sub in enumerate(sub_ops):
        stype = sub['type']
        rule = KERNEL_RULES[stype]
        written = recs[i]
        this_si = None
        if stype in rng_types:
            this_si = rng_si
            rng_si += 1

        # ---------------------------------------------- layout glue
        if rule.kind == 'layout':
            ik = key_of(sub['inputs']['X'][0])
            out_name = sub['outputs']['Out'][0]
            if out_name not in written:
                continue
            v = written[out_name]
            o_shape, o_dt = tuple(v.shape), str(v.dtype)
            identity = True
            if stype == 'transpose':
                perm = [int(a) for a in sub['attrs']['axis']]
                dims = aval[ik][0]
                nz = [p for p in perm if dims[p] != 1]
                identity = nz == sorted(nz)
            ok = (out_name, cur.get(out_name, 0) + 1)
            cur[out_name] = ok[1]
            if identity and loc[ik][0] == 'sym':
                s = seg[0]
                s.keys[ok] = s.keys[ik]        # flat alias, zero cost
                s.key_aval[ok] = (o_shape, o_dt)
                loc[ok] = ('sym', s)
            else:
                if loc[ik][0] == 'sym':
                    _flush(i)
                mid_in = _as_mat(ik)
                mid = new_mid()
                if identity:
                    steps.append(('glue', mid,
                                  (lambda x, sh=o_shape:
                                   jnp.reshape(x, sh)), [mid_in]))
                else:
                    steps.append(('glue', mid,
                                  (lambda x, p=tuple(perm):
                                   jnp.transpose(x, p)), [mid_in]))
                stats['glue'] += 1
                loc[ok] = ('mat', mid)
            aval[ok] = (o_shape, o_dt)
            continue

        # ------------------------------ rng whole-op draws (no body)
        if rule.kind == 'rng' and rule.body is None:
            out_name = sub['outputs']['Out'][0]
            v = written[out_name]
            mid = new_mid()
            steps.append(('draw', mid, this_si, rule, sub['attrs'],
                          None))
            ok = (out_name, cur.get(out_name, 0) + 1)
            cur[out_name] = ok[1]
            loc[ok] = ('mat', mid)
            aval[ok] = (tuple(v.shape), str(v.dtype))
            continue

        # -------------------- dedicated whole-op kernels (row/attention)
        if rule.kind in ('row', 'attention'):
            if any(loc[key_of(n)][0] == 'sym'
                   for names in sub['inputs'].values() for n in names):
                _flush(i)
            in_mids, in_avals_d = {}, {}
            for slot, names in sub['inputs'].items():
                in_mids[slot] = [_as_mat(key_of(n)) for n in names]
                if names:
                    in_avals_d[slot] = aval[key_of(names[0])]
            tune = _tune_step(stype, rule, sub['attrs'], in_avals_d,
                              allow_search)
            if tune is not None:
                tuned.append(tune)
            out_bind = {}
            for slot, names in sub['outputs'].items():
                binds = []
                for n in names:
                    if n not in written:
                        binds.append(None)
                        continue
                    v = written[n]
                    mid = new_mid()
                    ok = (n, cur.get(n, 0) + 1)
                    cur[n] = ok[1]
                    loc[ok] = ('mat', mid)
                    aval[ok] = (tuple(v.shape), str(v.dtype))
                    binds.append(mid)
                out_bind[slot] = binds
            steps.append(('dstep', sub, rule, in_mids, out_bind,
                          dict(in_avals_d), tune))
            stats['dsteps'] += 1
            continue

        # --------------------------------------- in-kernel compute op
        out_sizes = {n: _size(v.shape) for n, v in written.items()}
        if not out_sizes:
            continue
        g = max(out_sizes.values())
        if g == 0:
            raise KernelgenUnsupported(stype, 'zero-size tensor')
        O = ()
        for n, v in written.items():
            if _size(v.shape) == g:
                O = tuple(v.shape)
                break
        for n, sz in out_sizes.items():
            if sz not in (g, 1):
                raise KernelgenUnsupported(
                    stype, 'output %s size %d vs group size %d'
                    % (n, sz, g))

        x_shape = None
        if sub['inputs'].get('X'):
            x_shape = aval[key_of(sub['inputs']['X'][0])][0]

        # classify operands first (size-based, loc-independent), so a
        # needed flush happens BEFORE any sym operand is resolved
        classified = []        # (slot, first, key, cls, eff, size, dt)
        for slot, names in sub['inputs'].items():
            if slot in rule.shape_only:
                continue
            for nidx, n in enumerate(names):
                k = key_of(n)
                s_in, dt_in = aval[k]
                size = _size(s_in)
                eff = s_in
                if rule.bcast_y and slot == 'Y' and x_shape is not None:
                    eff = _bcast_y_shape(x_shape, s_in,
                                         sub['attrs'].get('axis', -1))
                compat = _flat_compatible(eff, O)
                if size == g and compat and g > 1:
                    cls = 'direct'
                elif size == 1:
                    cls = 'scalar'
                elif compat and size <= _FULL_CAP and g > 1 \
                        and g % size == 0 \
                        and _lcm(base, size) <= _BLOCK_CAP:
                    cls = 'bcast'
                elif g == 1:
                    raise KernelgenUnsupported(
                        stype, 'tensor input into a scalar group')
                else:
                    cls = 'glue'
                classified.append((slot, nidx == 0, k, cls, eff, size,
                                   dt_in))
        if any(cls in ('bcast', 'glue') and loc[k][0] == 'sym'
               for _, _, k, cls, _, _, _ in classified):
            _flush(i)

        s = seg[0]
        if s is None:
            s = _Seg()
            seg[0] = s

        in_bind = {}
        in_avals_by_slot = {}
        for slot, first, k, cls, eff, size, dt_in in classified:
            if first:
                in_avals_by_slot[slot] = (aval[k][0], dt_in)
            where = loc[k]
            if cls in ('direct', 'scalar') and where[0] == 'sym':
                od = ('sym', s.keys[k])
            elif cls == 'direct':
                ix = s.entry(where[1], 'tile', size, k, dt_in)
                s.groups.setdefault(size, set())
                od = ('ref', ix, 'tile', 0)
            elif cls == 'scalar':
                ix = s.entry(where[1], 'scalar', 1, k, dt_in)
                od = ('ref', ix, 'scalar', 0)
            elif cls == 'bcast':
                ix = s.entry(where[1], 'bcast', size, k, dt_in)
                s.groups.setdefault(g, set()).add(size)
                od = ('ref', ix, 'bcast', size)
            else:              # glue: materialize the broadcast via XLA
                nm = new_mid()
                steps.append(('glue', nm,
                              (lambda x, es=tuple(eff), Os=O:
                               jnp.broadcast_to(jnp.reshape(x, es),
                                                Os)), [_as_mat(k)]))
                stats['glue'] += 1
                ix = s.entry(nm, 'tile', g, None, dt_in)
                s.groups.setdefault(g, set())
                od = ('ref', ix, 'tile', 0)
            in_bind.setdefault(slot, []).append(od)

        # dropout's mask rides in as one more tiled ref
        draw_bind = None
        if rule.kind == 'rng':
            xa = aval[key_of(sub['inputs']['X'][0])]
            if not sub['attrs'].get('is_test', False):
                mid = new_mid()
                steps.append(('draw', mid, this_si, rule, sub['attrs'],
                              {'X': (tuple(xa[0]), str(xa[1]))}))
                dsize = _size(xa[0])
                if dsize > 1:
                    ix = s.entry(mid, 'tile', dsize, None, str(xa[1]))
                    s.groups.setdefault(dsize, set())
                    draw_bind = ('ref', ix, 'tile', 0)
                else:
                    ix = s.entry(mid, 'scalar', 1, None, str(xa[1]))
                    draw_bind = ('ref', ix, 'scalar', 0)
        if g > 1:
            s.groups.setdefault(g, set())

        out_bind = {}
        for slot, names in sub['outputs'].items():
            binds = []
            for n in names:
                if n not in written:
                    binds.append(None)
                    continue
                v = written[n]
                ok = (n, cur.get(n, 0) + 1)
                cur[n] = ok[1]
                s.keys[ok] = ok
                s.key_aval[ok] = (tuple(v.shape), str(v.dtype))
                loc[ok] = ('sym', s)
                aval[ok] = s.key_aval[ok]
                binds.append(ok)
            out_bind[slot] = (names, binds)

        info = _OpInfo(1, in_avals_by_slot)
        s.ops.append((sub, rule, in_bind, out_bind, g, info, draw_bind))

    _flush(len(sub_ops))

    finals = []
    for n in out_names:
        where = loc[(n, cur.get(n, 0))]
        if where[0] != 'mat':
            raise KernelgenUnsupported(
                '?', 'internal: output %s not materialized' % n)
        finals.append(where[1])

    n_args = len(arg_names)
    interp = _interpret()

    def core(xs, keys):
        from ...core import executor as _ex
        mats = {}
        for ix in range(n_args):
            mats[ix] = xs[ix]
        for st in steps:
            kind = st[0]
            if kind == 'draw':
                _, mid, si, rule, sattrs, davals = st
                mats[mid] = rule.draw(keys[si], _AvalsView(davals),
                                      sattrs)
            elif kind == 'glue':
                _, mid, fn, ins_ = st
                mats[mid] = fn(*[mats[m] for m in ins_])
            elif kind == 'dstep':
                _, sub, rule, in_mids, out_bind, avals_d, tune = st
                ins_vals = {}
                for slot, mids_ in in_mids.items():
                    vals = [mats[m] for m in mids_]
                    ins_vals[slot] = vals \
                        if sub['input_is_list'].get(slot) else vals[0]
                if amp:
                    ins_vals = _ex._amp_sub_ins(sub['type'], ins_vals,
                                                amp)
                outs = rule.step(ins_vals, sub['attrs'],
                                 _AvalsView(avals_d), tune, interp) \
                    or {}
                if amp:
                    outs = _ex._amp_sub_outs(sub['type'], sub['attrs'],
                                             outs, amp)
                for slot, binds in out_bind.items():
                    if slot not in outs:
                        continue
                    vals = outs[slot]
                    vals = vals if isinstance(vals, (list, tuple)) \
                        else [vals]
                    for mid, v in zip(binds, vals):
                        if mid is not None and v is not None:
                            mats[mid] = v
            else:
                _run_kernel(st[1], mats)
        return [mats[m] for m in finals]

    def ref_replay(xs, keys):
        from .. import fused as _fused
        env = dict(zip(arg_names, list(xs)))
        si = 0
        for sub in sub_ops:
            if sub['type'] in rng_types:
                ctx = _OneKeyCtx(keys[si])
                si += 1
            else:
                ctx = _OneKeyCtx(None)
            _fused._run_sub_op(ctx, sub, env, amp)
        return [env[n] for n in out_names]

    fn = jax.custom_vjp(core)

    def _fwd(xs, keys):
        return core(xs, keys), (xs, keys)

    def _bwd(res, cts):
        from ...core.executor import _zero_cotangent
        xs, keys = res
        _, vjp = jax.vjp(lambda xs_: ref_replay(xs_, keys), xs)
        (gxs,) = vjp(list(cts))
        return gxs, tuple(_zero_cotangent(k) for k in keys)

    fn.defvjp(_fwd, _bwd)

    plan = _Plan()
    plan.fn = fn
    plan.n_rng = rng_si
    plan.n_kernels = stats['kernels']
    plan.n_glue = stats['glue']
    plan.kernel_ops = stats['kernel_ops']
    plan.n_donated = stats['donated']
    plan.groups = all_groups
    plan.n_dsteps = stats['dsteps']
    plan.tuned = tuned
    return plan


# ---------------------------------------------------- pallas emission
def _compile_segment(s, esc, amp, reads, final_keys, stats, base=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    blocks = _blocks_for(_block_base() if base is None else base,
                         s.groups)
    if blocks is None:
        raise KernelgenUnsupported(
            'broadcast', 'block lcm exceeds cap %d' % _BLOCK_CAP)
    tiles = {g: -(-g // b) for g, b in blocks.items()}
    grid = max(tiles.values()) if tiles else 1

    outs_meta = []             # (key, n, group-or-None, shape, dt)
    for k in esc:
        shape, dt = s.key_aval[k]
        n = _size(shape)
        outs_meta.append((k, n, n if n > 1 else None, shape, dt))

    def _tile_spec(size):
        t = tiles[size]
        return pl.BlockSpec((blocks[size],),
                            lambda i, t=t: (jnp.minimum(i, t - 1),))

    def _full_spec(size):
        return pl.BlockSpec((size,), lambda i: (0,))

    in_specs = []
    for (mid, kind, size) in s.entries:
        in_specs.append(_tile_spec(size) if kind == 'tile'
                        else _full_spec(size))
    out_specs, out_shape = [], []
    for (k, n, g, shape, dt) in outs_meta:
        out_specs.append(_tile_spec(g) if g is not None
                         else _full_spec(max(n, 1)))
        out_shape.append(jax.ShapeDtypeStruct((max(n, 1),), dt))

    # donation: rule-declared aliases; the donated input must be a plain
    # program value with no other reader anywhere, spec-identical to the
    # output, and (for pid-0-stored scalars) not re-read across steps
    aliases = {}
    esc_ix = {k: j for j, (k, _, _, _, _) in enumerate(outs_meta)}
    for (sub, rule, in_bind, out_bind, g, info, draw_bind) in s.ops:
        for oslot, islot in rule.aliases.items():
            names, binds = out_bind.get(oslot, ((), ()))
            if not binds or binds[0] is None or binds[0] not in esc_ix:
                continue
            iops = in_bind.get(islot)
            if not iops or iops[0][0] != 'ref':
                continue
            _, ix, kind, _D = iops[0]
            if kind not in ('tile', 'scalar') or ix in aliases:
                continue
            if kind == 'scalar' and grid > 1:
                continue       # written once at pid 0, read every step
            src = s.entry_key.get(ix)
            if src is None or reads.get(src, 0) != 1 \
                    or src in final_keys:
                continue
            oj = esc_ix[binds[0]]
            _k, on, _og, _shape, odt = outs_meta[oj]
            _mid, _kind, esize = s.entries[ix]
            if esize != max(on, 1) or s.entry_dt.get(ix) != odt:
                continue
            aliases[ix] = oj
            stats['donated'] += 1

    ops_meta = list(s.ops)
    n_in = len(s.entries)
    root_of = dict(s.keys)

    def body(*refs):
        from ...core.executor import _amp_match_ins
        from ...core.registry import get_op
        from .rules import NO_RNG_CTX
        pid = pl.program_id(0)
        loads = [r[...] for r in refs[:n_in]]
        symv = {}

        def val_of(od, g):
            if od[0] == 'sym':
                return symv[od[1]]
            _, ix, kind, D = od
            if kind == 'tile':
                return loads[ix]
            if kind == 'scalar':
                return loads[ix].reshape(())
            return jnp.tile(loads[ix], blocks[g] // D)

        for (sub, rule, in_bind, out_bind, g, info, draw_bind) \
                in ops_meta:
            ins_vals = {}
            for slot, ops_ in in_bind.items():
                vals = [val_of(od, g) for od in ops_]
                ins_vals[slot] = vals \
                    if sub['input_is_list'].get(slot) else vals[0]
            if amp:
                ins_vals = _amp_match_ins(sub['type'], ins_vals)
            info2 = _OpInfo(blocks[g] if g > 1 else 1, info._in)
            if rule.kind == 'rng':
                draw_val = val_of(draw_bind, g) \
                    if draw_bind is not None else None
                outs = rule.body(ins_vals, sub['attrs'], info2,
                                 draw_val)
            elif rule.body is not None:
                outs = rule.body(ins_vals, sub['attrs'], info2)
            else:
                outs = get_op(sub['type']).impl(
                    NO_RNG_CTX, ins_vals, sub['attrs']) or {}
            for slot, (names, binds) in out_bind.items():
                if slot not in outs:
                    continue
                vals = outs[slot]
                vals = vals if isinstance(vals, (list, tuple)) \
                    else [vals]
                for bk, v in zip(binds, vals):
                    if bk is not None and v is not None:
                        symv[bk] = v

        def _store(ref, v):
            ref[...] = v

        for j, (k, n, g, shape, dt) in enumerate(outs_meta):
            v = symv[root_of[k]]
            ref = refs[n_in + j]
            if g is not None:
                pl.when(pid < tiles[g])(
                    lambda ref=ref, v=v, b=blocks[g]:
                    _store(ref, v.reshape(b)))
            else:
                pl.when(pid == 0)(
                    lambda ref=ref, v=v, n=max(n, 1):
                    _store(ref, jnp.asarray(v).reshape(n)))

    call = pl.pallas_call(
        body,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=_interpret(),
    )
    return {
        'call': call,
        'entries': list(s.entries),
        'outs_meta': outs_meta,
        'out_mids': [],
        'grid': grid,
        'blocks': dict(blocks),
        'donated': dict(aliases),
    }


def _run_kernel(kspec, mats):
    import jax.numpy as jnp
    args = [jnp.reshape(mats[mid], (-1,))
            for (mid, kind, size) in kspec['entries']]
    outs = kspec['call'](*args)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    for (k, n, g, shape, dt), mid, o in zip(
            kspec['outs_meta'], kspec['out_mids'], outs):
        mats[mid] = jnp.reshape(o, shape)
