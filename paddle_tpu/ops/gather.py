"""Pallas DMA row-gather for embedding lookups.

XLA's TPU row gather runs far below HBM bandwidth: 8192 x 512 f32 rows
from a 32000 x 512 table measure 1.50 ms via `jnp.take` but 0.865 ms
(1.7x) as per-row async DMA copies (TPU v5 lite; all jnp formulations —
take, fancy-index, 2-D ids — measure the same, see PERF.md).  The
kernel: ids ride SMEM scalar prefetch; the table stays in HBM
([V, 1, D] so each row is a leading-dim slice — dynamic sublane slicing
of a (8,128)-tiled HBM memref does not lower); each grid step DMAs
`block` rows into its VMEM output block.

Only the FORWARD gather runs in pallas; the backward stays XLA's
scatter-add, which measured identical across every formulation
(pre-sorted, segment_sum — PERF.md) and is duplicate-index-correct.

Parity: reference lookup_table_op.cu row gather (the reference's
CUDA kernel solves the same your-compiler-won't-do-it problem).
"""
import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

_BLOCK = 256
# Measured gate (TPU v5 lite, end-to-end A/B): at 8192 rows the kernel
# is 1.7x in isolation and ~+0.7% end-to-end on the transformer bench;
# at 4096 rows it is 3% SLOWER end-to-end on word2vec — the serial
# per-row DMA-issue loop stops amortizing.  Engage only at large N.
_MIN_ROWS = 8192


def _gather_kernel(ids_ref, tbl_ref, out_ref, sem, *, block):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    i = pl.program_id(0)

    def issue(j, _):
        row = ids_ref[i * block + j]
        pltpu.make_async_copy(tbl_ref.at[row], out_ref.at[j], sem).start()
        return 0

    jax.lax.fori_loop(0, block, issue, 0)

    def wait(j, _):
        row = ids_ref[i * block + j]
        pltpu.make_async_copy(tbl_ref.at[row], out_ref.at[j], sem).wait()
        return 0

    jax.lax.fori_loop(0, block, wait, 0)


def _any_memory_space(pltpu):
    """The HBM/'leave it where it is' memory space moved between jax
    releases: ``pltpu.ANY`` (<=0.4.x, where MemorySpace doesn't exist)
    vs ``pltpu.MemorySpace.ANY`` (newer).  BENCH_r04 lost the kernel to
    exactly this kind of API drift surfacing as a runtime TypeError and
    silently rerouting to jnp.take — resolve it explicitly."""
    any_space = getattr(pltpu, 'ANY', None)
    if any_space is not None:
        return any_space
    return pltpu.MemorySpace.ANY


def _pallas_gather(tbl, ids, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    N = ids.shape[0]
    V, D = tbl.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(N // _BLOCK,),
        in_specs=[pl.BlockSpec(memory_space=_any_memory_space(pltpu))],
        out_specs=pl.BlockSpec((_BLOCK, 1, D), lambda i, ids: (i, 0, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )
    out = pl.pallas_call(
        functools.partial(_gather_kernel, block=_BLOCK),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, 1, D), tbl.dtype),
        interpret=interpret,
    )(ids, tbl.reshape(V, 1, D))
    return out.reshape(N, D)


def _eligible(w, idx_flat):
    # PT_PALLAS_GATHER=0 is the kill-switch: a Mosaic LOWERING failure
    # surfaces when the whole step compiles — after tracing, where the
    # try/except in embedding_gather can no longer reroute — so a
    # platform where this kernel won't compile needs the env gate, not
    # the runtime fallback.
    return (os.environ.get('PT_PALLAS_GATHER', '1') != '0' and
            idx_flat.shape[0] >= _MIN_ROWS and
            idx_flat.shape[0] % _BLOCK == 0 and
            w.shape[1] % 128 == 0 and
            w.dtype in (jnp.float32, jnp.bfloat16))


@functools.lru_cache(maxsize=None)
def _make_kernel_gather(V, D, dtype_name):
    """Per-(shape, dtype) custom_vjp gather.  The table shape/dtype are
    closed over as STATIC values so the vjp residuals hold only arrays —
    a dtype object in residuals is not a valid JAX type and would make
    tracing under jax.grad raise (and silently reroute every training
    step to the jnp.take fallback)."""
    w_dtype = jnp.dtype(dtype_name)

    @jax.custom_vjp
    def kernel_gather(w, idx_flat):
        interpret = jax.default_backend() != 'tpu'
        return _pallas_gather(w, idx_flat, interpret)

    def fwd(w, idx_flat):
        return kernel_gather(w, idx_flat), (idx_flat,)

    def bwd(res, g):
        (idx_flat,) = res
        dw = jnp.zeros((V, D), w_dtype).at[idx_flat].add(g.astype(w_dtype))
        return dw, np.zeros(idx_flat.shape, jax.dtypes.float0)

    kernel_gather.defvjp(fwd, bwd)
    return kernel_gather


def _kernel_gather(w, idx_flat):
    V, D = w.shape
    return _make_kernel_gather(V, D, jnp.dtype(w.dtype).name)(w, idx_flat)


def embedding_gather(w, idx):
    """rows of `w` at `idx` (any idx shape), via the DMA kernel when the
    shapes qualify; falls back to jnp.take otherwise (trace-time
    failures only — see _eligible for the compile-time kill-switch).
    Fallbacks are LOUD: counted as kernel.fallbacks, warned once, and
    fatal under PT_STRICT_KERNELS=1 (ops/_fallback.py)."""
    idx_flat = idx.reshape(-1).astype(jnp.int32)
    if _eligible(w, idx_flat):
        # match jnp.take's semantics exactly: negative ids wrap (numpy
        # style), truly out-of-range ids fill with NaN (so corruption
        # SURFACES via executor check_nan).  The raw DMA would read
        # unchecked HBM addresses for either.
        V = w.shape[0]
        wrapped = jnp.where(idx_flat < 0, idx_flat + V, idx_flat)
        oob = (wrapped < 0) | (wrapped >= V)
        safe = jnp.clip(wrapped, 0, V - 1)
        try:
            out = _kernel_gather(w, safe)
            out = jnp.where(oob[:, None], jnp.nan, out)
            return out.reshape(tuple(idx.shape) + (w.shape[1],))
        except Exception as e:  # pragma: no cover - backend-specific
            from ._fallback import kernel_fallback
            kernel_fallback('embedding_gather', e, detail='using jnp.take')
    return jnp.take(w, idx, axis=0)
