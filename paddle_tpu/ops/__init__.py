"""JAX implementations of all registered ops.

Replaces reference paddle/fluid/operators/ (~439 CUDA/CPU kernel files).
Each module registers pure-JAX impls with core.registry; gradients come from
jax.vjp (no *_grad kernels needed), fusion comes from XLA.
"""
from . import math  # noqa
from . import tensor  # noqa
from . import nn  # noqa
from . import loss  # noqa
from . import rand  # noqa
from . import optimizer_ops  # noqa
from . import metric  # noqa
from . import sequence  # noqa
from . import detection  # noqa
from . import attention  # noqa
from . import sampling  # noqa
from . import ctc_crf  # noqa
from . import int8  # noqa
from . import fused  # noqa  (fused_elementwise from core/passes/fuse.py)
from . import collective  # noqa  (explicit collectives from core/passes/shard.py)
from . import kernelgen  # noqa  (Pallas codegen tier + its emit rule)
