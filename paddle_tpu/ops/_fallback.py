"""Loud graceful degradation for optional pallas kernels.

Every kernel that can reroute to a composed/jnp implementation at trace
time funnels the decision through `kernel_fallback`, so degradation is
never silent again (BENCH_r04 ran a whole TPU round on the jnp.take
gather without anyone noticing):

  * counts ``kernel.fallbacks`` and ``kernel.fallbacks.<kernel>`` in the
    observability registry — bench.py surfaces the total in its
    telemetry JSON block;
  * warns once per kernel with the underlying error;
  * under ``PT_STRICT_KERNELS=1`` RAISES instead of falling back — CI
    and kernel-development runs fail fast on the exact backend error.
"""
import os
import warnings

from .. import observability as _obs

__all__ = ['kernel_fallback', 'strict_kernels']

_warned = set()


def strict_kernels():
    return os.environ.get('PT_STRICT_KERNELS', '0') in ('1', 'true', 'True')


def kernel_fallback(kernel, exc, detail=''):
    """Record that `kernel` failed with `exc` and is about to degrade.
    Raises under PT_STRICT_KERNELS=1; otherwise counts + warns once and
    returns so the caller can take its fallback path."""
    _obs.metrics.counter('kernel.fallbacks').inc()
    _obs.metrics.counter('kernel.fallbacks.%s' % kernel).inc()
    _obs.tracing.instant('kernel.fallback', cat='kernel',
                         args={'kernel': kernel, 'error': repr(exc)[:200]})
    if strict_kernels():
        raise RuntimeError(
            'PT_STRICT_KERNELS=1: %s kernel failed (%r)%s'
            % (kernel, exc, detail and ' — ' + detail)) from exc
    if kernel not in _warned:
        _warned.add(kernel)
        warnings.warn('%s kernel failed (%r); falling back%s'
                      % (kernel, exc, detail and ' — ' + detail))
