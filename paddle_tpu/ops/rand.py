"""Random + initializer ops.

Parity: reference uniform_random_op, gaussian_random_op,
truncated_gaussian_random_op, fill ops used by initializers, sampling_id_op,
random_crop_op.  All use JAX threefry keys derived from the run seed.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core.dtypes import jax_dtype


def _key(ctx, attrs):
    seed = attrs.get('seed', 0)
    return jax.random.key(seed) if seed else ctx.rng()


@register('uniform_random')
def uniform_random(ctx, ins, attrs):
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    shape = [int(d) for d in attrs['shape']]
    out = jax.random.uniform(_key(ctx, attrs), shape,
                             minval=attrs.get('min', -1.0),
                             maxval=attrs.get('max', 1.0))
    return {'Out': out.astype(dtype)}


@register('gaussian_random')
def gaussian_random(ctx, ins, attrs):
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    shape = [int(d) for d in attrs['shape']]
    out = attrs.get('mean', 0.0) + attrs.get('std', 1.0) * \
        jax.random.normal(_key(ctx, attrs), shape)
    return {'Out': out.astype(dtype)}


@register('truncated_gaussian_random')
def truncated_gaussian_random(ctx, ins, attrs):
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    shape = [int(d) for d in attrs['shape']]
    out = attrs.get('mean', 0.0) + attrs.get('std', 1.0) * \
        jax.random.truncated_normal(_key(ctx, attrs), -2.0, 2.0, shape)
    return {'Out': out.astype(dtype)}


@register('sampling_id')
def sampling_id(ctx, ins, attrs):
    x = ins['X']  # [B, C] probabilities
    key = _key(ctx, attrs)
    ids = jax.random.categorical(key, jnp.log(x + 1e-20), axis=-1)
    return {'Out': ids.astype(jax_dtype('int64'))}


@register('random_crop')
def random_crop(ctx, ins, attrs):
    x = ins['X']
    shape = attrs['shape']  # crop shape for trailing dims
    key = _key(ctx, attrs)
    nlead = x.ndim - len(shape)
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[nlead + i] - s
        k = jax.random.fold_in(key, i)
        starts.append(jax.random.randint(k, (), 0, max(limit, 0) + 1))
    out = jax.lax.dynamic_slice(
        x, [0] * nlead + [s for s in starts],
        list(x.shape[:nlead]) + list(shape))
    return {'Out': out}


@register('crop')
def crop(ctx, ins, attrs):
    x = ins['X']
    shape = attrs.get('shape')
    if 'Y' in ins:
        shape = ins['Y'].shape
    offsets = attrs.get('offsets', [0] * x.ndim)
    return {'Out': jax.lax.dynamic_slice(x, offsets, shape)}
