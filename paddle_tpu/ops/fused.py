"""fused_elementwise: replay a serialized elementwise sub-program as ONE
registered op (built by core/passes/fuse.py).

The op's attrs carry the fused run:
  sub_ops    [{type, inputs, outputs, input_is_list, output_is_list,
               attrs, stop_grad}]  — original ops, original order
  arg_names  ordered external input names (bound from the 'X' slot)
  out_names  ordered escaping output names (returned in the 'Out' slot)

Replaying through each sub-op's own registered kernel, in order, emits
the IDENTICAL jaxpr the unfused executor loop would have — bitwise
parity is by construction.  The three pieces of executor-loop policy
that apply per op are replicated here: the full per-op AMP cast policy
(core/executor._amp_sub_ins/_amp_sub_outs — the _AMP_OPS bf16 in-cast,
elementwise-match glue, and _AMP_CAST_OPS f32 cast-back, so a fused
flash_attention sees exactly the unfused dtypes), per-output
stop_gradient, and RNG streams (ctx.sub_ctx derives each sub-op's
stream from its pinned ``rng_stream`` attr).
"""
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, get_op


def _run_sub_op(ctx, sub, env, amp):
    impl = get_op(sub['type']).impl
    ins = {}
    for slot, names in sub['inputs'].items():
        vals = [env[n] for n in names]
        ins[slot] = vals if sub['input_is_list'].get(slot) else vals[0]
    if amp:
        from ..core.executor import _amp_sub_ins
        ins = _amp_sub_ins(sub['type'], ins, amp)
    sctx = ctx.sub_ctx(sub) if hasattr(ctx, 'sub_ctx') else ctx
    outs = impl(sctx, ins, sub['attrs']) or {}
    if amp:
        from ..core.executor import _amp_sub_outs
        outs = _amp_sub_outs(sub['type'], sub['attrs'], outs, amp)
    stop = set(sub.get('stop_grad') or ())
    for slot, names in sub['outputs'].items():
        if slot not in outs:
            continue
        vals = outs[slot]
        vals = vals if isinstance(vals, (list, tuple)) else [vals]
        for name, val in zip(names, vals):
            if val is None:
                continue
            if name in stop and hasattr(val, 'dtype') and \
                    jnp.issubdtype(val.dtype, jnp.floating):
                val = lax.stop_gradient(val)
            env[name] = val


@register('fused_elementwise')
def fused_elementwise(ctx, ins, attrs):
    from . import kernelgen as _kg
    fx = getattr(ctx, 'forensic', None)
    if _kg.enabled() and fx is None:
        # a forensic lowering never hands the group to kernelgen: the
        # whole point is probing INSIDE the fused sub-program, which a
        # single generated kernel hides.  Production launches keep the
        # kernel tier — only the replay runner pays the granularity tax.
        try:
            return _kg.run_fused(ctx, ins, attrs)
        except Exception as e:        # noqa: BLE001 — loud by contract
            _kg.note_fallback(e)      # raises under PT_STRICT_KERNELS
    xs = ins.get('X', [])
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    env = dict(zip(attrs['arg_names'], xs))
    amp = bool(getattr(ctx, 'amp', False))
    pos = getattr(ctx, 'op_index', 0)
    loc = getattr(getattr(ctx, 'op', None), 'source_loc', None)
    for sub in attrs['sub_ops']:
        _run_sub_op(ctx, sub, env, amp)
        if fx is not None:
            # sub-program granularity: each replayed sub-op's outputs
            # get their own probe, named against the FUSED op's position
            # (the probe writes into fx.env — the executor's outer env —
            # so it escapes this impl's local sub-environment)
            sloc = sub['attrs'].get('source_loc') or loc
            for names in sub['outputs'].values():
                for nm in names:
                    if nm in env:
                        fx.note(pos, 'fused:%s' % sub['type'], nm, sloc,
                                env[nm])
    return {'Out': [env[n] for n in attrs['out_names']]}
