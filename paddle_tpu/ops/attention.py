"""Fused attention kernels (pallas) + the `flash_attention` op.

TPU-native replacement for the reference's unfused softmax(QK^T)V op chain
(there is no fused attention in the reference — this is where we beat it).
Online-softmax flash attention: one pass over K/V blocks with running
max/sum, O(T) memory instead of the T×T score matrix.  Padding is handled
with a per-row valid-K-length vector (pad is always a suffix in the padded
batch layout), causal masking with block-level position comparison.

Falls back to the composed jnp implementation when pallas is unavailable
(CPU test backend runs the kernel in interpret mode).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register

_NEG_INF = -1e30


def _ref_attention(q, k, v, causal, scale, k_len=None):
    """q: [B, H, Tq, D]; k/v: [B, Hkv, Tk, D] with H % Hkv == 0 (GQA —
    each kv head serves H/Hkv query heads without materializing copies)."""
    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    g = H // Hkv
    qg = q.reshape(B, Hkv, g, Tq, D)
    scores = jnp.einsum('bhgqd,bhkd->bhgqk', qg, k) * scale
    if causal:
        mask = np.tril(np.ones((Tq, Tk), np.bool_), k=Tk - Tq)
        scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
    if k_len is not None:
        kmask = jnp.arange(Tk)[None, :] < k_len[:, None]   # [B, Tk]
        scores = jnp.where(kmask[:, None, None, None, :], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('bhgqk,bhkd->bhgqd', w, v).reshape(B, H, Tq, D)


def _flash_kernel(klen_ref, q_ref, k_ref, v_ref, o_ref, *, block_k, causal,
                  scale, q_block, seq_len, causal_offset=0):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale            # [block_q, d]
    block_q = q.shape[0]
    d = q.shape[-1]
    klen = klen_ref[b]                                  # SMEM scalar prefetch
    m = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)
    # skip K blocks that are entirely invalid: past the padded length, and
    # (causal) past the last query row of this block
    num_k = jax.lax.div(klen + block_k - 1, block_k)
    if causal:
        q_end = causal_offset + (qi + 1) * q_block
        num_k = jnp.minimum(num_k,
                            jax.lax.div(q_end + block_k - 1, block_k))
    num_k = jnp.minimum(num_k, seq_len // block_k)

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        s = q @ k.T                                      # [bq, bk]
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < klen
        if causal:
            # end-aligned (matches _ref_attention's tril(k=Tk-Tq)): the last
            # query sees all keys when Tq < Tk (cached decode)
            q_pos = causal_offset + qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=1)
        acc_new = acc * alpha[:, None] + p @ v
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, causal=False, scale=None, k_len=None,
                    block_q=128, block_k=128, interpret=None):
    """q: [B, H, T, D]; k/v: [B, Hkv, T, D] (Hkv may divide H — GQA/MQA,
    served without repeating K/V); k_len: optional int32 [B] valid lengths.

    Differentiable: forward runs the pallas kernel; the VJP currently uses
    the composed formulation's gradient (a pallas backward kernel is the
    next perf step)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5

    @jax.custom_vjp
    def _attn(q, k, v, kl):
        return _flash_forward(q, k, v, kl, causal, scale, block_q, block_k,
                              interpret)

    def _fwd(q, k, v, kl):
        return _attn(q, k, v, kl), (q, k, v, kl)

    def _bwd(res, g):
        q, k, v, kl = res
        _, pullback = jax.vjp(
            lambda q, k, v: _ref_attention(q, k, v, causal, scale, kl),
            q, k, v)
        dq, dk, dv = pullback(g)
        return dq, dk, dv, None

    _attn.defvjp(_fwd, _bwd)
    if k_len is None:
        k_len = jnp.full((q.shape[0],), k.shape[2], jnp.int32)
    return _attn(q, k, v, k_len.astype(jnp.int32))


def _flash_forward(q, k, v, k_len, causal, scale, block_q=128, block_k=128,
                   interpret=None):
    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    g = H // Hkv
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if Tq % block_q or Tk % block_k or D % 8:
        return _ref_attention(q, k, v, causal, scale, k_len)
    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        qr = q.reshape(B * H, Tq, D)
        kr = k.reshape(B * Hkv, Tk, D)
        vr = v.reshape(B * Hkv, Tk, D)
        klr = jnp.repeat(k_len.astype(jnp.int32), H)     # [B*H]
        kernel = functools.partial(
            _flash_kernel, block_k=block_k, causal=causal, scale=scale,
            q_block=block_q, seq_len=Tk, causal_offset=Tk - Tq)

        def kv_row(b, i, kl):
            # GQA: query row b = bi*H + h reads kv row bi*Hkv + h//g, so
            # K/V stay at Hkv width in HBM — no materialized head copies
            return (b // H) * Hkv + (b % H) // g, 0, 0

        # k-lengths ride SMEM scalar prefetch (a (1,1) VMEM block would
        # violate the TPU (8,128) tiling minimum and refuse to lower)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * H, Tq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, kl: (b, i, 0)),
                pl.BlockSpec((1, Tk, D), kv_row),
                pl.BlockSpec((1, Tk, D), kv_row),
            ],
            out_specs=pl.BlockSpec((1, block_q, D),
                                   lambda b, i, kl: (b, i, 0)),
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            interpret=interpret,
        )(klr, qr, kr, vr)
        return out.reshape(B, H, Tq, D)
    except Exception as e:  # pragma: no cover - depends on backend
        global _warned_fallback
        if not _warned_fallback:
            import warnings
            warnings.warn('flash_attention pallas kernel failed (%r); '
                          'falling back to the composed implementation '
                          '(unfused, O(T^2) memory)' % (e,))
            _warned_fallback = True
        return _ref_attention(q, k, v, causal, scale, k_len)


_warned_fallback = False


@register('flash_attention')
def flash_attention_op(ctx, ins, attrs):
    q, k, v = ins['Q'], ins['K'], ins['V']
    k_len = ins.get('KLength')
    if k_len is not None and k_len.ndim > 1:
        k_len = k_len.reshape(-1)
    return {'Out': flash_attention(
        q, k, v, causal=attrs.get('causal', False),
        scale=attrs.get('scale', None), k_len=k_len)}


@register('ring_attention')
def ring_attention_op(ctx, ins, attrs):
    """Sequence-parallel exact attention (long-context path).

    When the executor runs with a mesh whose 'seq' axis is >1, the op runs
    the ppermute ring from parallel/ring_attention.py — each device holds
    T/n_seq of K/V, so context length scales with the ring size.  On a
    single chip (or no seq axis) it lowers to flash attention: the SAME
    program serves both, chosen at lowering time from ctx.mesh."""
    q, k, v = ins['Q'], ins['K'], ins['V']
    causal = attrs.get('causal', False)
    scale = attrs.get('scale', None)
    mesh = getattr(ctx, 'mesh', None)
    axis = attrs.get('axis_name', 'seq')
    if mesh is not None and axis in mesh.axis_names and \
            mesh.shape[axis] > 1 and q.shape[2] % mesh.shape[axis] == 0:
        from ..parallel.ring_attention import ring_attention
        return {'Out': ring_attention(q, k, v, mesh, axis_name=axis,
                                      causal=causal, scale=scale)}
    return {'Out': flash_attention(q, k, v, causal=causal, scale=scale)}
