"""Fused attention kernels (pallas) + the `flash_attention` op.

TPU-native replacement for the reference's unfused softmax(QK^T)V op chain
(there is no fused attention in the reference — this is where we beat it).
Online-softmax flash attention: one pass over K/V blocks with running
max/sum, O(T) memory instead of the T×T score matrix.  Padding is handled
with a per-row valid-K-length vector (pad is always a suffix in the padded
batch layout), causal masking with block-level position comparison.

Falls back to the composed jnp implementation when pallas is unavailable
(CPU test backend runs the kernel in interpret mode).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register

_NEG_INF = -1e30
_LSE_LANES = 8   # trailing broadcast dim that makes (1, bq) rows tileable


def _ref_attention(q, k, v, causal, scale, k_len=None):
    """q: [B, H, Tq, D]; k/v: [B, Hkv, Tk, D] with H % Hkv == 0 (GQA —
    each kv head serves H/Hkv query heads without materializing copies).

    Matches the pallas kernel's precision contract under AMP: the
    einsums run in the input dtype on the MXU but accumulate/emit f32
    (preferred_element_type), so masking and softmax statistics are
    always f32 even for bf16 activations; the output returns in the
    input dtype.  For f32 inputs every step is the plain f32 path."""
    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    g = H // Hkv
    qg = q.reshape(B, Hkv, g, Tq, D)
    scores = jnp.einsum('bhgqd,bhkd->bhgqk', qg, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = np.tril(np.ones((Tq, Tk), np.bool_), k=Tk - Tq)
        scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
    if k_len is not None:
        kmask = jnp.arange(Tk)[None, :] < k_len[:, None]   # [B, Tk]
        scores = jnp.where(kmask[:, None, None, None, :], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)                    # f32
    out = jnp.einsum('bhgqk,bhkd->bhgqd', w.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, H, Tq, D).astype(q.dtype)


def _flash_kernel(klen_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k,
                  causal, scale, q_block, seq_len, causal_offset=0):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale            # [block_q, d]
    block_q = q.shape[0]
    d = q.shape[-1]
    klen = klen_ref[b]                                  # SMEM scalar prefetch
    m = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)
    # skip K blocks that are entirely invalid: past the padded length, and
    # (causal) past the last query row of this block
    num_k = jax.lax.div(klen + block_k - 1, block_k)
    if causal:
        q_end = causal_offset + (qi + 1) * q_block
        num_k = jnp.minimum(num_k,
                            jax.lax.div(q_end + block_k - 1, block_k))
    num_k = jnp.minimum(num_k, seq_len // block_k)

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        s = q @ k.T                                      # [bq, bk]
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < klen
        if causal:
            # end-aligned (matches _ref_attention's tril(k=Tk-Tq)): the last
            # query sees all keys when Tq < Tk (cached decode)
            q_pos = causal_offset + qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=1)
        acc_new = acc * alpha[:, None] + p @ v
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # logsumexp of the (masked, scaled) score rows — the softmax statistic
    # the backward kernels need to rebuild P = exp(S - LSE) blockwise.
    # Stored broadcast along an 8-lane trailing dim: TPU refuses (1, bq)
    # blocks (sublane 1), and 8 lanes is the cheapest legal layout.
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG_INF)
    lse_ref[0] = jnp.broadcast_to(lse[:, None], (block_q, _LSE_LANES))


def _masked_p_ds(q, do, k, v, lse, delta, k_base, q_base, klen, causal):
    """Rebuild the softmax block P = exp(S - LSE) under padding/causal
    masking, plus dS = P * (dO V^T - delta) — the math shared by all
    three backward kernels.  exp(-inf - -inf) is NaN for fully-masked
    rows, hence the explicit where."""
    block_q, block_k = q.shape[0], k.shape[0]
    s = q @ k.T                                          # [bq, bk]
    k_pos = k_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = k_pos < klen
    if causal:
        q_pos = q_base + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = valid & (q_pos >= k_pos)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    ds = p * (do @ v.T - delta)
    return p, ds


def _flash_dq_kernel(klen_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, *, block_k, causal, scale, q_block,
                     seq_len, causal_offset=0):
    """dQ = scale * sum_k [P * (dO V^T - delta)] K, one q block per step."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale            # [bq, d]
    do = do_ref[0].astype(jnp.float32)                  # [bq, d]
    lse = lse_ref[0][:, :1]                             # [bq, 1]
    delta = delta_ref[0][:, :1]                         # [bq, 1]
    block_q, d = q.shape
    klen = klen_ref[b]
    num_k = jax.lax.div(klen + block_k - 1, block_k)
    if causal:
        q_end = causal_offset + (qi + 1) * q_block
        num_k = jnp.minimum(num_k,
                            jax.lax.div(q_end + block_k - 1, block_k))
    num_k = jnp.minimum(num_k, seq_len // block_k)

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        _, ds = _masked_p_ds(q, do, k, v, lse, delta, ki * block_k,
                             causal_offset + qi * q_block, klen, causal)
        return dq + ds @ k

    dq = jax.lax.fori_loop(0, num_k, body, jnp.zeros((block_q, d),
                                                     jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


# Up to this many query rows the dK/dV kernel keeps the whole q/do/lse/
# delta rows VMEM-resident and accumulates in registers (faster: no
# output read-modify-write per q block — llama T=4096 measured 36.3k vs
# 30.2k tok/s).  Above it, the full-row block specs overflow the 16 MB
# scoped-vmem limit (hard compile OOM in the T=8192 llama train step),
# so the streamed variant grids over q blocks instead.
_DKV_RESIDENT_MAX_T = 4096


def _flash_dkv_kernel_resident(klen_ref, q_ref, k_ref, v_ref, do_ref,
                               lse_ref, delta_ref, dk_ref, dv_ref, *,
                               block_q, causal, scale, q_len,
                               causal_offset=0):
    """dK/dV for one k block, looping over VMEM-resident q blocks; the
    GQA group axis is the innermost grid dim, accumulating into the
    kv-head-resident output block (init at gi==0, add after)."""
    from jax.experimental import pallas as pl

    bkv = pl.program_id(0)
    ki = pl.program_id(1)
    gi = pl.program_id(2)
    k = k_ref[0].astype(jnp.float32)                    # [bk, d]
    v = v_ref[0].astype(jnp.float32)
    block_k, d = k.shape
    klen = klen_ref[bkv]
    num_q = q_len // block_q
    if causal:
        # first q block whose last row can see this k block's first key
        q_start = jnp.maximum(
            0, jax.lax.div(ki * block_k - causal_offset, block_q))
    else:
        q_start = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q)].astype(
            jnp.float32) * scale                        # [bq, d]
        do = do_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q)][:, :1]   # [bq, 1]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q)][:, :1]
        p, ds = _masked_p_ds(q, do, k, v, lse, delta, ki * block_k,
                             causal_offset + qi * block_q, klen, causal)
        dv = dv + p.T @ do
        dk = dk + ds.T @ q                               # q pre-scaled
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        q_start, num_q, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))

    @pl.when(gi == 0)
    def _init():
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv.astype(dv_ref.dtype)

    @pl.when(gi > 0)
    def _accum():
        dk_ref[0] += dk.astype(dk_ref.dtype)
        dv_ref[0] += dv.astype(dv_ref.dtype)


def _flash_dkv_kernel(klen_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dk_ref, dv_ref, *, block_q, causal, scale,
                      causal_offset=0):
    """dK/dV for one k block.  The GQA group axis AND the q-block axis are
    the two innermost (sequential) grid dims, accumulating into the
    kv-head-resident output block — q/do/lse/delta stream through VMEM in
    (1, block_q, d) tiles, so VMEM stays O(block) at any sequence length
    (a full-Tq block spec overflowed the 16 MB scoped-vmem limit at
    T=8192, measured on TPU v5 lite)."""
    from jax.experimental import pallas as pl

    bkv = pl.program_id(0)
    ki = pl.program_id(1)
    gi = pl.program_id(2)
    qi = pl.program_id(3)
    k = k_ref[0].astype(jnp.float32)                    # [bk, d]
    v = v_ref[0].astype(jnp.float32)
    block_k, d = k.shape
    klen = klen_ref[bkv]

    @pl.when((gi == 0) & (qi == 0))
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    # whole-block skip: k block entirely past the valid length, or
    # (causal) entirely above this q block's last row
    needed = ki * block_k < klen
    if causal:
        needed &= causal_offset + (qi + 1) * block_q - 1 >= ki * block_k

    @pl.when(needed)
    def _accum():
        q = q_ref[0].astype(jnp.float32) * scale        # [bq, d]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]                         # [bq, 1]
        delta = delta_ref[0][:, :1]
        p, ds = _masked_p_ds(q, do, k, v, lse, delta, ki * block_k,
                             causal_offset + qi * block_q, klen, causal)
        dv_ref[0] += (p.T @ do).astype(dv_ref.dtype)
        dk_ref[0] += (ds.T @ q).astype(dk_ref.dtype)     # q pre-scaled


# Above this many bytes of would-be score matrix (B*H*Tq*Tk*2, bf16), the
# backward runs the blockwise pallas kernels; below it, the composed
# einsum backward.  Measured END-TO-END (fwd+grad, causal, B=2 H=8 D=64
# bf16, TPU v5 lite): composed 5.7 ms vs pallas 9.0 ms at T=2048
# (134 MB scores), pallas 16.1 vs 18.7 at T=4096 (537 MB), pallas 44 ms
# vs composed 486 ms at T=8192 (2.1 GB — XLA starts thrashing HBM long
# before the hard capacity wall).  Crossover ~T=4096, so the gate sits
# at 256 MiB of bf16 scores; the pallas kernels own the long-context
# regime, XLA's fused batched matmuls own the short one.
_BWD_PALLAS_SCORE_BYTES = 256 << 20

# Below this key length the FORWARD also routes to the composed einsum
# path: measured end-to-end on TPU v5 lite transformer-base training
# (B*T = 8k tokens), composed reaches 211.8k tok/s at T=256 vs 182.1k
# through the pallas forward (+16%) — XLA's fused batched matmuls win
# while the T^2 scores are small — with the crossover at T=512 (146.2k
# flash vs 145.6k composed).  `flash_attention` is fused-attention
# SEMANTICS; the op picks the fastest lowering per shape.
_FWD_PALLAS_MIN_T = 512


def flash_attention(q, k, v, causal=False, scale=None, k_len=None,
                    block_q=128, block_k=128, interpret=None):
    """q: [B, H, T, D]; k/v: [B, Hkv, T, D] (Hkv may divide H — GQA/MQA,
    served without repeating K/V); k_len: optional int32 [B] valid lengths.

    Differentiable end to end in pallas: the forward kernel saves the
    per-row logsumexp, and the VJP runs two flash backward kernels (dQ over
    q blocks; dK/dV over k blocks with GQA group accumulation) — O(T)
    memory in both directions, no T×T score matrix ever materializes.
    For sequence lengths whose score matrix comfortably fits in HBM the
    VJP instead uses the composed einsum gradient, which is faster there
    (see _BWD_PALLAS_SCORE_BYTES)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if k_len is None:
        k_len = jnp.full((q.shape[0],), Tk, jnp.int32)
    k_len = k_len.astype(jnp.int32)
    bq, bk = min(block_q, Tq), min(block_k, Tk)
    if Tq % bq or Tk % bk or D % 8 or Tk < _FWD_PALLAS_MIN_T:
        # shapes the kernel can't tile, or short-context sizes where the
        # composed path measures faster — composed (jax AD backward)
        return _ref_attention(q, k, v, causal, scale, k_len)
    pallas_bwd = B * H * Tq * Tk * 2 > _BWD_PALLAS_SCORE_BYTES

    @jax.custom_vjp
    def _attn(q, k, v, kl):
        out, _ = _flash_forward(q, k, v, kl, causal, scale, bq, bk,
                                interpret)
        return out

    def _fwd(q, k, v, kl):
        out, lse = _flash_forward(q, k, v, kl, causal, scale, bq, bk,
                                  interpret)
        return out, (q, k, v, kl, out, lse)

    def _bwd(res, g):
        q, k, v, kl, out, lse = res
        if pallas_bwd:
            try:
                return _flash_backward(q, k, v, kl, out, lse, g, causal,
                                       scale, bq, bk, interpret) + (None,)
            except Exception as e:  # pragma: no cover - backend-specific
                from ._fallback import kernel_fallback
                kernel_fallback(
                    'flash_attention_bwd', e,
                    detail='composed gradient materializes the T^2 scores')
        _, pullback = jax.vjp(
            lambda q, k, v: _ref_attention(q, k, v, causal, scale, kl),
            q, k, v)
        dq, dk, dv = pullback(g)
        return dq, dk, dv, None

    _attn.defvjp(_fwd, _bwd)
    try:
        return _attn(q, k, v, k_len)
    except Exception as e:  # pragma: no cover - depends on backend
        from ._fallback import kernel_fallback
        kernel_fallback('flash_attention', e,
                        detail='composed implementation, O(T^2) memory')
        return _ref_attention(q, k, v, causal, scale, k_len)


def _kv_row_map(H, Hkv, g):
    def kv_row(b, i, kl):
        # GQA: query row b = bi*H + h reads kv row bi*Hkv + h//g, so
        # K/V stay at Hkv width in HBM — no materialized head copies
        return (b // H) * Hkv + (b % H) // g, 0, 0
    return kv_row


def _flash_forward(q, k, v, k_len, causal, scale, block_q, block_k,
                   interpret=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    g = H // Hkv
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * Hkv, Tk, D)
    vr = v.reshape(B * Hkv, Tk, D)
    klr = jnp.repeat(k_len, H)                           # [B*H]
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale,
        q_block=block_q, seq_len=Tk, causal_offset=Tk - Tq)
    kv_row = _kv_row_map(H, Hkv, g)

    # k-lengths ride SMEM scalar prefetch (a (1,1) VMEM block would
    # violate the TPU (8,128) tiling minimum and refuse to lower)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, kl: (b, i, 0)),
            pl.BlockSpec((1, Tk, D), kv_row),
            pl.BlockSpec((1, Tk, D), kv_row),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, kl: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES),
                         lambda b, i, kl: (b, i, 0)),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
                   jax.ShapeDtypeStruct((B * H, Tq, _LSE_LANES),
                                        jnp.float32)],
        interpret=interpret,
    )(klr, qr, kr, vr)
    return out.reshape(B, H, Tq, D), lse


def _flash_backward(q, k, v, k_len, out, lse, g_out, causal, scale,
                    block_q, block_k, interpret=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    B, H, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    g = H // Hkv
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * Hkv, Tk, D)
    vr = v.reshape(B * Hkv, Tk, D)
    dor = g_out.reshape(B * H, Tq, D)
    # delta_i = <dO_i, O_i> — the softmax-jacobian rank-1 correction term;
    # a fused elementwise reduce, no kernel needed.  Broadcast to the same
    # 8-lane layout the kernels read lse in.
    delta = jnp.sum(dor.astype(jnp.float32) *
                    out.reshape(B * H, Tq, D).astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (B * H, Tq, _LSE_LANES))
    kv_row = _kv_row_map(H, Hkv, g)
    causal_offset = Tk - Tq

    dq_kernel = functools.partial(
        _flash_dq_kernel, block_k=block_k, causal=causal, scale=scale,
        q_block=block_q, seq_len=Tk, causal_offset=causal_offset)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, kl: (b, i, 0)),
            pl.BlockSpec((1, Tk, D), kv_row),
            pl.BlockSpec((1, Tk, D), kv_row),
            pl.BlockSpec((1, block_q, D), lambda b, i, kl: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES),
                         lambda b, i, kl: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES),
                         lambda b, i, kl: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, kl: (b, i, 0)),
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        interpret=interpret,
    )(jnp.repeat(k_len, H), qr, kr, vr, dor, lse, delta)

    # dK/dV: grid over kv rows × k blocks with the GQA group innermost.
    # Short Tq: whole q rows stay VMEM-resident, register accumulation
    # (faster).  Long Tq: q blocks join the grid as a 4th sequential dim
    # and stream through VMEM in (1, block_q, D) tiles (O(block) VMEM at
    # any Tq).  See _DKV_RESIDENT_MAX_T.
    if Tq <= _DKV_RESIDENT_MAX_T:
        def q_row(b, ki, gi, kl):
            return b // Hkv * H + (b % Hkv) * g + gi, 0, 0

        dkv_kernel = functools.partial(
            _flash_dkv_kernel_resident, block_q=block_q, causal=causal,
            scale=scale, q_len=Tq, causal_offset=causal_offset)
        dkv_grid = (B * Hkv, Tk // block_k, g)
        dkv_in_specs = [
            pl.BlockSpec((1, Tq, D), q_row),
            pl.BlockSpec((1, block_k, D), lambda b, ki, gi, kl: (b, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, ki, gi, kl: (b, ki, 0)),
            pl.BlockSpec((1, Tq, D), q_row),
            pl.BlockSpec((1, Tq, _LSE_LANES), q_row),
            pl.BlockSpec((1, Tq, _LSE_LANES), q_row),
        ]
        dkv_out_specs = [
            pl.BlockSpec((1, block_k, D), lambda b, ki, gi, kl: (b, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, ki, gi, kl: (b, ki, 0)),
        ]
    else:
        def q_blk(b, ki, gi, qi, kl):
            return b // Hkv * H + (b % Hkv) * g + gi, qi, 0

        dkv_kernel = functools.partial(
            _flash_dkv_kernel, block_q=block_q, causal=causal, scale=scale,
            causal_offset=causal_offset)
        dkv_grid = (B * Hkv, Tk // block_k, g, Tq // block_q)
        dkv_in_specs = [
            pl.BlockSpec((1, block_q, D), q_blk),
            pl.BlockSpec((1, block_k, D),
                         lambda b, ki, gi, qi, kl: (b, ki, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, ki, gi, qi, kl: (b, ki, 0)),
            pl.BlockSpec((1, block_q, D), q_blk),
            pl.BlockSpec((1, block_q, _LSE_LANES), q_blk),
            pl.BlockSpec((1, block_q, _LSE_LANES), q_blk),
        ]
        dkv_out_specs = [
            pl.BlockSpec((1, block_k, D),
                         lambda b, ki, gi, qi, kl: (b, ki, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, ki, gi, qi, kl: (b, ki, 0)),
        ]
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=dkv_grid,
        in_specs=dkv_in_specs,
        out_specs=dkv_out_specs,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=dkv_spec,
        out_shape=[jax.ShapeDtypeStruct((B * Hkv, Tk, D), jnp.float32),
                   jax.ShapeDtypeStruct((B * Hkv, Tk, D), jnp.float32)],
        interpret=interpret,
    )(jnp.repeat(k_len, Hkv), qr, kr, vr, dor, lse, delta)
    return (dq.reshape(B, H, Tq, D),
            dk.reshape(B, Hkv, Tk, D).astype(k.dtype),
            dv.reshape(B, Hkv, Tk, D).astype(v.dtype))




@register('flash_attention')
def flash_attention_op(ctx, ins, attrs):
    q, k, v = ins['Q'], ins['K'], ins['V']
    k_len = ins.get('KLength')
    if k_len is not None and k_len.ndim > 1:
        k_len = k_len.reshape(-1)
    return {'Out': flash_attention(
        q, k, v, causal=attrs.get('causal', False),
        scale=attrs.get('scale', None), k_len=k_len)}


@register('ring_attention')
def ring_attention_op(ctx, ins, attrs):
    """Sequence-parallel exact attention (long-context path).

    When the executor runs with a mesh whose 'seq' axis is >1, the op runs
    the ppermute ring from parallel/ring_attention.py — each device holds
    T/n_seq of K/V, so context length scales with the ring size.  On a
    single chip (or no seq axis) it lowers to flash attention: the SAME
    program serves both, chosen at lowering time from ctx.mesh."""
    q, k, v = ins['Q'], ins['K'], ins['V']
    causal = attrs.get('causal', False)
    scale = attrs.get('scale', None)
    mesh = getattr(ctx, 'mesh', None)
    axis = attrs.get('axis_name', 'seq')
    if mesh is not None and axis in mesh.axis_names and \
            mesh.shape[axis] > 1 and q.shape[2] % mesh.shape[axis] == 0:
        from ..parallel.ring_attention import ring_attention
        return {'Out': ring_attention(q, k, v, mesh, axis_name=axis,
                                      causal=causal, scale=scale)}
    return {'Out': flash_attention(q, k, v, causal=causal, scale=scale)}


# --------------------------------------------------- KV-cache read path

def cached_attention(q, kcache, vcache, qpos, scale=None):
    """Attention of new-position queries against a KV cache row.

    q: [B, H, Tq, D] — the Tq new positions (a prefill chunk, or Tq=1
    for one decode step); kcache/vcache: [B, Hkv, Tmax, D] with the new
    positions' K/V already written; qpos: [B, Tq] int32 ABSOLUTE
    positions of the queries.  Masking is positional — key position
    kpos is visible iff ``kpos <= qpos`` — so mid-prompt chunk offsets
    and per-slot decode lengths share one rule, and garbage beyond a
    row's true length is never attended (unlike `_ref_attention`'s
    end-aligned causal mask, which assumes the query block sits at the
    END of the key range).  GQA-native and f32-accumulating, matching
    the `_ref_attention` precision contract.
    """
    B, H, Tq, D = q.shape
    Hkv, Tmax = kcache.shape[1], kcache.shape[2]
    if scale is None:
        scale = D ** -0.5
    g = H // Hkv
    qg = q.reshape(B, Hkv, g, Tq, D)
    s = jnp.einsum('bhgqd,bhkd->bhgqk', qg, kcache,
                   preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(Tmax)
    mask = kpos[None, None, :] <= qpos[:, :, None]        # [B, Tq, Tmax]
    s = jnp.where(mask[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhgqk,bhkd->bhgqd', p.astype(vcache.dtype), vcache,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, H, Tq, D).astype(q.dtype)


def write_cache(kcache, vcache, k, v, slot, layer, offset):
    """Write one layer's new K/V for one slot at a position offset.

    kcache/vcache: [S, L, Hkv, Tmax, D] slot-major pages; k/v:
    [Hkv, C, D] for the C new positions of layer ``layer``; slot/offset
    are traced scalars.  The write is a pure dynamic_update_slice so the
    whole prefill/decode step stays one fused XLA program with the cache
    as donated carry (no host round-trip per layer or per token).
    """
    k = k[None, None].astype(kcache.dtype)
    v = v[None, None].astype(vcache.dtype)
    idx = (slot, layer, 0, offset, 0)
    return (jax.lax.dynamic_update_slice(kcache, k, idx),
            jax.lax.dynamic_update_slice(vcache, v, idx))
