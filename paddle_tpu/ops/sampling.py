"""Token sampling: pure decode-time helpers + the `sample_tokens` op.

Decode-time sampling must be *rerun-deterministic*: the same
``(seed, position)`` pair always draws the same token, whether the
token came from a fused K-step `lax.scan` window, K single-step
launches, or a rerun through a restored AOT executable.  The pure
helpers therefore derive one key per absolute sequence position —
``fold_in(key(seed), position)`` — with no stateful key splitting
anywhere, so decode order and batching can never shift the stream
(the contract `tests/test_generation.py` pins bitwise).

``temperature <= 0`` means greedy (argmax); ``top_k > 0`` restricts
the draw to the k highest logits first.  ``top_k`` is a *traced*
value (sort + threshold, not a static lax.top_k call), so one decode
executable serves every per-request k without retracing.  Ties at the
k-th logit all stay eligible — the restriction is "logit >= k-th
highest", the deterministic formulation.

The `sample_tokens` Program op wires the same math into the graph
runtime: with no explicit ``seed`` attr it draws from ``ctx.rng()``,
which honors the `rng_stream` attr pinned by the optimizer passes —
a rewritten (PT_OPT=1) program samples the same tokens as the raw one.
"""
import jax
import jax.numpy as jnp

from ..core.dtypes import jax_dtype
from ..core.registry import register

_NEG_INF = -1e30

__all__ = ['token_key', 'sample_logits', 'sample_tokens_at']


def token_key(seed, position):
    """The per-token PRNG key: keyed by (request seed, absolute position
    of the token being sampled) and nothing else."""
    return jax.random.fold_in(jax.random.key(seed), position)


def sample_logits(logits, key, temperature=0.0, top_k=0):
    """One row: logits [V] -> token id (int32).  All args traceable."""
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    k = jnp.clip(jnp.asarray(top_k, jnp.int32), 0, v)
    # k-th highest logit as the eligibility floor; k <= 0 disables it
    sorted_desc = -jnp.sort(-logits, axis=-1)
    thresh = sorted_desc[jnp.clip(k - 1, 0, v - 1)]
    allowed = jnp.where(k > 0, logits >= thresh, True)
    temp = jnp.asarray(temperature, jnp.float32)
    scaled = jnp.where(allowed, logits, _NEG_INF) \
        / jnp.where(temp > 0, temp, 1.0)
    drawn = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temp > 0, drawn, greedy)


def sample_tokens_at(logits, seeds, positions, temperatures, top_ks):
    """Batch of independent rows: logits [B, V] with per-row seeds /
    absolute positions / temperatures / top_ks (each [B])."""
    keys = jax.vmap(token_key)(seeds, positions)
    return jax.vmap(sample_logits)(logits, keys, temperatures, top_ks)


@register('sample_tokens')
def sample_tokens(ctx, ins, attrs):
    logits = ins['Logits']                     # [..., V]
    temp = float(attrs.get('temperature', 0.0))
    top_k = int(attrs.get('top_k', 0))
    seed = int(attrs.get('seed', 0))
    key = jax.random.key(seed) if seed else ctx.rng()
    flat = logits.reshape((-1, logits.shape[-1]))
    keys = jax.vmap(jax.random.fold_in, (None, 0))(
        key, jnp.arange(flat.shape[0]))
    out = jax.vmap(sample_logits, (0, 0, None, None))(
        flat, keys, temp, top_k)
    return {'Out': out.reshape(logits.shape[:-1])
            .astype(jax_dtype('int64'))}
