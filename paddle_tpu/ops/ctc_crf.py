"""CTC + linear-chain CRF + projection-LSTM ops.

Parity: reference paddle/fluid/operators/warpctc_op.cc (wraps Baidu
warp-ctc), ctc_align_op.cc, linear_chain_crf_op.{h,cc}, crf_decoding_op.cc,
lstmp_op.cc.

TPU-native redesign: the reference dispatches hand-written CPU/CUDA kernels
per sequence over LoD offset tables.  Here every recursion is a log-space
`lax.scan` over the padded time axis, batch-vectorized (vmap / dense masks),
so the whole loss lowers into the surrounding XLA program and the backward
pass comes from autodiff of the scan — no custom gradient kernels.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register
from ..core.dtypes import jax_dtype
from .sequence import _length_or_full, _ACTS

_NEG = -1e30  # log-space "minus infinity" that survives bf16/f32 adds


def _squeeze_label(lab):
    if lab.ndim >= 2 and lab.shape[-1] == 1:
        lab = lab.reshape(lab.shape[:-1])
    return lab


# ------------------------------------------------------------------ CTC

def _ctc_nll_single(logp, labels, T_len, L_len, blank):
    """Negative log-likelihood of one sequence.

    logp: [T, C] log-softmax scores; labels: [L] int32; T_len/L_len scalars.
    Classic alpha recursion over the extended label string
    [blank, l1, blank, ..., lL, blank] (S = 2L+1), log-space.
    """
    T, C = logp.shape
    L = labels.shape[0]
    S = 2 * L + 1
    ext = jnp.full((S,), blank, jnp.int32).at[1::2].set(
        labels.astype(jnp.int32))
    # skip connection s-2 -> s allowed where ext[s] is a label differing
    # from ext[s-2]
    prev2 = jnp.concatenate([jnp.full((2,), -1, jnp.int32), ext[:-2]])
    can_skip = (ext != blank) & (ext != prev2)
    svalid = jnp.arange(S) < 2 * L_len + 1

    lp0 = logp[0]
    alpha0 = jnp.full((S,), _NEG)
    alpha0 = alpha0.at[0].set(lp0[blank])
    alpha0 = jnp.where((jnp.arange(S) == 1) & (L_len > 0),
                       lp0[ext[1]], alpha0)

    def step(alpha, t):
        lp = logp[t]
        a1 = alpha
        a2 = jnp.concatenate([jnp.array([_NEG]), alpha[:-1]])
        a3 = jnp.where(can_skip,
                       jnp.concatenate([jnp.full((2,), _NEG), alpha[:-2]]),
                       _NEG)
        stacked = jnp.stack([a1, a2, a3])
        m = jnp.max(stacked, axis=0)
        new = m + jnp.log(jnp.sum(jnp.exp(stacked - m), axis=0))
        new = new + lp[ext]
        new = jnp.where(svalid, new, _NEG)
        # freeze once past this sequence's last frame
        return jnp.where(t < T_len, new, alpha), None

    alphaT, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    S_end = 2 * L_len  # index of final blank in the extended string
    last_blank = alphaT[S_end]
    last_label = jnp.where(S_end - 1 >= 0, alphaT[jnp.maximum(S_end - 1, 0)],
                           _NEG)
    m = jnp.maximum(last_blank, last_label)
    ll = m + jnp.log(jnp.exp(last_blank - m) + jnp.exp(last_label - m))
    return -ll


@register('warpctc')
def warpctc(ctx, ins, attrs):
    """CTC loss (ref warpctc_op.cc:1).  Logits [B, T, C] unnormalized;
    Label [B, L] int; per-sequence total NLL out as [B, 1]."""
    logits = ins['Logits']
    labels = _squeeze_label(ins['Label'])
    blank = int(attrs.get('blank', 0))
    T_lens = (ins['LogitsLength'] if ins.get('LogitsLength') is not None
              else jnp.full((logits.shape[0],), logits.shape[1], jnp.int32))
    L_lens = (ins['LabelLength'] if ins.get('LabelLength') is not None
              else jnp.full((labels.shape[0],), labels.shape[1], jnp.int32))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = jax.vmap(_ctc_nll_single, in_axes=(0, 0, 0, 0, None))(
        logp, labels, T_lens.astype(jnp.int32), L_lens.astype(jnp.int32),
        blank)
    if attrs.get('norm_by_times'):
        nll = nll / jnp.maximum(T_lens.astype(nll.dtype), 1.0)
    return {'Loss': nll[:, None].astype(logits.dtype)}


@register('ctc_align')
def ctc_align(ctx, ins, attrs):
    """Greedy CTC decode (ref ctc_align_op.cc:1): argmax per frame, merge
    repeats, drop blanks; zero-padded output + OutLength."""
    x = ins['X']
    blank = int(attrs.get('blank', 0))
    merge = bool(attrs.get('merge_repeated', True))
    if x.ndim == 3 and x.shape[-1] > 1:
        if not jnp.issubdtype(x.dtype, jnp.floating):
            raise ValueError(
                'ctc_align input must be float [B, T, V] logits/probs or '
                'integer ids [B, T] / [B, T, 1]; got %s %s' %
                (x.dtype, x.shape))
        # raw probs/logits [B, T, V]: take the greedy path first
        tok = jnp.argmax(x, axis=-1).astype(jnp.int32)
    else:
        # already token ids — [B, T] or the fluid [B, T, 1] id layout
        # (which must NOT be argmaxed: over a size-1 axis that decodes
        # every frame to 0)
        if jnp.issubdtype(x.dtype, jnp.floating):
            raise ValueError(
                'ctc_align id-shaped input (%s) must be integer tokens; '
                'float probabilities need the [B, T, V>1] logits layout'
                % (x.shape,))
        tok = _squeeze_label(x).astype(jnp.int32)
    B, T = tok.shape
    length = _length_or_full(ins, x).astype(jnp.int32)
    valid = jnp.arange(T)[None, :] < length[:, None]
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), tok[:, :-1]],
                           axis=1)
    keep = valid & (tok != blank)
    if merge:
        keep = keep & (tok != prev)

    def compact(row_tok, row_keep):
        pos = jnp.cumsum(row_keep) - 1
        safe = jnp.where(row_keep, pos, T)
        return jnp.zeros((T + 1,), jnp.int32).at[safe].set(row_tok)[:T]

    out = jax.vmap(compact)(tok, keep)
    out_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    return {'Output': out.astype(jax_dtype('int64')), 'OutLength': out_len}


# ------------------------------------------------------------------ CRF

def _crf_unpack(transition):
    """Reference layout (linear_chain_crf_op.h:1): row 0 = start weights,
    row 1 = stop weights, rows 2: = [C, C] tag-transition matrix."""
    return transition[0], transition[1], transition[2:]


@register('linear_chain_crf')
def linear_chain_crf(ctx, ins, attrs):
    """Linear-chain CRF negative log-likelihood (a cost, like the
    reference: conll05 does mean(crf_cost) and minimizes it)."""
    x = ins['X']                       # [B, T, C] emissions
    transition = ins['Transition']     # [C+2, C]
    labels = _squeeze_label(ins['Label']).astype(jnp.int32)  # [B, T]
    length = _length_or_full(ins, x).astype(jnp.int32)
    start_w, stop_w, w = _crf_unpack(transition.astype(jnp.float32))
    xf = x.astype(jnp.float32)
    B, T, C = xf.shape
    tpos = jnp.arange(T)

    def one(xb, lb, nb):
        alpha0 = start_w + xb[0]

        def step(carry, t):
            alpha, score, final_alpha, prev_lab = carry
            # partition function recursion
            scores = alpha[:, None] + w                 # [C_from, C_to]
            m = jnp.max(scores, axis=0)
            new_alpha = m + jnp.log(
                jnp.sum(jnp.exp(scores - m), axis=0)) + xb[t]
            # gold-path score increment
            new_score = score + w[prev_lab, lb[t]] + xb[t, lb[t]]
            live = t < nb
            alpha = jnp.where(live, new_alpha, alpha)
            score = jnp.where(live, new_score, score)
            final_alpha = jnp.where(live, new_alpha, final_alpha)
            prev_lab = jnp.where(live, lb[t], prev_lab)
            return (alpha, score, final_alpha, prev_lab), new_alpha

        init_score = start_w[lb[0]] + xb[0, lb[0]]
        (alpha, score, final_alpha, last_lab), alphas = lax.scan(
            step, (alpha0, init_score, alpha0, lb[0]), tpos[1:])
        score = score + stop_w[last_lab]
        z_terms = final_alpha + stop_w
        m = jnp.max(z_terms)
        logz = m + jnp.log(jnp.sum(jnp.exp(z_terms - m)))
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)
        return logz - score, alphas

    nll, alphas = jax.vmap(one)(xf, labels, length)
    return {'LogLikelihood': nll[:, None].astype(x.dtype),
            'Alpha': alphas.astype(x.dtype),
            'EmissionExps': jnp.exp(xf).astype(x.dtype),
            'TransitionExps': jnp.exp(transition)}


@register('crf_decoding')
def crf_decoding(ctx, ins, attrs):
    """Viterbi decode (ref crf_decoding_op.h:1).  With Label given, emits
    the per-token correctness indicator instead (reference semantics)."""
    x = ins['X']
    transition = ins['Transition']
    length = _length_or_full(ins, x).astype(jnp.int32)
    start_w, stop_w, w = _crf_unpack(transition.astype(jnp.float32))
    xf = x.astype(jnp.float32)
    B, T, C = xf.shape
    tpos = jnp.arange(T)

    def one(xb, nb):
        alpha0 = start_w + xb[0]

        def fwd(carry, t):
            alpha = carry
            scores = alpha[:, None] + w + xb[t][None, :]
            best_from = jnp.argmax(scores, axis=0)
            new_alpha = jnp.max(scores, axis=0)
            live = t < nb
            alpha = jnp.where(live, new_alpha, alpha)
            # frozen steps keep identity backpointers so backtracking
            # passes through them untouched
            bp = jnp.where(live, best_from, jnp.arange(C))
            return alpha, bp

        alphaT, bps = lax.scan(fwd, alpha0, tpos[1:])  # bps: [T-1, C]
        last = jnp.argmax(alphaT + stop_w).astype(jnp.int32)

        def back(carry, bp):
            tag = carry
            return bp[tag].astype(jnp.int32), tag

        first, rev_path = lax.scan(back, last, bps, reverse=True)
        path = jnp.concatenate([first[None], rev_path])
        return jnp.where(tpos < nb, path, 0)

    path = jax.vmap(one)(xf, length)
    if ins.get('Label') is not None:
        lab = _squeeze_label(ins['Label']).astype(path.dtype)
        valid = tpos[None, :] < length[:, None]
        return {'ViterbiPath':
                (jnp.where(valid, path == lab, False)).astype(jax_dtype('int64'))}
    return {'ViterbiPath': path.astype(jax_dtype('int64'))}


# ---------------------------------------------------------------- lstmp

@register('lstmp')
def lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection (ref lstmp_op.cc:1): the projection
    r_t = proj_act(h_t @ ProjWeight) feeds back into the gates, so the
    recurrent GEMM is [P, 4D] instead of [D, 4D]."""
    x = ins['Input']                 # [B, T, 4D] pre-projected
    w = ins['Weight']                # [P, 4D]
    pw = ins['ProjWeight']           # [D, P]
    bias = ins['Bias']
    length = _length_or_full(ins, x)
    D = pw.shape[0]
    P = pw.shape[1]
    B, T, _ = x.shape
    gate_act = _ACTS[attrs.get('gate_activation', 'sigmoid')]
    cell_act = _ACTS[attrs.get('cell_activation', 'tanh')]
    cand_act = _ACTS[attrs.get('candidate_activation', 'tanh')]
    proj_act = _ACTS[attrs.get('proj_activation', 'tanh')]
    use_peep = attrs.get('use_peepholes', True)
    is_rev = attrs.get('is_reverse', False)

    if is_rev:
        x = jnp.flip(x, axis=1)
    tmask = (jnp.arange(T)[None, :] < length[:, None]).astype(x.dtype)
    if is_rev:
        tmask = jnp.flip(tmask, axis=1)
    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(tmask, 0, 1)
    if use_peep:
        b_g, w_ic, w_fc, w_oc = (bias[:, :4 * D], bias[:, 4 * D:5 * D],
                                 bias[:, 5 * D:6 * D], bias[:, 6 * D:7 * D])
    else:
        b_g = bias
        w_ic = w_fc = w_oc = None

    def step(carry, inp):
        r, c = carry
        xt, mt = inp
        gates = xt + r @ w + b_g
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if use_peep:
            i = i + c * w_ic
            f = f + c * w_fc
        i, f = gate_act(i), gate_act(f)
        g = cand_act(g)
        c_new = f * c + i * g
        if use_peep:
            o = o + c_new * w_oc
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        r_new = proj_act(h_new @ pw)
        m = mt[:, None]
        r = m * r_new + (1 - m) * r
        c = m * c_new + (1 - m) * c
        return (r, c), (r, c)

    r0 = jnp.zeros((B, P), x.dtype)
    c0 = jnp.zeros((B, D), x.dtype)
    _, (rs, cs) = lax.scan(step, (r0, c0), (xs, ms))
    rs = jnp.swapaxes(rs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if is_rev:
        rs = jnp.flip(rs, axis=1)
        cs = jnp.flip(cs, axis=1)
    return {'Projection': rs, 'Cell': cs}
