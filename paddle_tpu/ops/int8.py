"""True int8 inference kernels.

Parity: the reference's int8 deployment path runs conv/fc on MKLDNN int8
kernels after contrib/int8_inference calibration.  The TPU analog feeds
the MXU int8×int8→int32 directly: activations quantize at their
calibrated scale in-graph, weights are the int8 arrays
Calibrator/QuantizeTranspiler packed, and the int32 accumulator
dequantizes by (x_scale · w_scale / 127²).

Measured (TPU v5 lite, 8192×4096×4096 GEMM): int8 2.88 ms vs bf16
3.58 ms — **1.24×**, well short of the 2× the int8 spec sheet implies;
XLA's int8 dot lowering doesn't reach the doubled MXU rate on this
generation.  Int8's main win here remains the 4× weight-memory cut
(and with it HBM bandwidth on weight-bound inference).
"""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.registry import register

_Q = 127.0


def _quantize(x, scale):
    s = jnp.asarray(scale, jnp.float32)
    q = jnp.clip(jnp.round(x / jnp.maximum(s, 1e-8) * _Q), -_Q, _Q)
    return q.astype(jnp.int8)


@register('mul_int8')
def mul_int8(ctx, ins, attrs):
    """reference mul_op flattened GEMM, int8 in / int32 accumulate."""
    x, w = ins['X'], ins['Y']          # w already int8 [K, N]
    xn = attrs.get('x_num_col_dims', 1)
    xs = x.shape
    x2 = x.reshape(int(np.prod(xs[:xn])), -1)
    xq = _quantize(x2, attrs['x_scale'])
    acc = lax.dot_general(
        xq, w.astype(jnp.int8), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    deq = acc.astype(jnp.float32) * (
        float(attrs['x_scale']) * float(attrs['w_scale']) / (_Q * _Q))
    return {'Out': deq.reshape(xs[:xn] + w.shape[1:])}


@register('conv2d_int8')
def conv2d_int8(ctx, ins, attrs):
    from .nn import _pair
    x, w = ins['Input'], ins['Filter']  # w int8 OIHW
    strides = _pair(attrs.get('strides', [1, 1]))
    pads = _pair(attrs.get('paddings', [0, 0]))
    dil = _pair(attrs.get('dilations', [1, 1]))
    groups = attrs.get('groups', 1) or 1
    xq = _quantize(x, attrs['x_scale'])
    acc = lax.conv_general_dilated(
        xq, w.astype(jnp.int8), window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil, feature_group_count=groups,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (
        float(attrs['x_scale']) * float(attrs['w_scale']) / (_Q * _Q))
    if 'Bias' in ins:
        out = out + ins['Bias'].reshape(1, -1, 1, 1)
    return {'Output': out}
