"""Loss ops.

Parity: reference cross_entropy_op, softmax_with_cross_entropy_op,
squared_l2/smooth_l1/huber/log/rank/margin_rank/bpr loss ops, nce_op,
hsigmoid_op, sigmoid_cross_entropy_with_logits_op.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register

_EPS = 1e-8


def _squeeze_label(label):
    if label.ndim >= 2 and label.shape[-1] == 1:
        return label[..., 0]
    return label


@register('cross_entropy')
def cross_entropy(ctx, ins, attrs):
    # log/sum in f32 regardless of input dtype (AMP feeds bf16 probs);
    # the per-row loss is always f32 so downstream reductions stay exact
    x, label = ins['X'].astype(jnp.float32), ins['Label']
    if attrs.get('soft_label', False):
        out = -jnp.sum(label.astype(jnp.float32) * jnp.log(x + _EPS),
                       axis=-1, keepdims=True)
        return {'Y': out}
    lab = _squeeze_label(label)
    picked = jnp.take_along_axis(x, lab[..., None].astype(jnp.int32),
                                 axis=-1)
    ignore = attrs.get('ignore_index', -100)
    out = -jnp.log(picked + _EPS)
    out = jnp.where(lab[..., None] == ignore, jnp.zeros_like(out), out)
    return {'Y': out}


import functools


@functools.lru_cache(maxsize=None)
def _make_hard_ce(V, eps, ignore):
    """Efficient hard-label CE with a hand-written vjp (per-HLO profile,
    PERF.md r5): JAX autodiff of the logsumexp chain materialized the
    dlogits cotangent as an f32 [B, T, V] buffer (1 GB at bench shapes)
    plus a separate log_softmax backward reduction pass.  Here the
    residuals are just (logits, label, lse[B,T,1]); the backward
    computes dlogits = g * (softmax - (1-eps)*onehot - eps/V) in ONE
    fused elementwise pass and emits it in the LOGITS dtype — bf16 when
    the projection flows through under AMP, so the two backward GEMMs
    read half the bytes.  Numerics: all reductions and the stored lse
    are f32 regardless of logits dtype (same contract as before); the
    bf16 rounding of dlogits is the same rounding the MXU applied to
    the f32 cotangent anyway."""

    @jax.custom_vjp
    def ce(logits, lab):
        return _fwd(logits, lab)[0]

    def _fwd(logits, lab):
        x = logits.astype(jnp.float32)
        m = jnp.max(x, axis=-1, keepdims=True)
        lse = m + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True))
        # gather from the UNconverted logits: XLA can fuse a convert
        # into reduce fusions but not into the gather's kCustom call, so
        # take_along_axis(x, ...) forced a full f32 [B, T, V]
        # materialization just to pick B*T scalars (per-HLO ledger,
        # PERF.md r5); converting the picked values is identical math
        tgt = jnp.take_along_axis(logits, lab, axis=-1).astype(jnp.float32)
        if eps:
            # (1-eps)*hard_ce + eps*(-mean logp), closed form
            loss = lse - (1.0 - eps) * tgt - eps * jnp.mean(
                x, axis=-1, keepdims=True)
        else:
            loss = lse - tgt
        loss = jnp.where(lab == ignore, jnp.zeros_like(loss), loss)
        return loss, (logits, lab, lse)

    def bwd(res, g):
        logits, lab, lse = res
        x = logits.astype(jnp.float32)
        p = jnp.exp(x - lse)
        onehot = (jnp.arange(V) == lab).astype(jnp.float32)
        d = p - (1.0 - eps) * onehot - (eps / V)
        gz = jnp.where(lab == ignore, jnp.zeros_like(g),
                       g.astype(jnp.float32))
        dlogits = (gz * d).astype(logits.dtype)
        return dlogits, np.zeros(lab.shape, jax.dtypes.float0)

    ce.defvjp(_fwd, bwd)
    return ce


@register('softmax_with_cross_entropy')
def softmax_with_cross_entropy(ctx, ins, attrs):
    # logsumexp in f32 (bf16 logits under AMP are fine — the reduction is
    # not); Loss is always f32.  Hard labels over the last axis take the
    # custom-vjp fast path (_make_hard_ce); jax.checkpoint remat of the
    # whole op measured 19% slower (PERF.md), kept behind PT_CE_REMAT=1.
    logits, label = ins['Logits'], ins['Label']
    axis = attrs.get('axis', -1)
    ndim = logits.ndim
    if not attrs.get('soft_label', False) and axis in (-1, ndim - 1):
        lab = label
        if lab.ndim == ndim - 1:
            lab = jnp.expand_dims(lab, -1)
        lab = lab.astype(jnp.int32)
        ce = _make_hard_ce(int(logits.shape[-1]),
                           float(attrs.get('label_smooth_eps', 0.0)),
                           int(attrs.get('ignore_index', -100)))
        loss = ce(logits, lab)
        # derived lazily so an unused Softmax output DCEs away with its
        # whole log_softmax chain (the common training case)
        softmax = jnp.exp(jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1))
        return {'Loss': loss, 'Softmax': softmax}
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if attrs.get('soft_label', False):
        loss = -jnp.sum(label.astype(jnp.float32) * logp, axis=axis,
                        keepdims=True)
    else:
        # label keeps a size-1 dim at `axis` (reference convention); add it
        # if the caller passed the squeezed form
        lab = label
        if lab.ndim == logp.ndim - 1:
            lab = jnp.expand_dims(lab, axis)
        picked = jnp.take_along_axis(logp, lab.astype(jnp.int32), axis=axis)
        loss = -picked
        eps = attrs.get('label_smooth_eps', 0.0)
        if eps:
            # fused uniform label smoothing: -sum(soft*logp) with
            # soft = (1-eps)*onehot + eps/V equals
            # (1-eps)*hard_ce + eps*(-mean(logp)) — the [.., V] one-hot /
            # smoothed-label tensors never materialize, and AD yields the
            # same softmax-minus-soft gradient
            loss = (1.0 - eps) * loss + eps * (
                -jnp.mean(logp, axis=axis, keepdims=True))
        ignore = attrs.get('ignore_index', -100)
        loss = jnp.where(lab == ignore, jnp.zeros_like(loss), loss)
    return {'Loss': loss, 'Softmax': jnp.exp(logp)}


@register('square_error_cost')
def square_error_cost(ctx, ins, attrs):
    return {'Out': jnp.square(ins['X'] - ins['Y'])}


@register('smooth_l1_loss')
def smooth_l1_loss(ctx, ins, attrs):
    x, y = ins['X'], ins['Y']
    sigma = attrs.get('sigma', 1.0)
    s2 = sigma * sigma
    diff = x - y
    if 'InsideWeight' in ins:
        diff = diff * ins['InsideWeight']
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * jnp.square(diff),
                     ad - 0.5 / s2)
    if 'OutsideWeight' in ins:
        loss = loss * ins['OutsideWeight']
    return {'Out': jnp.sum(loss, axis=tuple(range(1, loss.ndim)),
                           keepdims=False).reshape(-1, 1),
            'Diff': diff}


@register('huber_loss')
def huber_loss(ctx, ins, attrs):
    x, y = ins['X'], ins['Y']
    d = attrs.get('delta', 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= d, 0.5 * jnp.square(r), d * (ar - 0.5 * d))
    return {'Out': loss, 'Residual': r}


@register('log_loss')
def log_loss(ctx, ins, attrs):
    p, label = ins['Predicted'], ins['Labels']
    eps = attrs.get('epsilon', 1e-4)
    out = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {'Loss': out}


@register('rank_loss')
def rank_loss(ctx, ins, attrs):
    label, left, right = ins['Label'], ins['Left'], ins['Right']
    d = left - right
    out = jnp.log1p(jnp.exp(d)) - label * d
    return {'Out': out}


@register('margin_rank_loss')
def margin_rank_loss(ctx, ins, attrs):
    label, x1, x2 = ins['Label'], ins['X1'], ins['X2']
    m = attrs.get('margin', 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + m)
    return {'Out': out, 'Activated': (out > 0).astype(x1.dtype)}


@register('bpr_loss')
def bpr_loss(ctx, ins, attrs):
    x, label = ins['X'], ins['Label']  # x: [N, C] logits
    lab = _squeeze_label(label).astype(jnp.int32)
    n, c = x.shape
    pos = jnp.take_along_axis(x, lab[:, None], axis=1)
    diff = pos - x  # [N, C]
    lse = -jnp.log(jax.nn.sigmoid(diff) + _EPS)
    mask = 1.0 - jax.nn.one_hot(lab, c, dtype=x.dtype)
    out = jnp.sum(lse * mask, axis=1, keepdims=True) / (c - 1)
    return {'Y': out}


@register('sigmoid_cross_entropy_with_logits')
def sigmoid_cross_entropy_with_logits(ctx, ins, attrs):
    x, label = ins['X'], ins['Label']
    ignore = attrs.get('ignore_index', -100)
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    loss = jnp.where(label == ignore, jnp.zeros_like(loss), loss)
    if attrs.get('normalize', False):
        cnt = jnp.sum((label != ignore).astype(x.dtype))
        loss = loss / jnp.maximum(cnt, 1.0)
    return {'Out': loss}


@register('teacher_student_sigmoid_loss')
def teacher_student_sigmoid_loss(ctx, ins, attrs):
    x, label = ins['X'], ins['Label']
    soft_max_up = attrs.get('soft_max_up_bound', 15.0)
    soft_max_lo = attrs.get('soft_max_lower_bound', -15.0)
    z = jnp.clip(x, soft_max_lo, soft_max_up)
    # teacher (soft) part + student (hard) part, ref
    # teacher_student_sigmoid_loss_op.cc
    out = jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(z, 0) - z * label
    return {'Y': out}


@register('kldiv_loss')
def kldiv_loss(ctx, ins, attrs):
    x, target = ins['X'], ins['Target']
    loss = target * (jnp.log(target + _EPS) - x)
    red = attrs.get('reduction', 'mean')
    if red == 'mean':
        loss = jnp.mean(loss).reshape(1)
    elif red == 'sum':
        loss = jnp.sum(loss).reshape(1)
    elif red == 'batchmean':
        loss = (jnp.sum(loss) / x.shape[0]).reshape(1)
    return {'Loss': loss}


@register('nce')
def nce(ctx, ins, attrs):
    """Noise-contrastive estimation (ref nce_op.cc).  TPU-native: sampled
    softmax with uniform negative sampling, fully batched."""
    x, w, label = ins['Input'], ins['Weight'], ins['Label']
    num_neg = attrs.get('num_neg_samples', 10)
    num_classes = attrs.get('num_total_classes')
    lab = _squeeze_label(label).astype(jnp.int32)
    b = x.shape[0]
    key = ctx.rng()
    neg = jax.random.randint(key, (b, num_neg), 0, num_classes)
    ids = jnp.concatenate([lab[:, None], neg], axis=1)  # [B, 1+K]
    wsel = jnp.take(w, ids, axis=0)  # [B, 1+K, D]
    logits = jnp.einsum('bd,bkd->bk', x, wsel)
    if 'Bias' in ins:
        logits = logits + jnp.take(ins['Bias'], ids, axis=0).reshape(
            logits.shape)
    labels01 = jnp.concatenate(
        [jnp.ones((b, 1)), jnp.zeros((b, num_neg))], axis=1)
    loss = jnp.maximum(logits, 0) - logits * labels01 + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return {'Cost': jnp.sum(loss, axis=1, keepdims=True),
            'SampleLogits': logits, 'SampleLabels': ids}


@register('hierarchical_sigmoid')
def hierarchical_sigmoid(ctx, ins, attrs):
    """hsigmoid (ref hierarchical_sigmoid_op.cc) with a complete binary
    tree over classes."""
    x, w, label = ins['X'], ins['W'], ins['Label']
    num_classes = attrs.get('num_classes')
    code_len = int(np.ceil(np.log2(max(num_classes, 2))))
    lab = _squeeze_label(label).astype(jnp.int32)
    # path of internal nodes for each class in a complete binary tree
    codes = []
    bits = []
    node = lab + num_classes  # leaves occupy [num_classes, 2*num_classes)
    for _ in range(code_len):
        parent = node // 2
        bit = (node % 2).astype(x.dtype)
        codes.append(parent - 1)  # internal nodes indexed from 1
        bits.append(bit)
        node = parent
    codes = jnp.stack(codes, axis=1)  # [B, L]
    bits = jnp.stack(bits, axis=1)
    codes = jnp.clip(codes, 0, w.shape[0] - 1)
    wsel = jnp.take(w, codes, axis=0)  # [B, L, D]
    logits = jnp.einsum('bd,bld->bl', x, wsel)
    if 'Bias' in ins:
        logits = logits + jnp.take(ins['Bias'].reshape(-1), codes, axis=0)
    loss = jnp.maximum(logits, 0) - logits * bits + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return {'Out': jnp.sum(loss, axis=1, keepdims=True),
            'PreOut': logits}


@register('dice_loss')
def dice_loss(ctx, ins, attrs):
    # implemented at layer level in reference too; kept as op for parity
    x, label = ins['X'], ins['Label']
    eps = attrs.get('epsilon', 1e-5)
    label = label.astype(x.dtype)
    inter = 2.0 * jnp.sum(x * label, axis=tuple(range(1, x.ndim)))
    union = jnp.sum(x, axis=tuple(range(1, x.ndim))) + \
        jnp.sum(label, axis=tuple(range(1, x.ndim)))
    return {'Out': (1.0 - inter / (union + eps)).reshape(-1, 1)}
