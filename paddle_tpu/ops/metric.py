"""Metric ops: accuracy, auc, mean_iou, edit distance (batch-local parts).

Parity: reference accuracy_op, auc_op, mean_iou_op, precision_recall.
Streaming state (AUC stat buckets etc.) lives in persistable vars updated by
the op, same pattern as the reference.
"""
import jax.numpy as jnp

from ..core.registry import register


@register('accuracy')
def accuracy(ctx, ins, attrs):
    indices, label = ins['Indices'], ins['Label']
    if label.ndim == 2 and label.shape[1] == 1:
        label = label[:, 0]
    correct = jnp.any(indices == label[:, None], axis=1)
    total = jnp.asarray(label.shape[0], jnp.int32)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    acc = (num_correct.astype(jnp.float32) / total.astype(jnp.float32))
    return {'Accuracy': acc.reshape(1), 'Correct': num_correct.reshape(1),
            'Total': total.reshape(1)}


@register('auc')
def auc(ctx, ins, attrs):
    """Streaming AUC with histogram buckets (ref auc_op.cc)."""
    preds, label = ins['Predict'], ins['Label']
    stat_pos, stat_neg = ins['StatPos'], ins['StatNeg']
    num_thresholds = attrs.get('num_thresholds', 4095)
    if label.ndim == 2:
        label = label[:, 0]
    p1 = preds[:, -1] if preds.ndim == 2 else preds
    bucket = jnp.clip((p1 * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    pos = (label > 0).astype(stat_pos.dtype)
    new_pos = stat_pos.at[bucket].add(pos)
    new_neg = stat_neg.at[bucket].add(1 - pos)
    # trapezoid integration over thresholds, descending
    tp = jnp.cumsum(new_pos[::-1])
    fp = jnp.cumsum(new_neg[::-1])
    total_pos = tp[-1]
    total_neg = fp[-1]
    tpr = tp / jnp.maximum(total_pos, 1)
    fpr = fp / jnp.maximum(total_neg, 1)
    auc_val = jnp.trapezoid(tpr, fpr)
    return {'AUC': auc_val.reshape(1).astype(jnp.float64)
            if False else auc_val.reshape(1),
            'StatPosOut': new_pos, 'StatNegOut': new_neg}


@register('mean_iou')
def mean_iou(ctx, ins, attrs):
    pred, label = ins['Predictions'], ins['Labels']
    num_classes = attrs['num_classes']
    p = pred.reshape(-1).astype(jnp.int32)
    l = label.reshape(-1).astype(jnp.int32)
    idx = l * num_classes + p
    cm = jnp.zeros((num_classes * num_classes,), jnp.float32).at[idx].add(1.0)
    cm = cm.reshape(num_classes, num_classes)
    inter = jnp.diag(cm)
    union = jnp.sum(cm, 0) + jnp.sum(cm, 1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-12), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return {'OutMeanIou': miou.reshape(1),
            'OutWrong': jnp.sum(cm, 1) - inter,
            'OutCorrect': inter}


@register('precision_recall')
def precision_recall(ctx, ins, attrs):
    raise NotImplementedError('use paddle_tpu.metrics.Precision/Recall')
