"""D010/D011: retrace hazards, predicted statically.

The PR-2 retrace explainer (observability/retrace.py) names the cache-key
component that changed AFTER a retrace already cost a compile; this pass
reports the same hazards from the program alone, before anything runs:

  D010  a feed var has a dynamic (-1) dim.  Every distinct extent seen
        at run time is a fresh jit signature -> a fresh trace+compile.
        Severity is graded: a dynamic BATCH dim (axis 0) is info — every
        minibatch model has one, and a FeedBucketer with a mask feed
        collapses it onto a handful of boundaries; dynamic sequence/
        feature dims are warnings, annotated with whether the provided
        bucketer (Program.lint(bucketer=...)) already covers them.
  D011  an op attr holds a numpy array: unhashable in cache keys, and a
        per-run mutation via op.set_attr bumps the program version and
        forces a full re-lower every step.
"""
import numpy as np

from ..engine import register_pass

__all__ = ['run']


def _covered_axes(bucketer, name, lod_level):
    if bucketer is None:
        return set()
    if hasattr(bucketer, 'covered_axes'):
        return bucketer.covered_axes(name, lod_level=lod_level)
    return {0}


@register_pass('retrace_hazard')
def run(ctx):
    diags = []
    root = ctx.program.global_block()
    bucketer = ctx.bucketer
    for name, v in root.vars.items():
        if not getattr(v, 'is_data', False) or v.shape is None:
            continue
        if '@' in name:
            continue  # @LENGTH companions follow their owner's bucketing
        lod = getattr(v, 'lod_level', 0)
        covered = _covered_axes(bucketer, name, lod)
        for axis, d in enumerate(v.shape):
            if d not in (-1, None):
                continue
            if axis in covered:
                continue
            if axis == 0:
                if bucketer is not None:
                    continue  # batch padding is the bucketer's default job
                diags.append(ctx.diag(
                    'D010', 'info',
                    'feed "%s" has a dynamic batch dim: every distinct '
                    'batch size compiles a fresh executable (ragged '
                    'epoch tails retrace)' % name,
                    block=root, var=name,
                    fixit='wrap the feed stream in FeedBucketer('
                          'mask_name=...) to pad batches onto bucket '
                          'boundaries',
                    pass_name='retrace_hazard'))
            elif axis == 1 and lod <= 1:
                how = ('add "%s" to FeedBucketer(seq_names=...)' % name
                       if bucketer is not None else
                       'bucket it via FeedBucketer(seq_names=[%r])' % name)
                diags.append(ctx.diag(
                    'D010', 'warning',
                    'feed "%s" has a dynamic sequence dim (axis 1) not '
                    'covered by any bucket: every distinct padded length '
                    'is a fresh trace+compile — the retrace explainer '
                    'would report these as "bucketable" after the fact'
                    % name,
                    block=root, var=name, fixit=how,
                    pass_name='retrace_hazard'))
            else:
                diags.append(ctx.diag(
                    'D010', 'warning',
                    'feed "%s" has a dynamic dim on axis %d that no '
                    'bucketer can pad: every distinct extent compiles a '
                    'fresh executable' % (name, axis),
                    block=root, var=name,
                    fixit='declare a static extent for axis %d' % axis,
                    pass_name='retrace_hazard'))
    for block in ctx.program.blocks:
        for i, op in enumerate(block.ops):
            for k, val in op.attrs.items():
                if isinstance(val, np.ndarray):
                    diags.append(ctx.diag(
                        'D011', 'warning',
                        'op "%s" attr "%s" holds a %s array: array attrs '
                        'are unhashable in the lowering-cache key, and '
                        'mutating one per run (op.set_attr) bumps the '
                        'program version — a full re-lower every step'
                        % (op.type, k, 'x'.join(map(str, val.shape))),
                        block=block, op=op, op_index=i,
                        fixit='feed the tensor as a (persistable) input '
                              'instead of an attr',
                        pass_name='retrace_hazard'))
    return diags
