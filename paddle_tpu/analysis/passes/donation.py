"""D021: donation safety — the static form of the PR-6 heap bug.

The executor donates the parameter dict to the lowered executable
(donate_argnums=(0,)) whenever the program writes parameters back.
Donation frees the INPUT buffer the moment the executable runs, which
is only safe for buffers the runtime owns.  Two program shapes hand it
buffers someone else still holds, and both are invisible to the dynamic
D007 check (they are cross-launch, not in-block):

  * host-owned array into a donating executable: a feed name that
    shadows a written-back persistable routes the fed host ndarray into
    the donated params slot — after the launch the scope entry aliases
    freed memory (PR-6 corrupted the heap exactly here, at runtime;
    docs/robustness.md tells the dynamic half of that story)
  * param read after donation across fused `run_steps` chains: fetching
    a written-back Parameter hands the caller a handle into the donated
    carry — the NEXT chained launch invalidates it under the reader

Severity is warning (like D007/D008): the executor's copy-on-feed and
sync paths mask many instances, but each one is a latent use-after-free
that surfaces the day the masking path changes.
"""
from ...core.framework import Parameter
from ..engine import register_pass

__all__ = ['run']


def _written_persistables(ctx, block):
    """Persistable names written anywhere in `block` -> (op_index, op)
    of the first writing op (the point donation is decided)."""
    out = {}
    for i, op in enumerate(block.ops):
        for n in op.output_names():
            v = block._find_var_recursive(n)
            if v is not None and (v.persistable or
                                  isinstance(v, Parameter)):
                out.setdefault(n, (i, op))
    return out


@register_pass('donation')
def run(ctx):
    diags = []
    root = ctx.program.global_block()
    written = _written_persistables(ctx, root)
    if not written:
        return diags  # no writeback -> executor never donates

    for n in ctx.feed_names:
        if n in written:
            w_i, w_op = written[n]
            diags.append(ctx.diag(
                'D021', 'warning',
                'host-owned feed "%s" reaches a donating executable: the '
                'program writes it back (op#%d "%s"), so the executor '
                'donates the params dict and the fed host array\'s '
                'buffer is freed under the caller after the launch'
                % (n, w_i, w_op.type),
                block=root, op=w_op, op_index=w_i, var=n,
                fixit='device_put the array into the scope instead of '
                      'feeding it, or rename the feed',
                pass_name='donation'))

    for n in ctx.fetch_names:
        v = root._find_var_recursive(n)
        if isinstance(v, Parameter) and n in written:
            w_i, w_op = written[n]
            diags.append(ctx.diag(
                'D021', 'warning',
                'parameter "%s" is both written back (op#%d "%s") and '
                'fetched: under donation the fetched handle aliases the '
                'scan carry, and the next chained run_steps launch '
                'invalidates it while the caller still reads it'
                % (n, w_i, w_op.type),
                block=root, op=w_op, op_index=w_i, var=n,
                fixit='fetch a copy (assign to a fresh var) instead of '
                      'the parameter itself',
                pass_name='donation'))
    return diags
