"""D005/D006: dead ops and unused vars.

Reverse liveness walk per block (the reference's C++ analog is the
`graph_to_program` + dead-code-elimination IR passes): an op is alive
when any output (transitively) reaches a fetch, a persistable write, a
sub-block boundary, or a side-effecting op.  Everything else is work XLA
would DCE anyway — but silently, so the user never learns their fetch
list is wrong or a head was left unwired.

The walk itself lives in core/passes/walker.py, shared with the DCE
REWRITE pass (core/passes/dce.py) so reporting and elimination can never
drift apart.  This pass keeps `kill_overwrites=False`: a duplicate
writer of a downstream-read name is D009's finding, not a dead op.

The dead-op half needs a fetch set to anchor liveness; without one
(e.g. linting a startup program) it is skipped and only the unused-var
half runs.
"""
from ...core.framework import Parameter
from ...core.passes import walker
from ..engine import register_pass

__all__ = ['run']

# re-exported: aliasing/retrace passes and tests import it from here
_SIDE_EFFECT_OPS = walker.SIDE_EFFECT_OPS


def _block_liveness(ctx, block, fetch_names, diags):
    alive = walker.block_live_mask(ctx.program, block, fetch_names,
                                   kill_overwrites=False)
    for i, op in enumerate(block.ops):
        if not alive[i]:
            diags.append(ctx.diag(
                'D005', 'warning',
                'dead op "%s": its outputs %s never reach a fetch, '
                'persistable, or sub-block boundary'
                % (op.type, sorted(op.output_names())),
                block=block, op=op, op_index=i,
                fixit='remove the op, or add its output to fetch_list '
                      '(the PT_OPT=1 rewriter removes it automatically)',
                pass_name='liveness'))


def _unused_vars(ctx, diags):
    program = ctx.program
    fetch = set(ctx.fetch_names)
    for b in program.blocks:
        for name, v in b.vars.items():
            if '@' in name:
                continue  # @GRAD / @LENGTH / @LR_DECAY_COUNTER@ plumbing
            if v.persistable or isinstance(v, Parameter):
                continue
            if name in fetch or name in ctx.readers:
                continue
            produced = any(name in ctx.producers[bb.idx]
                           for bb in program.blocks)
            if not produced and not getattr(v, 'is_data', False):
                continue  # declared-only scratch var: nothing to report
            kind = 'feed var' if getattr(v, 'is_data', False) else 'var'
            diags.append(ctx.diag(
                'D006', 'info',
                '%s "%s" is never read and never fetched' % (kind, name),
                block=b, var=name,
                fixit='drop it from the program or the feed list',
                pass_name='liveness'))


@register_pass('liveness')
def run(ctx):
    diags = []
    if ctx.fetch_names:
        _block_liveness(ctx, ctx.program.global_block(), ctx.fetch_names,
                        diags)
    _unused_vars(ctx, diags)
    return diags
