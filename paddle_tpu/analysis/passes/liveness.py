"""D005/D006: dead ops and unused vars.

Reverse liveness walk per block (the reference's C++ analog is the
`graph_to_program` + dead-code-elimination IR passes): an op is alive
when any output (transitively) reaches a fetch, a persistable write, a
sub-block boundary, or a side-effecting op.  Everything else is work XLA
would DCE anyway — but silently, so the user never learns their fetch
list is wrong or a head was left unwired.

The dead-op half needs a fetch set to anchor liveness; without one
(e.g. linting a startup program) it is skipped and only the unused-var
half runs.
"""
from ...core.framework import Parameter
from ..engine import register_pass

__all__ = ['run']

# ops that are alive regardless of dataflow (observable effects)
_SIDE_EFFECT_OPS = {'print', 'py_func', '__backward__', 'write_to_array'}


def _sub_block_reads(program, block_idx, seen=None):
    """All var names read anywhere inside a sub-block tree — control-flow
    bodies read outer vars straight from the lowering env, not through
    the owning op's input slots, so they count as escaping uses."""
    seen = set() if seen is None else seen
    if block_idx in seen:
        return set()
    seen.add(block_idx)
    reads = set()
    for op in program.block(block_idx).ops:
        reads |= set(op.input_names())
        reads |= set(op.attrs.get('params', ()))
        sub = op.attrs.get('sub_block')
        if sub is not None:
            reads |= _sub_block_reads(program, sub, seen)
    return reads


def _block_liveness(ctx, block, fetch_names, diags):
    program = ctx.program
    persistable = set()
    for b in program.blocks:
        persistable |= {n for n, v in b.vars.items()
                        if v.persistable or isinstance(v, Parameter)}
    # names read by sub-blocks anywhere below an op of this block count
    # as escaping uses (the sub-block boundary)
    needed = set(fetch_names)
    alive = [False] * len(block.ops)
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        outs = set(op.output_names())
        is_alive = (bool(outs & needed) or
                    bool(outs & persistable) or
                    op.type in _SIDE_EFFECT_OPS or
                    op.attrs.get('sub_block') is not None)
        if is_alive:
            alive[i] = True
            needed |= set(op.input_names())
            if op.type == '__backward__':
                needed |= set(op.attrs.get('params', ()))
            sub = op.attrs.get('sub_block')
            if sub is not None:
                needed |= _sub_block_reads(program, sub)
    for i, op in enumerate(block.ops):
        if not alive[i]:
            diags.append(ctx.diag(
                'D005', 'warning',
                'dead op "%s": its outputs %s never reach a fetch, '
                'persistable, or sub-block boundary'
                % (op.type, sorted(op.output_names())),
                block=block, op=op, op_index=i,
                fixit='remove the op, or add its output to fetch_list',
                pass_name='liveness'))


def _unused_vars(ctx, diags):
    program = ctx.program
    fetch = set(ctx.fetch_names)
    for b in program.blocks:
        for name, v in b.vars.items():
            if '@' in name:
                continue  # @GRAD / @LENGTH / @LR_DECAY_COUNTER@ plumbing
            if v.persistable or isinstance(v, Parameter):
                continue
            if name in fetch or name in ctx.readers:
                continue
            produced = any(name in ctx.producers[bb.idx]
                           for bb in program.blocks)
            if not produced and not getattr(v, 'is_data', False):
                continue  # declared-only scratch var: nothing to report
            kind = 'feed var' if getattr(v, 'is_data', False) else 'var'
            diags.append(ctx.diag(
                'D006', 'info',
                '%s "%s" is never read and never fetched' % (kind, name),
                block=b, var=name,
                fixit='drop it from the program or the feed list',
                pass_name='liveness'))


@register_pass('liveness')
def run(ctx):
    diags = []
    if ctx.fetch_names:
        _block_liveness(ctx, ctx.program.global_block(), ctx.fetch_names,
                        diags)
    _unused_vars(ctx, diags)
    return diags
