"""D002/D003/D004: shape/dtype abstract interpretation.

Propagates jax.ShapeDtypeStruct through every registered op with
`jax.eval_shape` (the same machinery framework.Block._infer_shapes uses
at build time), but over the WHOLE program at once — so it also covers
ops appended with infer_shape=False (optimizer updates, detection
heads), programs loaded from disk via io.desc_to_program (which never
ran build-time inference), and hand-edited descs.

Like build-time inference, the batch dim stays symbolic: every -1 dim is
probed with two trial sizes (7 and 11) and output dims that differ
between the probes are batch dims.  An op whose inputs aren't fully
known is skipped (its outputs become unknown) — the pass is conservative
by construction and can only flag ops it could genuinely evaluate, which
is exactly the set that would fail identically mid-trace.

  D002 warning  op type has no registered JAX impl (would fail to lower)
  D003 error    eval_shape raised, or inferred shape/dtype contradicts
                the declared output var
  D004 info     attrs request a 64-bit dtype that jax_dtype narrows to
                32-bit under x64-disabled (core/dtypes.py semantics)
"""
import numpy as np

from ...core import registry
from ...core.dtypes import convert_dtype, jax_dtype
from ..engine import register_pass

__all__ = ['run']

_PROBE_B1, _PROBE_B2 = 7, 11

# executor-native op types: lowered by core/control_flow_exec.py /
# the __backward__ vjp path, not through the registry
_BACKWARD_OP = '__backward__'

# registered ops whose output extents are data-dependent (selected boxes,
# decoded paths, ...): build-time inference is skipped for them
# (infer_shape=False call sites), so the linter must not re-derive and
# compare shapes either — outputs become unknown
_DATA_DEPENDENT = {
    'multiclass_nms', 'generate_proposals', 'generate_proposal_labels',
    'generate_mask_labels', 'rpn_target_assign', 'bipartite_match',
    'beam_search', 'beam_search_decode', 'ctc_align', 'edit_distance',
    'detection_map', 'py_func',
}

_UNKNOWN = object()

_DTYPE_ATTRS = ('dtype', 'out_dtype')
_64BIT = {'int64', 'uint64', 'float64', 'complex128'}


def _native_ops():
    from ...core.control_flow_exec import NATIVE_OPS
    return NATIVE_OPS


def _struct_from_var(v, B):
    """Declared var -> probe ShapeDtypeStruct, or _UNKNOWN."""
    import jax
    if v is None or v.shape is None or v.dtype is None:
        return _UNKNOWN
    try:
        shape = tuple(B if d in (-1, None) else int(d) for d in v.shape)
        return jax.ShapeDtypeStruct(shape, jax_dtype(v.dtype))
    except Exception:
        return _UNKNOWN


def _merge_probe_shapes(s1, s2):
    """Two probe results -> declared-style shape (-1 where they differ)."""
    return tuple(int(a) if a == b else -1
                 for a, b in zip(s1.shape, s2.shape))


def _shapes_conflict(declared, inferred):
    """True when two declared-style shapes cannot describe one tensor:
    different rank, or a static dim disagreeing with a static dim."""
    if len(declared) != len(inferred):
        return True
    for d, i in zip(declared, inferred):
        if d in (-1, None) or i in (-1, None):
            continue
        if int(d) != int(i):
            return True
    return False


class _AbstractInterp(object):
    def __init__(self, ctx):
        self.ctx = ctx
        self.diags = []
        self.native = _native_ops()

    # -------------------------------------------------- per-op handlers
    def _inputs_for(self, op, env, B, block):
        idx = 0 if B == _PROBE_B1 else 1
        ins = {}
        for slot, names in op.inputs.items():
            structs = []
            for n in names:
                s = env.get(n, _UNKNOWN)
                if s is _UNKNOWN:
                    # not propagated (skipped producer / outer var):
                    # the declared shape from build-time inference is
                    # still the best — and a sound — estimate
                    s = _struct_from_var(block._find_var_recursive(n), B)
                else:
                    s = s[idx]
                if s is _UNKNOWN:
                    return None
                structs.append(s)
            ins[slot] = (structs if op.input_is_list.get(slot, False)
                         else structs[0])
        return ins

    def _mark_outputs_unknown(self, op, env):
        for n in op.output_names():
            env[n] = _UNKNOWN

    def _set_outputs_declared(self, op, env, block):
        """Seed outputs from declared shapes (native / skipped ops)."""
        for n in op.output_names():
            s1 = _struct_from_var(block._find_var_recursive(n), _PROBE_B1)
            s2 = _struct_from_var(block._find_var_recursive(n), _PROBE_B2)
            env[n] = (_UNKNOWN if s1 is _UNKNOWN or s2 is _UNKNOWN
                      else (s1, s2))

    def _backward_outputs(self, op, env, block):
        """jax.vjp semantics: each grad matches its parameter's
        shape/dtype AT THAT POINT (a later in-place clip may rebind the
        @GRAD var's declared dtype — the actual cotangent doesn't care);
        LossGrad matches the loss."""
        pnames = op.attrs.get('params', ())
        for slot, names in op.outputs.items():
            if slot == 'Grads':
                for p, gname in zip(pnames, names):
                    s = env.get(p, _UNKNOWN)
                    if s is _UNKNOWN:
                        s1 = _struct_from_var(
                            block._find_var_recursive(p), _PROBE_B1)
                        s2 = _struct_from_var(
                            block._find_var_recursive(p), _PROBE_B2)
                        s = (_UNKNOWN if s1 is _UNKNOWN or
                             s2 is _UNKNOWN else (s1, s2))
                    env[gname] = s
            elif slot == 'LossGrad' and names:
                loss = op.inputs.get('Loss', [None])[0]
                env[names[0]] = env.get(loss, _UNKNOWN) \
                    if loss is not None else _UNKNOWN
            else:
                for n in names:
                    env[n] = _UNKNOWN

    def _check_64bit_attrs(self, op, i, block):
        import jax
        if jax.config.jax_enable_x64:
            return
        for a in _DTYPE_ATTRS:
            val = op.attrs.get(a)
            if isinstance(val, str) and val in _64BIT:
                self.diags.append(self.ctx.diag(
                    'D004', 'info',
                    "attr %s='%s' narrows to %s inside the computation "
                    '(x64 is disabled; core/dtypes.jax_dtype semantics)'
                    % (a, val, jax_dtype(val).name),
                    block=block, op=op, op_index=i,
                    fixit="declare the 32-bit dtype explicitly",
                    pass_name='shape_dtype'))
                return

    # -------------------------------------------------- the block walk
    def walk_block(self, block, env):
        import jax
        program = self.ctx.program
        for i, op in enumerate(block.ops):
            sub = op.attrs.get('sub_block')
            if sub is not None:
                inner = dict(env)
                self.walk_block(program.block(sub), inner)
                self._set_outputs_declared(op, env, block)
                continue
            if op.type == _BACKWARD_OP:
                self._backward_outputs(op, env, block)
                continue
            if op.type in self.native:
                # tensor-array / control-flow results: declared shapes
                # are the only ground truth available
                self._set_outputs_declared(op, env, block)
                continue
            if not registry.has_op(op.type):
                guess = self.ctx.suggest(op.type, registry.op_names())
                self.diags.append(self.ctx.diag(
                    'D002', 'warning',
                    'op "%s" has no registered JAX impl — the program '
                    'cannot lower' % op.type,
                    block=block, op=op, op_index=i,
                    fixit=('did you mean "%s"?' % guess) if guess else
                    'register an impl via core.registry.register',
                    pass_name='shape_dtype'))
                self._mark_outputs_unknown(op, env)
                continue
            self._check_64bit_attrs(op, i, block)
            if op.type in _DATA_DEPENDENT:
                self._mark_outputs_unknown(op, env)
                continue
            impl = registry.get_op(op.type).impl
            results = []
            err = None
            for B in (_PROBE_B1, _PROBE_B2):
                ins = self._inputs_for(op, env, B, block)
                if ins is None:
                    results = None
                    break
                ictx = registry.InferCtx(op)
                try:
                    results.append(jax.eval_shape(
                        lambda kw: impl(ictx, kw, op.attrs), ins))
                except Exception as e:  # noqa: BLE001 - reported as D003
                    err = e
                    break
            if err is not None:
                in_vars = ', '.join(op.input_names()) or '<none>'
                self.diags.append(self.ctx.diag(
                    'D003', 'error',
                    'op "%s" fails shape/dtype inference on inputs [%s]: '
                    '%s' % (op.type, in_vars, err),
                    block=block, op=op, op_index=i,
                    fixit='check the input shapes/dtypes feeding this op',
                    pass_name='shape_dtype'))
                self._mark_outputs_unknown(op, env)
                continue
            if results is None:
                # some input unknown: cannot evaluate — stay conservative
                self._mark_outputs_unknown(op, env)
                continue
            self._record_outputs(op, i, block, env, results)
        return env

    def _record_outputs(self, op, i, block, env, results):
        r1, r2 = results
        for slot, names in op.outputs.items():
            o1 = r1.get(slot) if isinstance(r1, dict) else None
            o2 = r2.get(slot) if isinstance(r2, dict) else None
            if o1 is None:
                for n in names:
                    env[n] = _UNKNOWN
                continue
            l1 = o1 if isinstance(o1, (list, tuple)) else [o1]
            l2 = o2 if isinstance(o2, (list, tuple)) else [o2]
            for n, s1, s2 in zip(names, l1, l2):
                env[n] = (s1, s2)
                v = block._find_var_recursive(n)
                if v is None or v.shape is None:
                    continue
                if self.ctx.write_counts.get(n, 0) > 1:
                    # rebound var (e.g. in-place grad clip): declared
                    # metadata reflects only the LAST write — comparing
                    # an earlier write against it is meaningless.  The
                    # propagated env struct stays point-in-time correct.
                    continue
                inferred = _merge_probe_shapes(s1, s2)
                if _shapes_conflict(tuple(v.shape), inferred):
                    self.diags.append(self.ctx.diag(
                        'D003', 'error',
                        'op "%s" produces var "%s" with shape %s but the '
                        'program declares %s'
                        % (op.type, n, list(inferred), list(v.shape)),
                        block=block, op=op, op_index=i, var=n,
                        fixit='fix the producing op or the declared shape',
                        pass_name='shape_dtype'))
                    continue
                try:
                    declared_dt = jax_dtype(v.dtype)
                except Exception:
                    continue
                inferred_dt = np.dtype(s1.dtype)
                if jax_dtype(inferred_dt) != declared_dt:
                    # warning, not error: impls lean on JAX promotion, so
                    # a drifted dtype usually still RUNS — it just runs
                    # at a different precision than declared (e.g. bf16
                    # params silently updating in f32 after an f32 clip
                    # scale).  That's worth surfacing, not blocking.
                    self.diags.append(self.ctx.diag(
                        'D003', 'warning',
                        'op "%s" produces var "%s" as %s but the program '
                        'declares %s — the computation silently runs at '
                        'the promoted dtype'
                        % (op.type, n, inferred_dt.name,
                           convert_dtype(v.dtype).name),
                        block=block, op=op, op_index=i, var=n,
                        fixit='insert a cast or fix the declared dtype',
                        pass_name='shape_dtype'))


@register_pass('shape_dtype')
def run(ctx):
    interp = _AbstractInterp(ctx)
    program = ctx.program
    root = program.global_block()
    env = {}
    # seed: feeds, data vars (+@LENGTH companions), params, persistables
    from ...core.framework import Parameter
    for name, v in root.vars.items():
        if isinstance(v, Parameter) or v.persistable or \
                getattr(v, 'is_data', False) or name in ctx.feed_names:
            s1 = _struct_from_var(v, _PROBE_B1)
            s2 = _struct_from_var(v, _PROBE_B2)
            env[name] = (_UNKNOWN if s1 is _UNKNOWN or s2 is _UNKNOWN
                         else (s1, s2))
    interp.walk_block(root, env)
    return interp.diags
