"""D012/D013/D014: numerical hazards.

Heuristic dataflow checks over producer chains (the linter analog of the
runtime check_nan guard — check_nan tells you a step went non-finite,
this pass points at the op that will make it go non-finite):

  D012  log/div/exp over an input with no positivity/clipping guarantee
  D013  softmax assembled by hand (exp -> reduce_sum -> div) without
        subtracting the row max first — overflows in fp32 near x~88
  D014  a learning-rate decay schedule whose constants cannot decay
        (decay_rate >= 1 or <= 0, power <= 0, lr scaled by 0)
"""
from ..engine import register_pass

__all__ = ['run']

# producers whose output is strictly positive (safe under log / as a
# divisor)
_POSITIVE_PRODUCERS = {'exp', 'softplus'}
# producers whose output is >= 0
_NONNEG_PRODUCERS = {'exp', 'softplus', 'abs', 'square', 'relu',
                     'sigmoid', 'softmax', 'sequence_softmax',
                     'sequence_mask'}
# log over these is a known anti-pattern with a fused replacement
_LOG_OF = {'softmax': 'log_softmax', 'sequence_softmax': 'log_softmax',
           'sigmoid': 'logsigmoid'}

_DECAY_COUNTER_MARK = '_COUNTER@'


def _const_value(op):
    """fill_constant value, else None."""
    if op is not None and op.type == 'fill_constant':
        return op.attrs.get('value')
    return None


def _is_safe_positive(ctx, block, name, depth=3):
    """Conservatively True when `name` is provably > 0 (heuristic,
    bounded recursion)."""
    if depth <= 0:
        return False
    op = ctx.producer_of(block, name)
    if op is None:
        return False
    v = _const_value(op)
    if v is not None:
        try:
            return float(v) > 0.0
        except (TypeError, ValueError):
            return False
    if op.type in _POSITIVE_PRODUCERS:
        return True
    if op.type == 'clip':
        try:
            return float(op.attrs.get('min', 0.0)) > 0.0
        except (TypeError, ValueError):
            return False
    if op.type == 'scale':
        # scale*x + bias with scale >= 0, bias > 0 over a non-negative
        # base stays positive; unknown bases get the benefit of the
        # doubt — this is a linter, not a prover
        try:
            s = float(op.attrs.get('scale', 1.0))
            b = float(op.attrs.get('bias', 0.0))
        except (TypeError, ValueError):
            return False
        return s >= 0.0 and b > 0.0
    if op.type in ('elementwise_add', 'elementwise_max'):
        # x + p and max(x, p) are positive whenever either side is
        # positive and the op can only move the result up (add assumes a
        # non-negative other side — heuristic, see module docstring)
        ins = op.input_names()
        return any(_is_safe_positive(ctx, block, n, depth - 1)
                   for n in ins)
    return False


def _is_guarded(ctx, block, name):
    """True when `name` went through an explicit clip/guard."""
    op = ctx.producer_of(block, name)
    return op is not None and op.type in ({'clip', 'clip_by_norm'} |
                                          _POSITIVE_PRODUCERS)


def _softmax_pattern(ctx, block, exp_op, exp_idx):
    """Detect exp -> reduce_sum -> elementwise_div over exp's output."""
    outs = exp_op.output_names()
    if not outs:
        return False
    exp_out = outs[0]
    readers = [r for r in ctx.readers.get(exp_out, ())
               if r[0] == block.idx]
    sum_outs = {o for _, _, r_op in readers
                if r_op.type in ('reduce_sum', 'sum')
                for o in r_op.output_names()}
    if not sum_outs:
        return False
    for _, _, r_op in readers:
        if r_op.type == 'elementwise_div' and \
                set(r_op.input('Y')) & sum_outs:
            return True
    return False


def _has_max_subtraction(ctx, block, exp_op):
    """exp's input produced by elementwise_sub whose Y is a reduce_max."""
    ins = exp_op.input_names()
    if not ins:
        return False
    prod = ctx.producer_of(block, ins[0])
    if prod is None or prod.type != 'elementwise_sub':
        return False
    y = prod.input('Y')
    if not y:
        return False
    y_prod = ctx.producer_of(block, y[0])
    return y_prod is not None and y_prod.type == 'reduce_max'


def _lr_taint(ctx):
    """Var names derived from an autoincreased decay/step counter."""
    tainted = set()
    for block in ctx.program.blocks:
        for name in block.vars:
            if name.endswith('@') and _DECAY_COUNTER_MARK in name:
                tainted.add(name)
    if not tainted:
        return tainted
    for block in ctx.program.blocks:
        for op in block.ops:
            if set(op.input_names()) & tainted:
                tainted |= set(op.output_names())
    return tainted


@register_pass('numeric_hazard')
def run(ctx):
    diags = []
    tainted = _lr_taint(ctx)
    for block in ctx.program.blocks:
        for i, op in enumerate(block.ops):
            if op.type == 'log':
                ins = op.input_names()
                prod = ctx.producer_of(block, ins[0]) if ins else None
                if prod is not None and prod.type in _LOG_OF:
                    diags.append(ctx.diag(
                        'D012', 'warning',
                        'log(%s(x)) underflows to -inf when the inner '
                        'probability reaches 0' % prod.type,
                        block=block, op=op, op_index=i,
                        var=ins[0],
                        fixit='use the fused %s op' % _LOG_OF[prod.type],
                        pass_name='numeric_hazard'))
                elif not ins or not (
                        _is_guarded(ctx, block, ins[0]) or
                        _is_safe_positive(ctx, block, ins[0])):
                    diags.append(ctx.diag(
                        'D012', 'warning',
                        'log over an unclipped input: log(0) = -inf and '
                        'log(x<0) = nan poison the whole step',
                        block=block, op=op, op_index=i,
                        var=ins[0] if ins else None,
                        fixit='clip the input to [eps, inf) first '
                              '(layers.clip)',
                        pass_name='numeric_hazard'))
            elif op.type == 'elementwise_div':
                y = op.input('Y')
                if y and not _is_safe_positive(ctx, block, y[0]):
                    yv = block._find_var_recursive(y[0])
                    if yv is not None and getattr(yv, 'is_data', False):
                        why = 'a raw feed'
                    elif ctx.producer_of(block, y[0]) is None:
                        why = 'an unguarded value'
                    else:
                        why = ('produced by "%s"' %
                               ctx.producer_of(block, y[0]).type)
                    diags.append(ctx.diag(
                        'D012', 'warning',
                        'division by %s with no positivity guarantee: a '
                        'zero divisor yields inf/nan' % why,
                        block=block, op=op, op_index=i, var=y[0],
                        fixit='clip the divisor away from zero or add '
                              'an epsilon',
                        pass_name='numeric_hazard'))
            elif op.type == 'exp':
                if _softmax_pattern(ctx, block, op, i):
                    if not _has_max_subtraction(ctx, block, op):
                        diags.append(ctx.diag(
                            'D013', 'warning',
                            'softmax assembled by hand without max-'
                            'subtraction: exp overflows fp32 once logits '
                            'exceed ~88',
                            block=block, op=op, op_index=i,
                            fixit='use layers.softmax, or subtract '
                                  'reduce_max(x) before exp',
                            pass_name='numeric_hazard'))
                else:
                    ins = op.input_names()
                    iv = (block._find_var_recursive(ins[0]) if ins
                          else None)
                    if iv is not None and getattr(iv, 'is_data', False):
                        diags.append(ctx.diag(
                            'D012', 'warning',
                            'exp over a raw feed: unbounded inputs '
                            'overflow fp32 past ~88',
                            block=block, op=op, op_index=i, var=ins[0],
                            fixit='clip the exponent input',
                            pass_name='numeric_hazard'))
            # ---- D014: degenerate decay constants --------------------
            if not tainted:
                continue
            if op.type == 'elementwise_pow':
                x, y = op.input('X'), op.input('Y')
                if x and y and y[0] in tainted:
                    base = _const_value(ctx.producer_of(block, x[0]))
                    if base is not None and \
                            (float(base) >= 1.0 or float(base) <= 0.0):
                        diags.append(ctx.diag(
                            'D014', 'warning',
                            'decay base %g raised to the step counter '
                            '%s' % (float(base),
                                    'never decays (>= 1)'
                                    if float(base) >= 1.0 else
                                    'is non-positive (nan/0 schedule)'),
                            block=block, op=op, op_index=i, var=x[0],
                            fixit='use a decay_rate in (0, 1)',
                            pass_name='numeric_hazard'))
                elif x and y and x[0] in tainted:
                    # negative powers (noam's step**-0.5) DO decay; only
                    # power == 0 degenerates to a constant schedule
                    p = _const_value(ctx.producer_of(block, y[0]))
                    if p is not None and float(p) == 0.0:
                        diags.append(ctx.diag(
                            'D014', 'warning',
                            'decay power 0 makes the schedule a '
                            'constant 1',
                            block=block, op=op, op_index=i,
                            fixit='use a non-zero power',
                            pass_name='numeric_hazard'))
            elif op.type == 'scale' and \
                    set(op.input_names()) & tainted:
                try:
                    s = float(op.attrs.get('scale', 1.0))
                    b = float(op.attrs.get('bias', 0.0))
                except (TypeError, ValueError):
                    continue
                if s == 0.0 and b == 0.0:
                    diags.append(ctx.diag(
                        'D014', 'warning',
                        'learning-rate schedule multiplied by 0: the '
                        'effective LR is constant 0',
                        block=block, op=op, op_index=i,
                        fixit='use a non-zero decay factor',
                        pass_name='numeric_hazard'))
    return diags
