"""Self-registering lint passes, in execution order.

Registration order is severity-logical: def-use first (everything else
assumes a well-formed graph), the abstract interpreter second (later
passes may consult its findings), then the graph-hygiene and hazard
passes.
"""
from . import defuse  # noqa: F401
from . import shapes  # noqa: F401
from . import liveness  # noqa: F401
from . import aliasing  # noqa: F401
from . import retrace  # noqa: F401
from . import numeric  # noqa: F401
from . import emit_coverage  # noqa: F401
from . import kernelgen_coverage  # noqa: F401
from . import sharding  # noqa: F401
from . import memplan  # noqa: F401
from . import donation  # noqa: F401
