"""D015: ops the direct Program→jaxpr emitter cannot lower.

The emitter (core/emit) falls back to classic traced lowering — per
PROGRAM, not per op — the moment its coverage walk meets one op it has
no capability for, so a single exotic op silently forfeits the whole
program's trace-free cold start (warn-once + ``emitter.fallbacks``
counters at run time).  This pass reports the same gap statically, with
op locations, using the exact capability test the engine applies
(``emit.op_capability``), including fused sub-programs whose sub-ops
must each be replayable.

Severity is info: falling back is correct, just slow — ci_smoke's
``--all-builtin`` gate holds the zoo to zero D015s so builtin coverage
regressions surface in CI rather than as cold-start regressions.
"""
from ..engine import register_pass

__all__ = ['run']


@register_pass('emit_coverage')
def run(ctx):
    from ...core.emit import emitter
    diags = []
    seen = set()
    for block in ctx.program.blocks:
        for i, op in enumerate(block.ops):
            gaps = []
            ok, why = emitter.op_capability(op.type)
            if not ok:
                gaps.append((op.type, why))
            elif op.type == 'fused_elementwise':
                for sub in op.attrs.get('sub_ops', ()):
                    sok, swhy = emitter.op_capability(sub['type'])
                    if not sok:
                        gaps.append((sub['type'],
                                     swhy + ' (fused sub-op)'))
            for gap_type, gap_why in gaps:
                if gap_type in seen:
                    continue
                seen.add(gap_type)
                diags.append(ctx.diag(
                    'D015', 'info',
                    'op "%s" is not emit-capable (%s): the direct '
                    'emitter (PT_EMIT=1) falls back to traced lowering '
                    'for the WHOLE program, forfeiting its trace-free '
                    'cold start' % (gap_type, gap_why),
                    block=block, op=op, op_index=i,
                    fixit='register the op (registry.register_op) or an '
                          'emit rule (registry.register_emit), or set '
                          'PT_EMIT=0 to silence the runtime warning',
                    pass_name='emit_coverage'))
    return diags
