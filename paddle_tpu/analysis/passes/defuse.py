"""D001: def-use ordering (folded in from core/validation.py).

Same walk the executor has always run on lowering-cache miss, upgraded
to structured diagnostics with the full block path and a did-you-mean
suggestion (nearest visible var name by edit distance).  Unlike the old
validate_def_use this reports EVERY violation, not just the first —
core/validation.py keeps its first-error ValueError contract on top.
"""
from ...core.framework import Parameter
from ..engine import register_pass

__all__ = ['run', 'initially_defined']


def initially_defined(program, feed_names):
    defined = set(feed_names)
    root = program.global_block()
    for name, v in root.vars.items():
        if isinstance(v, Parameter) or v.persistable or \
                getattr(v, 'is_data', False):
            defined.add(name)
            if getattr(v, 'lod_level', 0) > 0:
                defined.add(name + '@LENGTH')
    return defined


@register_pass('def_use')
def run(ctx):
    program = ctx.program
    diags = []

    def walk(block, defined):
        for i, op in enumerate(block.ops):
            for slot, names in op.inputs.items():
                for n in names:
                    if n is None or n in defined:
                        continue
                    v = block._find_var_recursive(n)
                    if v is not None and (isinstance(v, Parameter) or
                                          v.persistable or
                                          getattr(v, 'is_data', False) or
                                          # arrays allocate on first
                                          # write; the runtime raises its
                                          # own read-before-write error
                                          getattr(v, 'is_tensor_array',
                                                  False)):
                        defined.add(n)
                        continue
                    guess = ctx.suggest(n, defined | ctx.visible_names(block))
                    diags.append(ctx.diag(
                        'D001', 'error',
                        'op "%s" reads var "%s" before any prior op, feed, '
                        'parameter or persistable defines it. If this var '
                        'is produced later in the program, reorder the '
                        'ops; if it should be fed, add it to the feed '
                        'list.' % (op.type, n),
                        block=block, op=op, op_index=i, var=n,
                        fixit=('did you mean "%s"?' % guess) if guess
                        else None, pass_name='def_use'))
                    # treat as defined from here on: one root cause, one
                    # diagnostic — not a cascade per downstream reader
                    defined.add(n)
            sub = op.attrs.get('sub_block')
            if sub is not None:
                inner = set(defined)
                if op.type == 'recurrent':
                    inner |= set(op.attrs.get('step_vars', ()))
                    inner |= set(op.attrs.get('mem_vars', ()))
                # body-LOCAL temps do NOT survive the loop: the lowering
                # writes back only carries (vars that pre-existed), so
                # sub-block definitions are deliberately not merged — a
                # later read of a body temp is itself a def-use violation
                walk(program.block(sub), inner)
            defined.update(n for n in op.output_names() if n)
        return defined

    walk(program.global_block(),
         initially_defined(program, ctx.feed_names))
    return diags
