"""D016: ops the Pallas codegen tier cannot lower — or never saw.

The kernelgen tier (ops/kernelgen) compiles each ``fused_elementwise``
sub-program into generated Pallas kernels; a sub-op with no
``KERNEL_RULES`` entry makes the WHOLE group fall back loudly to the
reference replay at run time (``kernelgen.fallbacks`` counter, warn-once,
``PT_STRICT_KERNELS=1`` raises).  This pass reports the same gap
statically, per fused op, with sub-op names — the static face of
``kernelgen.unsupported_sub_ops``.

It also flags the dual failure: a KERNEL_TIER op (softmax / layer_norm /
flash_attention — ops with dedicated generated kernels) that the
rewriter's fuse pass FAILED to present as a fused group.  Since the fuse
pass wraps tier ops even as singleton runs, a bare tier op in an
otherwise-fused program means something blocked the escape — the fixit
names the blocking reason (sub_block, non-serializable attrs, or a
control-flow-pinned output).  Raw never-optimized programs (no
fused_elementwise anywhere) are skipped: there is no evidence the
rewriter ran at all.

Severity is info: the replay fallback is bitwise-correct, just unfused —
ci_smoke's strict-kernelgen zoo gate holds the bench programs to zero
fallbacks so coverage regressions surface in CI rather than as perf
regressions.
"""
from ..engine import register_pass

__all__ = ['run']


def _bare_tier_reason(op):
    """(why, fixit) for a KERNEL_TIER op the fuse pass left bare, by
    re-checking the pass's own escape conditions."""
    from ...core.passes import fuse as _fuse
    if op.attrs.get('sub_block') is not None:
        return ('it carries a sub_block (control-flow ops never fuse)',
                'hoist the op out of the control-flow body so '
                'core/passes/fuse.py can wrap it')
    if _fuse._plain_attrs(op.attrs) is None:
        return ('its attrs are not JSON-serializable, so '
                'core/passes/fuse.py could not record the sub-program',
                'make the op attrs plain str/int/float/bool/list values')
    return ('its output is control-flow-pinned (or the fuse pass was '
            'skipped via PT_OPT_SKIP)',
            'check walker.control_flow_pinned consumers of its outputs '
            'and the PT_OPT_SKIP setting')


@register_pass('kernelgen_coverage')
def run(ctx):
    from ...core.passes import fuse as _fuse
    from ...ops import kernelgen
    diags = []
    seen = set()
    seen_bare = set()
    fused_present = any(op.type == 'fused_elementwise'
                        for block in ctx.program.blocks
                        for op in block.ops)
    for block in ctx.program.blocks:
        for i, op in enumerate(block.ops):
            if op.type in _fuse.KERNEL_TIER_OPS and fused_present:
                if op.type in seen_bare:
                    continue
                seen_bare.add(op.type)
                why, fixit = _bare_tier_reason(op)
                diags.append(ctx.diag(
                    'D016', 'info',
                    'kernel-tier op "%s" was not presented to the '
                    'kernelgen tier as a fused group: %s — it runs '
                    'through its plain registered impl instead of a '
                    'generated kernel' % (op.type, why),
                    block=block, op=op, op_index=i, fixit=fixit,
                    pass_name='kernelgen_coverage'))
                continue
            if op.type != 'fused_elementwise':
                continue
            for sub_type in kernelgen.unsupported_sub_ops(op.attrs):
                if sub_type in seen:
                    continue
                seen.add(sub_type)
                diags.append(ctx.diag(
                    'D016', 'info',
                    'fused sub-op "%s" has no KERNEL_RULES entry: this '
                    'fused_elementwise group falls back from its '
                    'generated Pallas kernel (PT_KERNELGEN=1) to the '
                    'reference replay' % sub_type,
                    block=block, op=op, op_index=i,
                    fixit='add a KERNEL_RULES entry '
                          '(ops/kernelgen/rules.py), or set '
                          'PT_KERNELGEN=0 to silence the runtime '
                          'warning',
                    pass_name='kernelgen_coverage'))
    return diags
