"""D016: fused sub-ops the Pallas codegen tier cannot lower.

The kernelgen tier (ops/kernelgen) compiles each ``fused_elementwise``
sub-program into generated Pallas kernels; a sub-op with no
``KERNEL_RULES`` entry makes the WHOLE group fall back loudly to the
reference replay at run time (``kernelgen.fallbacks`` counter, warn-once,
``PT_STRICT_KERNELS=1`` raises).  This pass reports the same gap
statically, per fused op, with sub-op names — the static face of
``kernelgen.unsupported_sub_ops``.

Severity is info: the replay fallback is bitwise-correct, just unfused —
ci_smoke's strict-kernelgen zoo gate holds the bench programs to zero
fallbacks so coverage regressions surface in CI rather than as perf
regressions.
"""
from ..engine import register_pass

__all__ = ['run']


@register_pass('kernelgen_coverage')
def run(ctx):
    from ...ops import kernelgen
    diags = []
    seen = set()
    for block in ctx.program.blocks:
        for i, op in enumerate(block.ops):
            if op.type != 'fused_elementwise':
                continue
            for sub_type in kernelgen.unsupported_sub_ops(op.attrs):
                if sub_type in seen:
                    continue
                seen.add(sub_type)
                diags.append(ctx.diag(
                    'D016', 'info',
                    'fused sub-op "%s" has no KERNEL_RULES entry: this '
                    'fused_elementwise group falls back from its '
                    'generated Pallas kernel (PT_KERNELGEN=1) to the '
                    'reference replay' % sub_type,
                    block=block, op=op, op_index=i,
                    fixit='add a KERNEL_RULES entry '
                          '(ops/kernelgen/rules.py), or set '
                          'PT_KERNELGEN=0 to silence the runtime '
                          'warning',
                    pass_name='kernelgen_coverage'))
    return diags
