"""D017/D018/D019: sharding propagation abstract interpretation.

The executor shards launches via `Program._sharding` (in_shardings) but
until now nothing checked the specs statically: a conflict surfaced as a
cryptic GSPMD error mid-trace, an implicit reshard surfaced as nothing
at all — just silently moved bytes every step.  This pass walks the
whole program (incl. `__backward__` and control-flow sub-blocks, the
same skeleton as the D003 interpreter) propagating one sharding spec per
var name, seeded from the first-class `Variable.sharding` annotations:

  D017 error    two producers force incompatible specs on one var, or a
                declared spec cannot describe the var (rank overflow)
  D018 warning  an op consumes layouts that disagree (between its own
                inputs, or dataflow-delivered vs declared): XLA inserts
                an implicit reshard there — reported with the estimated
                resharded bytes per the all-to-all cost model of the
                memory-efficient array-redistribution paper
                (arxiv 2112.01075), the seed data for a future
                collective-inserting rewrite pass
  D019 error    a spec (or an op's `axis_name` attr) references a mesh
                axis the declared mesh (`Program.set_mesh_axes`) lacks

D019 stays quiet when no mesh is declared — annotating specs without
declaring a mesh is the common single-host authoring state.
"""
from ...core.sharding import (normalize_spec, spec_axes, spec_divisor,
                              spec_from_jsonable)
from ..engine import register_pass

__all__ = ['run']

# explicit collectives inserted by core/passes/shard.py — their dst_spec
# attr IS the output layout, and they never trip D018 themselves: they
# are what a materialized D018 looks like
_COLLECTIVE = {'reshard', 'all_gather', 'grad_allreduce'}

# ops whose (first) output keeps the layout of their X/Y inputs
_SAME_LAYOUT = {
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min',
    'elementwise_pow', 'relu', 'relu6', 'gelu', 'tanh', 'sigmoid', 'exp',
    'log', 'sqrt', 'square', 'abs', 'scale', 'cast', 'dropout', 'assign',
    'clip', 'softmax', 'rms_norm', 'rope',
}

# contraction ops: out layout = X's leading entries + W/Y's last entry
_MATMUL = {'mul', 'matmul', 'fc'}

# attrs that name mesh axes directly (collective ops, ring attention)
_AXIS_NAME_ATTRS = ('axis_name', 'mesh_axis')

_BACKWARD_OP = '__backward__'


def _trim(spec):
    """Strip redundant trailing None entries (PartitionSpec semantics:
    unmentioned trailing dims are replicated)."""
    spec = tuple(spec or ())
    while spec and spec[-1] is None:
        spec = spec[:-1]
    return spec


def _eqspec(a, b):
    """Layout equality up to trailing replication — (None,) and
    (None, None) describe the same placement."""
    return _trim(a) == _trim(b)


def _declared_spec(block, name):
    v = block._find_var_recursive(name)
    return v._sharding_spec if v is not None else None


def _var_rank(block, name):
    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return None
    return len(v.shape)


def _var_bytes(block, name, spec, mesh):
    """Per-device bytes of one shard of `name` under `spec` (batch dims
    count as 1 — a lower bound, which is the honest direction for a
    reshard-cost estimate)."""
    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return 0
    n = 1
    for d in v.shape:
        n *= 1 if d in (-1, None) else int(d)
    try:
        itemsize = v.np_dtype.itemsize
    except Exception:
        itemsize = 4
    return (n * itemsize) // spec_divisor(spec, mesh)


class _ShardingInterp(object):
    def __init__(self, ctx):
        self.ctx = ctx
        self.diags = []
        self.mesh = ctx.program.mesh_axes()
        # var name -> (spec, block, op_index, op) of the write that last
        # forced a spec onto it (for the D017 two-producer report)
        self.forced = {}
        self._d019_seen = set()

    # ------------------------------------------------------------ D019
    def check_axes(self, spec, block, op=None, op_index=None, var=None,
                   what='sharding spec'):
        if self.mesh is None or spec is None:
            return
        missing = [a for a in sorted(spec_axes(spec)) if a not in self.mesh]
        for a in missing:
            key = (a, var, op_index, block.idx if block else None)
            if key in self._d019_seen:
                continue
            self._d019_seen.add(key)
            guess = self.ctx.suggest(a, self.mesh.keys())
            self.diags.append(self.ctx.diag(
                'D019', 'error',
                '%s references mesh axis "%s" but the declared mesh only '
                'has axes %s' % (what, a, sorted(self.mesh.keys())),
                block=block, op=op, op_index=op_index, var=var,
                fixit=('did you mean "%s"?' % guess) if guess else
                'declare the axis via Program.set_mesh_axes',
                pass_name='sharding'))

    # --------------------------------------------------------- merging
    def _reshard(self, op, i, block, name, have, want, why):
        bytes_ = _var_bytes(block, name, have, self.mesh)
        self.diags.append(self.ctx.diag(
            'D018', 'warning',
            'implicit reshard of "%s" at op "%s": dataflow delivers %s '
            'but %s %s — XLA moves ~%d bytes/device here every step '
            '(arxiv 2112.01075 cost model)'
            % (name, op.type, list(have), why, list(want), bytes_),
            block=block, op=op, op_index=i, var=name,
            fixit='annotate matching specs on both sides, or insert an '
                  'explicit reshard/collective once outside the hot loop',
            pass_name='sharding'))

    def _record_write(self, op, i, block, name, spec):
        """Bind `spec` (may be None) as what this write forces on `name`;
        conflicting non-None forcings from two producers are D017."""
        prev = self.forced.get(name)
        if spec is not None and prev is not None and \
                prev[0] is not None and not _eqspec(prev[0], spec):
            p_spec, p_block, p_i, p_op = prev
            self.diags.append(self.ctx.diag(
                'D017', 'error',
                'sharding conflict on "%s": op#%d "%s" forces %s but '
                'op#%d "%s" forces %s — one buffer cannot hold both '
                'layouts' % (name, p_i, p_op.type, list(p_spec), i,
                             op.type, list(spec)),
                block=block, op=op, op_index=i, var=name,
                fixit='route one producer through a fresh variable or '
                      'align the two specs',
                pass_name='sharding'))
        if spec is not None or prev is None:
            self.forced[name] = (spec, block, i, op)

    def _finish_outputs(self, op, i, block, env, out_specs):
        """Apply declared-spec precedence + conflict checks per output."""
        for name, spec in out_specs.items():
            declared = _declared_spec(block, name)
            rank = _var_rank(block, name)
            if declared is not None and rank is not None and \
                    len(declared) > rank:
                self.diags.append(self.ctx.diag(
                    'D017', 'error',
                    'declared sharding %s of "%s" has %d entries but the '
                    'var is rank %d — the spec cannot describe this '
                    'tensor' % (list(declared), name, len(declared),
                                rank),
                    block=block, op=op, op_index=i, var=name,
                    fixit='shorten the spec to one entry per dimension',
                    pass_name='sharding'))
            if declared is not None:
                if spec is not None and not _eqspec(spec, declared):
                    # dataflow delivers one layout, the annotation
                    # demands another: XLA reshards at the producer
                    self._reshard(op, i, block, name, spec, declared,
                                  'the annotation declares')
                spec = declared
            self._record_write(op, i, block, name, spec)
            env[name] = spec

    # -------------------------------------------------------- the walk
    def walk_block(self, block, env):
        program = self.ctx.program
        for i, op in enumerate(block.ops):
            for a in _AXIS_NAME_ATTRS:
                val = op.attrs.get(a)
                if isinstance(val, str) and val:
                    self.check_axes((val,), block, op=op, op_index=i,
                                    what='attr %s="%s"' % (a, val))
            sub = op.attrs.get('sub_block')
            if sub is not None:
                inner = dict(env)
                self.walk_block(program.block(sub), inner)
                self._finish_outputs(op, i, block, env,
                                     {n: None for n in op.output_names()})
                continue
            if op.type == _BACKWARD_OP:
                self._backward_outputs(op, i, block, env)
                continue
            out_specs = self._propagate(op, i, block, env)
            self._finish_outputs(op, i, block, env, out_specs)
        return env

    def _in_spec(self, block, env, name):
        if name in env:
            return env[name]
        return _declared_spec(block, name)

    def _propagate(self, op, i, block, env):
        """Op-type transfer function: input specs -> {out name: spec}."""
        outs = {n: None for n in op.output_names()}
        first_out = (op.outputs.get('Out') or [None])[0]
        if op.type in _COLLECTIVE:
            for a in ('src_spec', 'dst_spec'):
                raw = op.attrs.get(a)
                if raw is not None:
                    try:
                        self.check_axes(normalize_spec(
                            spec_from_jsonable(raw)), block, op=op,
                            op_index=i, what='attr %s' % a)
                    except Exception:
                        pass
            if first_out is not None:
                try:
                    outs[first_out] = normalize_spec(
                        spec_from_jsonable(op.attrs.get('dst_spec')))
                except Exception:
                    outs[first_out] = None
            return outs
        if op.type in _SAME_LAYOUT:
            merged = None
            merged_from = None
            for slot in ('X', 'Y'):
                for n in op.inputs.get(slot, ()):
                    s = self._in_spec(block, env, n)
                    if s is None:
                        continue
                    if merged is None:
                        merged, merged_from = s, n
                    elif not _eqspec(s, merged):
                        # two inputs arrive in different layouts: the
                        # later (usually smaller) one gets resharded
                        self._reshard(op, i, block, n, s, merged,
                                      '"%s" arrives as' % merged_from)
            if first_out is not None:
                outs[first_out] = merged
        elif op.type in _MATMUL:
            xs = [self._in_spec(block, env, n)
                  for n in op.inputs.get('X', ())]
            ws = [self._in_spec(block, env, n)
                  for n in (op.inputs.get('Y', ()) or
                            op.inputs.get('W', ()))]
            x = xs[0] if xs else None
            w = ws[0] if ws else None
            if x is not None and w is not None and len(x) >= 1 and \
                    len(w) >= 1 and x[-1] is not None and \
                    w[0] is not None and x[-1] != w[0]:
                wname = (op.inputs.get('Y', ()) or
                         op.inputs.get('W', ()))[0]
                self._reshard(op, i, block, wname, w,
                              (x[-1],) + tuple(w[1:]),
                              'the contraction against "%s" needs'
                              % op.inputs.get('X', ['?'])[0])
            if first_out is not None:
                if x is not None and len(x) >= 1:
                    tail = (w[-1],) if w is not None and len(w) >= 1 \
                        else (None,)
                    outs[first_out] = tuple(x[:-1]) + tail
                elif w is not None:
                    outs[first_out] = None
        # transpose permutes entries; everything else (reshape, reduce,
        # gather, concat, unknown ops) degrades to None — the pass only
        # reports what it can genuinely track
        elif op.type in ('transpose', 'transpose2'):
            perm = op.attrs.get('axis') or op.attrs.get('perm')
            src = (op.inputs.get('X') or [None])[0]
            s = self._in_spec(block, env, src) if src else None
            if s is not None and perm and len(perm) == len(s) and \
                    first_out is not None:
                outs[first_out] = tuple(s[p] for p in perm)
        return outs

    def _backward_outputs(self, op, i, block, env):
        """jax.vjp: each grad cotangent carries its parameter's layout."""
        pnames = op.attrs.get('params', ())
        outs = {}
        for slot, names in op.outputs.items():
            if slot == 'Grads':
                for p, gname in zip(pnames, names):
                    outs[gname] = self._in_spec(block, env, p)
            else:
                for n in names:
                    outs[n] = None
        self._finish_outputs(op, i, block, env, outs)


@register_pass('sharding')
def run(ctx):
    interp = _ShardingInterp(ctx)
    program = ctx.program
    root = program.global_block()
    env = {}
    # seed every declared annotation (any block) + legacy side-table
    # entries, and vet their axes against the declared mesh up front
    for b in program.blocks:
        for name, v in b.vars.items():
            spec = v._sharding_spec
            if spec is None and name in program._sharding:
                try:
                    spec = normalize_spec(program._sharding[name])
                except Exception:
                    spec = None
            if spec is None:
                continue
            if b.idx == 0:
                env[name] = spec
            interp.check_axes(spec, b, var=name)
            rank = _var_rank(b, name)
            if rank is not None and len(spec) > rank and \
                    v.op is None:
                # producer-less vars (feeds/params) get the rank check
                # here; produced vars get it at their producer for a
                # better anchor
                interp.diags.append(ctx.diag(
                    'D017', 'error',
                    'declared sharding %s of "%s" has %d entries but the '
                    'var is rank %d' % (list(spec), name, len(spec),
                                        rank),
                    block=b, var=name,
                    fixit='shorten the spec to one entry per dimension',
                    pass_name='sharding'))
    interp.walk_block(root, env)
    return interp.diags
