"""D007/D008/D009: donation & aliasing conflicts.

The executor donates the parameter dict to the lowered executable
(donate_argnums) and, under run_steps, threads it as the lax.scan carry
— so in-block aliasing patterns that are harmless in an op-by-op
interpreter become real hazards here:

  D007 warning  a Parameter is READ by an op after an earlier op in the
                same block wrote it back: the reader sees the updated
                value this step, and under a K-step scan the stale/fresh
                split silently changes with K
  D008 warning  a feed name shadows a parameter/persistable: the feed
                wins, the scope value is ignored, and the writeback then
                clobbers the scope entry
  D009 warning  the same persistable is written by two ops in one block:
                last-write-wins silently (the reference raises on this)
"""
from ...core.framework import Parameter
from ..engine import register_pass

__all__ = ['run']


def _is_persistable(block, name):
    v = block._find_var_recursive(name)
    return v is not None and (v.persistable or isinstance(v, Parameter))


def _is_parameter(block, name):
    return isinstance(block._find_var_recursive(name), Parameter)


@register_pass('aliasing')
def run(ctx):
    diags = []
    program = ctx.program
    root = program.global_block()

    # ---- D008: feeds shadowing persistables --------------------------
    for n in ctx.feed_names:
        if _is_persistable(root, n):
            kind = ('parameter' if _is_parameter(root, n)
                    else 'persistable')
            diags.append(ctx.diag(
                'D008', 'warning',
                'feed "%s" shadows a %s: the fed value replaces the '
                'scope value for this launch, and any writeback then '
                'overwrites the scope entry' % (n, kind),
                block=root, var=n,
                fixit='rename the feed, or drop the var from the feed '
                      'list and assign it in the scope instead',
                pass_name='aliasing'))

    # ---- per-block write tracking for D007 / D009 --------------------
    for block in program.blocks:
        first_write = {}   # persistable name -> (op_index, op)
        for i, op in enumerate(block.ops):
            # D007: Parameter read after an in-block writeback.
            # The same op reading AND writing a param (sgd's Param ->
            # ParamOut) is the normal update idiom, not a hazard.
            for n in op.input_names():
                if n in first_write and first_write[n][0] < i and \
                        _is_parameter(block, n):
                    w_i, w_op = first_write[n]
                    diags.append(ctx.diag(
                        'D007', 'warning',
                        'parameter "%s" is read by op "%s" after op#%d '
                        '"%s" already wrote it back — the read sees the '
                        'updated value; donated as a scan carry this '
                        'read/writeback interleaving changes with '
                        'steps=K' % (n, op.type, w_i, w_op.type),
                        block=block, op=op, op_index=i, var=n,
                        fixit='read the parameter before the update op, '
                              'or snapshot it into a temporary first',
                        pass_name='aliasing'))
            for n in op.output_names():
                if not _is_persistable(block, n):
                    continue
                if n in first_write and first_write[n][1] is not op:
                    w_i, w_op = first_write[n]
                    diags.append(ctx.diag(
                        'D009', 'warning',
                        'persistable "%s" is written by both op#%d "%s" '
                        'and op#%d "%s" in one block — last write wins '
                        'silently' % (n, w_i, w_op.type, i, op.type),
                        block=block, op=op, op_index=i, var=n,
                        fixit='drop one of the writes, or route the '
                              'second through a fresh variable',
                        pass_name='aliasing'))
                else:
                    first_write.setdefault(n, (i, op))
    return diags
