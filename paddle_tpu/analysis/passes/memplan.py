"""D020: static per-device HBM planning.

Folds every statically-knowable byte a program will pin into one
per-device footprint — params, optimizer accumulators (persistable
non-parameter state), the liveness peak of forward/backward activations
(reusing the walker's read-attribution machinery), and the serving
KV-cache pool declared via `Program.set_kv_plan` (PR-18's
`CacheConfig.bytes()` arithmetic, paged/quantized aware) — and emits
D020 when it exceeds the per-device limit, BEFORE any tracing happens.
The Julia→TPU full-compilation work (arxiv 1810.09868) is the shape
argument here: whole-program memory knowledge belongs in the IR, not
reconstructed from an OOM at lowering time.

Per-var bytes divide by the sharding divisor (product of declared mesh
sizes over the var's spec axes), so a model-parallel annotation shrinks
the plan the way it shrinks the real footprint.  Batch dims (-1) count
via the `batch` knob (default 1 — a lower bound, the honest direction).

The limit comes from `Program.set_device_limit(bytes)`; with none
declared the pass asks the runtime (`memory_stats()['bytes_limit']`,
absent on CPU) and stays quiet when neither exists.

`plan_memory()` is also a public API: `pt_lint --memplan` renders its
table, JSON consumers get `MEMPLAN_JSON_KEYS`-shaped dicts.
"""
from ...core.framework import Parameter
from ...core.passes.walker import block_last_reads, persistable_names
from ...core.sharding import spec_divisor
from ..engine import register_pass

__all__ = ['run', 'plan_memory', 'MemPlan', 'MEMPLAN_JSON_KEYS']

MEMPLAN_JSON_KEYS = ('params_bytes', 'opt_state_bytes',
                     'activation_peak_bytes', 'kv_pool_bytes',
                     'total_bytes', 'limit_bytes', 'limit_source',
                     'peak_op', 'top', 'mesh_axes', 'batch')

_UNSET = object()


def _zero_specs(program):
    """{name: spec} the shard pass's ZeRO tier WILL apply when it runs
    on this program — so the plan divides persistable bytes by the same
    divisor the executed partitioning does.  Empty when the pass is off
    (PT_SHARD/PT_OPT/skip), no mesh is declared, or the specs are
    already applied (optimized programs: plan_zero_specs skips vars
    already split over the data axis, so no double division)."""
    try:
        from ...core.passes import shard
        if not shard.active_for(program):
            return {}
        return shard.plan_zero_specs(program)[0]
    except Exception:
        return {}


def _var_bytes(v, mesh, batch, spec=_UNSET):
    if v is None or v.shape is None:
        return 0
    n = 1
    for d in v.shape:
        n *= batch if d in (-1, None) else int(d)
    try:
        itemsize = v.np_dtype.itemsize
    except Exception:
        itemsize = 4
    if spec is _UNSET:
        spec = v._sharding_spec
    return (n * itemsize) // spec_divisor(spec, mesh)


def _fmt_bytes(b):
    for unit in ('B', 'KiB', 'MiB', 'GiB'):
        if abs(b) < 1024 or unit == 'GiB':
            return ('%d %s' % (b, unit)) if unit == 'B' else \
                ('%.2f %s' % (b, unit))
        b /= 1024.0
    return '%d B' % b


class MemPlan(object):
    """One program's static per-device memory plan."""

    def __init__(self, params_bytes, opt_state_bytes,
                 activation_peak_bytes, kv_pool_bytes, limit_bytes,
                 limit_source, peak_op, top, mesh_axes, batch):
        self.params_bytes = params_bytes
        self.opt_state_bytes = opt_state_bytes
        self.activation_peak_bytes = activation_peak_bytes
        self.kv_pool_bytes = kv_pool_bytes
        self.limit_bytes = limit_bytes
        self.limit_source = limit_source
        self.peak_op = peak_op        # (op_index, op_type) or None
        self.top = top                # [(name, kind, bytes)] largest first
        self.mesh_axes = mesh_axes
        self.batch = batch

    @property
    def total_bytes(self):
        return (self.params_bytes + self.opt_state_bytes +
                self.activation_peak_bytes + self.kv_pool_bytes)

    def over_limit(self):
        return self.limit_bytes is not None and \
            self.total_bytes > self.limit_bytes

    def to_dict(self):
        return {'params_bytes': self.params_bytes,
                'opt_state_bytes': self.opt_state_bytes,
                'activation_peak_bytes': self.activation_peak_bytes,
                'kv_pool_bytes': self.kv_pool_bytes,
                'total_bytes': self.total_bytes,
                'limit_bytes': self.limit_bytes,
                'limit_source': self.limit_source,
                'peak_op': (list(self.peak_op) if self.peak_op else None),
                'top': [[n, k, b] for n, k, b in self.top],
                'mesh_axes': (dict(self.mesh_axes)
                              if self.mesh_axes else None),
                'batch': self.batch}

    def render_table(self):
        rows = [('params', self.params_bytes),
                ('optimizer state', self.opt_state_bytes),
                ('activation peak', self.activation_peak_bytes),
                ('kv pool', self.kv_pool_bytes),
                ('total', self.total_bytes)]
        width = max(len(r[0]) for r in rows)
        lines = ['memplan (per device, batch=%d%s):'
                 % (self.batch,
                    ', mesh=%s' % dict(self.mesh_axes)
                    if self.mesh_axes else '')]
        for name, b in rows:
            lines.append('  %-*s  %12s' % (width, name, _fmt_bytes(b)))
        if self.limit_bytes is not None:
            lines.append('  %-*s  %12s  (%s)%s'
                         % (width, 'limit', _fmt_bytes(self.limit_bytes),
                            self.limit_source,
                            '  ** OVER **' if self.over_limit() else ''))
        if self.peak_op:
            lines.append('  peak at op#%d %s' % tuple(self.peak_op))
        for name, kind, b in self.top[:5]:
            lines.append('    %-12s %-24s %12s' % (kind, name,
                                                   _fmt_bytes(b)))
        return '\n'.join(lines)

    __repr__ = __str__ = lambda self: self.render_table()


def _query_runtime_limit():
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            limit = stats.get('bytes_limit')
            return int(limit) if limit else None
    except Exception:
        return None
    return None


def plan_memory(program, feed_names=(), fetch_names=(), batch=1):
    """Build the static per-device MemPlan for `program`."""
    mesh = program.mesh_axes()
    root = program.global_block()
    persist = persistable_names(program)
    contrib = []  # (name, kind, bytes)

    params_bytes = 0
    opt_bytes = 0
    zspecs = _zero_specs(program)
    for b in program.blocks:
        for name, v in b.vars.items():
            spec = zspecs[name] if name in zspecs else _UNSET
            if isinstance(v, Parameter):
                by = _var_bytes(v, mesh, batch, spec)
                params_bytes += by
                contrib.append((name, 'param', by))
            elif v.persistable and not getattr(v, 'is_data', False):
                by = _var_bytes(v, mesh, batch, spec)
                opt_bytes += by
                contrib.append((name, 'opt_state', by))

    # activation liveness over the root block: a buffer is born at its
    # producing op and dies after its last read (walker attribution);
    # feeds live from op 0, fetches live to the end
    last_read = block_last_reads(program, root)
    n_ops = len(root.ops)
    for n in fetch_names:
        last_read[n] = n_ops
    births = {}
    for i, op in enumerate(root.ops):
        for n in op.output_names():
            births.setdefault(n, i)
    for n in feed_names:
        births[n] = 0
    live = 0
    peak = 0
    peak_i = None
    sizes = {}
    deaths = {}
    for n, i in births.items():
        if n in persist:
            continue  # persistables counted above, alive forever
        v = root._find_var_recursive(n)
        by = _var_bytes(v, mesh, batch)
        if by <= 0:
            continue
        sizes[n] = by
        deaths.setdefault(last_read.get(n, i), []).append(n)
    for i in range(n_ops + 1):
        for n, bi in births.items():
            if bi == i and n in sizes:
                live += sizes[n]
        if live > peak:
            peak = live
            peak_i = i
        for n in deaths.get(i, ()):
            live -= sizes.pop(n, 0)
    peak_op = None
    if peak_i is not None and peak_i < n_ops:
        peak_op = (peak_i, root.ops[peak_i].type)

    kv_bytes = 0
    if program._kv_plan:
        try:
            from ...serving.generation.kv_cache import CacheConfig
            kv_bytes = int(CacheConfig(**program._kv_plan).bytes())
        except Exception:
            kv_bytes = 0

    limit = program._device_limit_bytes
    source = 'declared'
    if limit is None:
        limit = _query_runtime_limit()
        source = 'runtime' if limit is not None else 'none'

    contrib.sort(key=lambda t: -t[2])
    return MemPlan(params_bytes, opt_bytes, peak, kv_bytes, limit, source,
                   peak_op, contrib[:8], program._mesh_axes, batch)


@register_pass('memplan')
def run(ctx):
    program = ctx.program
    plan = plan_memory(program, feed_names=ctx.feed_names,
                       fetch_names=ctx.fetch_names)
    # stash for pt_lint --memplan so the CLI renders the same plan the
    # pass judged, without a second walk
    program._last_memplan = plan
    if not plan.over_limit():
        return []
    root = program.global_block()
    op = None
    op_index = None
    if plan.peak_op is not None:
        op_index = plan.peak_op[0]
        op = root.ops[op_index]
    worst = ', '.join('%s %s (%s)' % (k, n, _fmt_bytes(b))
                      for n, k, b in plan.top[:3])
    return [ctx.diag(
        'D020', 'error',
        'static per-device footprint %s exceeds the %s limit %s '
        '(params %s + opt state %s + activation peak %s + kv pool %s); '
        'largest: %s'
        % (_fmt_bytes(plan.total_bytes), plan.limit_source,
           _fmt_bytes(plan.limit_bytes), _fmt_bytes(plan.params_bytes),
           _fmt_bytes(plan.opt_state_bytes),
           _fmt_bytes(plan.activation_peak_bytes),
           _fmt_bytes(plan.kv_pool_bytes), worst),
        block=root, op=op, op_index=op_index,
        fixit='shard the largest contributors over the mesh, shrink the '
              'kv plan, or raise the declared device limit',
        pass_name='memplan')]
