"""Lint engine: pass registry + shared program-walk context.

Mirrors the reference's `framework/ir/pass.h` Pass/PassRegistry pair, but
passes here are plain functions `fn(ctx) -> list[Diagnostic]` over the
pure-Python Program IR (no C++ graph).  `lint_program` is the single
entry point used by `Program.lint()`, the executor's PT_LINT hook, and
tools/pt_lint.py.

A crashing pass NEVER fails the lint run: the crash is downgraded to a
D099 info diagnostic so analyzer bugs cannot block training (the
executor hook depends on this).
"""
import traceback

from .diagnostics import Diagnostic, LintResult

__all__ = ['LintContext', 'register_pass', 'pass_names', 'lint_program']

_PASSES = []  # [(name, fn)] in registration (= execution) order


def register_pass(name):
    def deco(fn):
        _PASSES.append((name, fn))
        return fn
    return deco


def pass_names():
    _ensure_passes_loaded()
    return [n for n, _ in _PASSES]


_loaded = [False]


def _ensure_passes_loaded():
    if not _loaded[0]:
        _loaded[0] = True
        from . import passes  # noqa: F401  (self-registering modules)


def _did_you_mean(name, candidates, n=1):
    """Nearest candidate(s) by edit distance (difflib ratio)."""
    import difflib
    matches = difflib.get_close_matches(name, list(candidates), n=n,
                                        cutoff=0.6)
    return matches[0] if matches else None


class LintContext(object):
    """Everything a pass needs: the program plus precomputed walk maps."""

    def __init__(self, program, feed_names=(), fetch_names=(),
                 bucketer=None):
        self.program = program
        self.feed_names = tuple(feed_names)
        self.fetch_names = tuple(fetch_names)
        self.bucketer = bucketer
        # block idx -> "block 0 > while@op12 > block 1" style path
        self._block_paths = self._build_block_paths()
        # block idx -> {var name -> (op_index, op)} LAST writer in block
        self.producers = {}
        # var name -> [(block_idx, op_index, op)] readers, program-wide
        self.readers = {}
        # var name -> number of writing ops program-wide: names written
        # more than once are REBOUND (e.g. in-place grad clip) and their
        # declared shape/dtype only reflects the last write
        self.write_counts = {}
        for b in program.blocks:
            prod = {}
            for i, op in enumerate(b.ops):
                for n in op.input_names():
                    self.readers.setdefault(n, []).append((b.idx, i, op))
                for n in op.output_names():
                    prod[n] = (i, op)
                    self.write_counts[n] = self.write_counts.get(n, 0) + 1
            self.producers[b.idx] = prod

    def _build_block_paths(self):
        paths = {0: 'block 0'}
        # owning op of each sub-block: parent op carrying sub_block attr
        for b in self.program.blocks:
            for i, op in enumerate(b.ops):
                sub = op.attrs.get('sub_block')
                if sub is not None and sub not in paths:
                    parent = paths.get(b.idx, 'block %d' % b.idx)
                    paths[sub] = '%s > %s@op%d > block %d' % (
                        parent, op.type, i, sub)
        for b in self.program.blocks:
            paths.setdefault(b.idx, 'block %d' % b.idx)
        return paths

    def block_path(self, block_idx):
        return self._block_paths.get(block_idx, 'block %d' % block_idx)

    def producer_of(self, block, name):
        """Last op writing `name`, searched from `block` up the parent
        chain (matches _find_var_recursive visibility)."""
        b = block
        while b is not None:
            hit = self.producers[b.idx].get(name)
            if hit is not None:
                return hit[1]
            b = b.parent
        return None

    def visible_names(self, block):
        names = set()
        b = block
        while b is not None:
            names.update(b.vars)
            b = b.parent
        return names

    def suggest(self, name, candidates):
        return _did_you_mean(name, candidates)

    def diag(self, code, severity, message, block=None, op=None,
             op_index=None, var=None, fixit=None, pass_name=None):
        return Diagnostic(
            code, severity, message, op=op, op_index=op_index,
            block_idx=block.idx if block is not None else None,
            block_path=(self.block_path(block.idx)
                        if block is not None else None),
            var=var, fixit=fixit, pass_name=pass_name)


def lint_program(program, feed_names=(), fetch_names=(), bucketer=None,
                 passes=None):
    """Run the registered lint passes; returns a LintResult.

    `passes` restricts to a subset of pass names (None = all).  Never
    raises: pass crashes become D099 info diagnostics.  Strict-mode
    raising is the caller's policy (see core.executor / Program.lint).
    """
    _ensure_passes_loaded()
    ctx = LintContext(program, feed_names=feed_names,
                      fetch_names=fetch_names, bucketer=bucketer)
    result = LintResult()
    for name, fn in _PASSES:
        if passes is not None and name not in passes:
            continue
        try:
            result.extend(fn(ctx) or ())
        except Exception:
            result.add(Diagnostic(
                'D099', 'info',
                'lint pass %r crashed: %s' % (
                    name, traceback.format_exc(limit=3).strip()),
                pass_name=name))
    return result
