"""Diagnostic objects for the static program analyzer.

The reference framework reports program bugs through ~40 C++ IR passes
(paddle/fluid/framework/ir/) each with ad-hoc logging; here every pass
emits the same structured `Diagnostic` so results render uniformly as
text, JSON (tools/pt_lint.py), or graphviz highlights (debugger.py).

Code table (docs/analysis.md has the full semantics):

  D001 error    def-use violation (read before any definition)
  D002 warning  unknown op (no registered JAX impl)
  D003 error    shape/dtype mismatch or abstract-interp failure
  D004 info     64-bit dtype narrowed to 32-bit under x64-disabled
  D005 warning  dead op (outputs reach no fetch/persistable/sub-block)
  D006 info     unused var (defined, never read)
  D007 warning  parameter read after in-block writeback
  D008 warning  feed shadows a parameter / persistable
  D009 warning  persistable double-write within one block
  D010 warning  retrace hazard: dynamic feed dim not covered by buckets
  D011 warning  retrace hazard: array-valued / per-run-varying attr
  D012 warning  numerical hazard: unclipped log/div/exp
  D013 warning  numerical hazard: softmax built without max-subtraction
  D014 warning  degenerate learning-rate decay constant
  D015 info     op not emit-capable (direct emitter would fall back)
  D016 info     fused sub-op not kernelgen-capable (replay fallback)
  D017 error    sharding conflict (producers force incompatible specs)
  D018 warning  implicit reshard (consumed spec differs from delivered)
  D019 error    mesh-axis mismatch (spec names an undeclared mesh axis)
  D020 error    memplan over budget (static HBM footprint > device limit)
  D021 warning  donation hazard (host array / param read after donation)
  D099 info     lint pass crashed (analyzer bug, never fatal)
"""

__all__ = ['Diagnostic', 'LintResult', 'LintError', 'SEVERITIES', 'CODES',
           'DIAG_JSON_KEYS', 'RESULT_JSON_KEYS']

SEVERITIES = ('info', 'warning', 'error')
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

CODES = {
    'D001': 'def-use violation',
    'D002': 'unknown op',
    'D003': 'shape/dtype mismatch',
    'D004': '64-bit narrowing',
    'D005': 'dead op',
    'D006': 'unused var',
    'D007': 'param read after writeback',
    'D008': 'feed shadows persistable',
    'D009': 'persistable double-write',
    'D010': 'unbucketed dynamic feed dim',
    'D011': 'per-run-varying attr',
    'D012': 'unclipped log/div/exp',
    'D013': 'softmax without max-subtraction',
    'D014': 'degenerate lr decay',
    'D015': 'op not emit-capable',
    'D016': 'fused sub-op not kernelgen-capable',
    'D017': 'sharding conflict',
    'D018': 'implicit reshard',
    'D019': 'mesh-axis mismatch',
    'D020': 'memplan over device limit',
    'D021': 'donation hazard',
    'D099': 'lint pass crashed',
}

# The JSON shapes `Diagnostic.to_dict` / `LintResult.to_dict` emit —
# pinned as constants so tools (pt_lint --json consumers, the ci_smoke
# schema gate) validate against the same source of truth the renderer
# uses instead of a hand-copied list.
DIAG_JSON_KEYS = ('code', 'severity', 'message', 'op_type', 'op_index',
                  'block_idx', 'block_path', 'var', 'fixit', 'source_loc',
                  'pass')
RESULT_JSON_KEYS = ('diagnostics', 'errors', 'warnings', 'infos')


class Diagnostic(object):
    """One finding: code + severity + location (op, var, block path)."""

    __slots__ = ('code', 'severity', 'message', 'op_type', 'op_index',
                 'block_idx', 'block_path', 'var', 'fixit', 'source_loc',
                 'pass_name')

    def __init__(self, code, severity, message, op=None, op_index=None,
                 block_idx=None, block_path=None, var=None, fixit=None,
                 source_loc=None, pass_name=None):
        if code not in CODES:
            raise ValueError('unknown diagnostic code %r' % code)
        if severity not in SEVERITIES:
            raise ValueError('bad severity %r' % severity)
        self.code = code
        self.severity = severity
        self.message = message
        self.op_type = getattr(op, 'type', op)
        self.op_index = op_index
        self.block_idx = block_idx
        self.block_path = block_path
        self.var = var
        self.fixit = fixit
        self.source_loc = source_loc or getattr(op, 'source_loc', None)
        self.pass_name = pass_name

    @property
    def rank(self):
        return _SEV_RANK[self.severity]

    def location(self):
        parts = []
        if self.block_path:
            parts.append(self.block_path)
        elif self.block_idx is not None:
            parts.append('block %d' % self.block_idx)
        if self.op_type is not None:
            parts.append('op#%s %s' % (self.op_index
                                       if self.op_index is not None else '?',
                                       self.op_type))
        if self.var:
            parts.append("var '%s'" % self.var)
        return ' '.join(parts)

    def render(self):
        loc = self.location()
        line = '%s %-7s %s%s' % (self.code, self.severity,
                                 ('[%s] ' % loc) if loc else '',
                                 self.message)
        if self.fixit:
            line += '  (fix: %s)' % self.fixit
        if self.source_loc:
            line += '  @ %s:%s' % tuple(self.source_loc)
        return line

    def to_dict(self):
        return {'code': self.code, 'severity': self.severity,
                'message': self.message, 'op_type': self.op_type,
                'op_index': self.op_index, 'block_idx': self.block_idx,
                'block_path': self.block_path, 'var': self.var,
                'fixit': self.fixit,
                'source_loc': (list(self.source_loc)
                               if self.source_loc else None),
                'pass': self.pass_name}

    __repr__ = __str__ = lambda self: self.render()


class LintResult(object):
    """Ordered collection of diagnostics from one lint run."""

    def __init__(self, diagnostics=None):
        self.diagnostics = list(diagnostics or ())

    def add(self, diag):
        self.diagnostics.append(diag)

    def extend(self, diags):
        self.diagnostics.extend(diags)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def __bool__(self):
        return bool(self.diagnostics)

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == 'error']

    @property
    def warnings(self):
        return [d for d in self.diagnostics if d.severity == 'warning']

    @property
    def infos(self):
        return [d for d in self.diagnostics if d.severity == 'info']

    def has_errors(self):
        return any(d.severity == 'error' for d in self.diagnostics)

    def codes(self):
        return sorted({d.code for d in self.diagnostics})

    def at_least(self, severity):
        """Diagnostics at `severity` or worse."""
        floor = _SEV_RANK[severity]
        return [d for d in self.diagnostics if d.rank >= floor]

    def op_findings(self):
        """(block_idx, op_index) -> worst severity, for graph highlighting
        (debugger.draw_block_graphviz / net_drawer.draw_graph)."""
        worst = {}
        for d in self.diagnostics:
            if d.op_index is None or d.block_idx is None:
                continue
            key = (d.block_idx, d.op_index)
            if key not in worst or _SEV_RANK[worst[key]] < d.rank:
                worst[key] = d.severity
        return worst

    def render(self, min_severity='info'):
        diags = sorted(self.at_least(min_severity),
                       key=lambda d: (-d.rank, d.code))
        if not diags:
            return 'lint: no findings at severity >= %s' % min_severity
        lines = [d.render() for d in diags]
        lines.append('lint: %d error(s), %d warning(s), %d info(s)'
                     % (len(self.errors), len(self.warnings),
                        len(self.infos)))
        return '\n'.join(lines)

    def to_dict(self):
        return {'diagnostics': [d.to_dict() for d in self.diagnostics],
                'errors': len(self.errors), 'warnings': len(self.warnings),
                'infos': len(self.infos)}

    __repr__ = __str__ = lambda self: self.render()


class LintError(ValueError):
    """Raised under PT_LINT=strict when error-severity findings exist.
    Subclasses ValueError so callers that caught the old validate_def_use
    error keep working unchanged."""

    def __init__(self, result, header='program lint failed'):
        self.result = result
        errs = result.errors if isinstance(result, LintResult) else [result]
        msg = '%s:\n%s' % (header, '\n'.join(d.render() for d in errs))
        super(LintError, self).__init__(msg)
