"""paddle_tpu.analysis — static program analysis (the `pt-lint` engine).

The reference framework runs C++ IR passes over ProgramDesc before
execution (paddle/fluid/framework/ir/); this package is the TPU-native
analog: multi-pass linting over the pure-Python Program IR, with a
shape/dtype abstract interpreter (jax.eval_shape, no compilation) at its
core.  Three entry points share one engine:

  * ``Program.lint(...)``        — in-memory API (core/framework.py)
  * ``tools/pt_lint.py``         — CLI over saved models & bundled models
  * the executor's PT_LINT hook  — strict|warn|0 at lowering-cache miss
                                   (core/executor.py _lower)

See docs/analysis.md for the diagnostic code table (D001..D021) and
severity semantics.
"""
import os
import warnings

from .diagnostics import Diagnostic, LintResult, LintError, CODES, SEVERITIES
from .engine import lint_program, register_pass, pass_names, LintContext

__all__ = ['Diagnostic', 'LintResult', 'LintError', 'CODES', 'SEVERITIES',
           'lint_program', 'register_pass', 'pass_names', 'LintContext',
           'lint_mode', 'apply_lint_policy', 'LintWarning']


class LintWarning(UserWarning):
    """Emitted (once per lint run) under PT_LINT=warn."""


def lint_mode():
    """Current executor lint policy from $PT_LINT: 'strict' (default),
    'warn', or '0' (off — today's raw mid-trace failures)."""
    mode = os.environ.get('PT_LINT', 'strict').strip().lower()
    if mode in ('0', 'false', 'off', 'no'):
        return '0'
    if mode == 'warn':
        return 'warn'
    return 'strict'


def apply_lint_policy(program, feed_names=(), fetch_names=(),
                      bucketer=None, mode=None, header=None):
    """Lint + enforce the PT_LINT policy; returns the LintResult.

    strict: raise LintError (a ValueError) when error-severity findings
            exist; warnings/infos are recorded silently.
    warn:   one LintWarning summarizing everything at warning+.
    0:      skip entirely (returns an empty result).

    The result is stashed on ``program._last_lint`` and counted into the
    observability registry (lint.findings / lint.errors) either way.
    """
    mode = lint_mode() if mode is None else mode
    if mode == '0':
        return LintResult()
    # one lint per (program version, launch signature): run_steps tails
    # and K-variants re-lower the same program — don't re-walk it
    memo_key = (program._version, tuple(feed_names), tuple(fetch_names),
                mode)
    if getattr(program, '_lint_memo_key', None) == memo_key:
        return program._last_lint
    result = lint_program(program, feed_names=feed_names,
                          fetch_names=fetch_names, bucketer=bucketer)
    program._last_lint = result
    from .. import observability as _obs
    if _obs.enabled() and len(result):
        _obs.metrics.counter('lint.findings').inc(len(result))
        if result.errors:
            _obs.metrics.counter('lint.errors').inc(len(result.errors))
        if result.warnings:
            _obs.metrics.counter('lint.warnings').inc(
                len(result.warnings))
    if mode == 'warn':
        noteworthy = result.at_least('warning')
        if noteworthy:
            warnings.warn(LintWarning(
                '%s:\n%s' % (header or 'program lint found issues',
                             '\n'.join(d.render() for d in noteworthy))),
                stacklevel=3)
    elif result.has_errors():
        raise LintError(result, header or 'program lint failed '
                        '(PT_LINT=strict; set PT_LINT=warn or PT_LINT=0 '
                        'to bypass)')
    # memoize only the non-raising outcome: a strict failure must raise
    # again on the next lowering attempt
    program._lint_memo_key = memo_key
    return result
