"""Device-health watchdog for pod-scale training.

A pod participant that dies (preemption, kernel panic, a wedged PJRT
runtime) does not return an error — it simply stops answering, and every
peer blocks forever in the next collective.  The watchdog turns that
silent hang into a typed, recoverable failure:

  * every participant ``beat(step)``s a per-host heartbeat file
    (JSON, atomic tmp+rename) into a shared ``health_dir`` each step;
  * every participant ``check(step)``s the roster: a peer whose beat has
    gone stale for ``timeout_s`` raises :class:`DeviceLossError`; a peer
    whose reported step runs ``desync_steps`` ahead raises
    :class:`HostDesyncError` (a drifted host corrupts lockstep
    semantics long before it hangs);
  * a trip records + dumps the flight recorder (postmortems cover pod
    failures) and is STICKY — once lost, always lost, so a background
    poller and the step loop cannot disagree.

`RecoveryPolicy` (train/recovery.py) treats :class:`DeviceLossError` as
a pod fault, not a divergence: it rolls the scope back to the last good
manifest and RE-RAISES, and the trainer process exits with
``RESTART_EXIT_CODE`` so its supervisor respawns it — typically on a
smaller roster (the elastic restore re-slices the manifest onto
whatever mesh comes up).  ``tools/pod_soak.py`` drives exactly that
loop under the ``device_loss`` / ``host_desync`` fault sites.

Heartbeats are plain files on the shared checkpoint volume — no extra
transport, works under multiprocess CPU testing, and the staleness
clock is injectable (``time_fn``) so the unit tests never sleep.
A finished participant calls :meth:`HealthMonitor.mark_done` so peers
still training do not mistake a clean exit for a loss.
"""
import json
import os
import threading
import time

from .. import observability as _obs
from ..observability import flight as _flight
from ..testing import faults as _faults

__all__ = ['DeviceLossError', 'HostDesyncError', 'HealthConfig',
           'HealthMonitor', 'RESTART_EXIT_CODE']

# sysexits.h EX_TEMPFAIL: "try again (on a smaller mesh)" — the contract
# between a tripped worker and its supervisor (tools/pod_soak.py)
RESTART_EXIT_CODE = 75

# step skew the host_desync fault injects (kept in sync with
# train/checkpoint.py): far past any plausible desync_steps tolerance
_DESYNC_SKEW = 10000


class DeviceLossError(RuntimeError):
    """A pod participant stopped heartbeating: treat the collective as
    dead, roll back, restart on the surviving mesh."""


class HostDesyncError(DeviceLossError):
    """A participant's reported step drifted out of the lockstep window —
    its collectives (and its checkpoint shards) no longer describe the
    same training state as the rest of the roster."""


class HealthConfig(object):
    def __init__(self, health_dir, host_id=None, host_count=None,
                 timeout_s=5.0, desync_steps=500):
        self.health_dir = health_dir
        if host_id is None:
            host_id = int(os.environ.get('PT_HOST_ID', '0'))
        if host_count is None:
            host_count = int(os.environ.get('PT_HOST_COUNT', '1'))
        self.host_id = int(host_id)
        self.host_count = max(1, int(host_count))
        if not 0 <= self.host_id < self.host_count:
            raise ValueError('host_id %d not in roster of %d host(s)'
                             % (self.host_id, self.host_count))
        self.timeout_s = float(timeout_s)
        self.desync_steps = int(desync_steps)


class HealthMonitor(object):
    """Heartbeat writer + roster checker for one pod participant."""

    def __init__(self, config, time_fn=time.time, on_trip=None):
        if isinstance(config, str):
            config = HealthConfig(config)
        self.config = config
        self._time = time_fn
        self.on_trip = on_trip
        self._hung = False       # device_loss injected: stop beating
        self._tripped = None     # sticky: first trip wins
        self._my_step = None
        self._seen = {}          # host -> last beat read (joined peers)
        self._poller = None
        self._stop = threading.Event()
        os.makedirs(config.health_dir, exist_ok=True)

    def path_of(self, host):
        return os.path.join(self.config.health_dir, 'host_%d.json' % host)

    # ------------------------------------------------------------- beat
    def beat(self, step, done=False):
        """Write this host's heartbeat.  Returns False when the armed
        ``device_loss`` fault fires — the caller should then act like a
        lost device (hang or exit without cleanup), NOT keep beating."""
        if self._hung:
            return False
        step = int(step)
        if _faults.fire('device_loss', step):
            # a lost device goes silent mid-step: no further beats, no
            # goodbye — peers must detect the staleness
            self._hung = True
            _flight.record('health.device_loss_injected',
                           host=self.config.host_id, step=step)
            return False
        rec_step = step
        if _faults.fire('host_desync', step):
            # a drifted host: heartbeat claims a far-future step
            rec_step = step + _DESYNC_SKEW
        rec = {'host': self.config.host_id, 'step': rec_step,
               'time': float(self._time()), 'pid': os.getpid(),
               'done': bool(done)}
        path = self.path_of(self.config.host_id)
        tmp = '%s.tmp%d' % (path, os.getpid())
        with open(tmp, 'w') as f:
            json.dump(rec, f)
        os.replace(tmp, path)
        self._my_step = step
        _obs.metrics.counter('health.beats').inc()
        return True

    def mark_done(self):
        """Final heartbeat flagging a CLEAN exit: peers still training
        treat this host as healthy forever instead of tripping on its
        (now permanently stale) beat."""
        if self._my_step is not None:
            self.beat(self._my_step, done=True)

    # ------------------------------------------------------------ check
    def _read(self, host):
        try:
            with open(self.path_of(host)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None   # not yet joined, or mid-rename

    def snapshot(self):
        """{host: last heartbeat record} for every roster member that
        has ever beaten."""
        out = {}
        for h in range(self.config.host_count):
            rec = self._read(h)
            if rec is not None:
                out[h] = rec
        return out

    def check(self, step=None):
        """Scan the roster; raise on a lost or desynced peer.  A peer
        that has NEVER beaten is treated as not-yet-joined (startup is
        not a loss); a peer marked done is healthy forever.  Trips are
        sticky — every later check re-raises the first verdict."""
        if self._tripped is not None:
            raise self._tripped
        cfg = self.config
        now = float(self._time())
        mine = int(step) if step is not None else self._my_step
        for h in range(cfg.host_count):
            if h == cfg.host_id:
                continue
            rec = self._read(h)
            if rec is None:
                if h in self._seen and not self._seen[h].get('done'):
                    self._trip(DeviceLossError(
                        'host %d heartbeat file disappeared' % h),
                        kind='device_loss', host=h)
                continue
            self._seen[h] = rec
            if rec.get('done'):
                continue
            age = now - float(rec.get('time', 0.0))
            if age > cfg.timeout_s:
                self._trip(DeviceLossError(
                    'host %d lost: last heartbeat %.2fs ago (> %.2fs) at '
                    'step %s' % (h, age, cfg.timeout_s, rec.get('step'))),
                    kind='device_loss', host=h, age=age)
            if mine is not None and \
                    int(rec.get('step', 0)) - mine > cfg.desync_steps:
                self._trip(HostDesyncError(
                    'host %d desynced: reports step %s, local step %d '
                    '(tolerance %d)' % (h, rec.get('step'), mine,
                                        cfg.desync_steps)),
                    kind='host_desync', host=h)

    def _trip(self, exc, kind, **args):
        _obs.metrics.counter('health.trips').inc()
        _obs.metrics.counter(
            'health.desyncs' if kind == 'host_desync'
            else 'health.lost_hosts').inc()
        _obs.tracing.instant('health.trip', cat='health',
                             args=dict(args, kind=kind))
        _flight.record('health.trip', trip=kind, error=str(exc), **args)
        # the postmortem must exist even if the raise below kills the
        # run before any give-up handler runs
        _flight.maybe_dump('health_trip')
        self._tripped = exc
        if self.on_trip is not None:
            self.on_trip(exc)
        raise exc

    # ------------------------------------------------------- background
    def start(self, poll_s=0.2):
        """Optional background poller: detects a loss while the step
        loop is blocked (e.g. inside a hung collective).  The verdict is
        sticky, so the loop's own next ``check()`` re-raises it; an
        ``on_trip`` callback can additionally interrupt the block."""
        if self._poller is not None and self._poller.is_alive():
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(poll_s):
                try:
                    self.check()
                except DeviceLossError:
                    return   # sticky verdict recorded; poller retires

        self._poller = threading.Thread(target=_loop, name='HealthPoller',
                                        daemon=True)
        self._poller.start()

    def stop(self):
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=2.0)
            self._poller = None
