"""Device-mesh management.

The mesh is the TPU-native replacement for the reference's places list
(ParallelExecutor) and trainer endpoints (DistributeTranspiler).  Axes:
  data  — batch sharding (data parallel; gradients all-reduce over ICI)
  model — tensor parallelism (weight sharding)
  pipe  — pipeline stages
  seq   — sequence/context parallelism (ring attention)
"""
import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ['make_mesh', 'default_mesh', 'set_default_mesh', 'shard_map']


def shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions (check_rep → check_vma rename),
    always with replication checking off (we use psum-to-replicate)."""
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)

_default_mesh = [None]


def make_mesh(data=None, model=1, pipe=1, seq=1, devices=None):
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if data is None:
        data = n // (model * pipe * seq)
    assert data * model * pipe * seq == n, (
        'mesh %dx%dx%dx%d != %d devices' % (data, model, pipe, seq, n))
    arr = np.array(devices).reshape(data, seq, pipe, model)
    return Mesh(arr, ('data', 'seq', 'pipe', 'model'))


def default_mesh():
    if _default_mesh[0] is None:
        _default_mesh[0] = make_mesh()
    return _default_mesh[0]


def set_default_mesh(mesh):
    _default_mesh[0] = mesh
