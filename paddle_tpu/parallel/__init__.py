"""Distributed / parallel execution over jax.sharding meshes.

Replaces reference paddle/fluid/framework/details (multi-GPU SSA graph +
NCCL all-reduce) and transpiler/distribute_transpiler.py (pserver & NCCL2
modes): data/tensor/pipeline/sequence parallelism are expressed as sharding
annotations over a `jax.sharding.Mesh`; XLA GSPMD inserts the collectives
(all-reduce/all-gather/reduce-scatter) over ICI.
"""
from .mesh import make_mesh, default_mesh, set_default_mesh  # noqa
from .parallel_executor import ParallelExecutor  # noqa
