"""Distributed / parallel execution over jax.sharding meshes.

Replaces reference paddle/fluid/framework/details (multi-GPU SSA graph +
NCCL all-reduce) and transpiler/distribute_transpiler.py (pserver & NCCL2
modes): data/tensor/pipeline/sequence parallelism are expressed as sharding
annotations over a `jax.sharding.Mesh`; XLA GSPMD inserts the collectives
(all-reduce/all-gather/reduce-scatter) over ICI.
"""
from .mesh import make_mesh, default_mesh, set_default_mesh, shard_map  # noqa
from .parallel_executor import ParallelExecutor  # noqa
from .health import (HealthConfig, HealthMonitor,  # noqa
                     DeviceLossError, HostDesyncError, RESTART_EXIT_CODE)
from .tp import shard_program_tp, annotate  # noqa
from .ring_attention import ring_attention, ring_attention_sharded  # noqa
from .pipeline import pipeline_apply, stack_stage_params  # noqa
from .sharded_embedding import shard_embedding, sharded_embedding  # noqa
from . import moe  # noqa
from . import distributed  # noqa
from .distributed import (init_parallel_env, get_rank,  # noqa
                          get_world_size, barrier, global_mesh)
