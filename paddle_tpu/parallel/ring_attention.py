"""Ring attention: exact attention over sequence shards (context parallel).

First-class long-context support (SURVEY §2.4).  The reference has no
sequence parallelism; on TPU this is how attention scales past one chip's
HBM: Q stays resident per device, K/V blocks rotate around the ring of
devices on the `seq` mesh axis via `lax.ppermute` (ICI neighbour hops),
and the softmax is accumulated online (flash-attention style running max /
denominator), so the full [T, T] score matrix never materialises and each
device only ever holds 1/n of K and V.

Differentiable: the ring is a `lax.scan` of ppermutes + matmuls, and JAX
transposes ppermute exactly, so jax.vjp gives the exact backward ring for
free.  Wrap the caller in `jax.checkpoint` to avoid saving per-hop K/V.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from .mesh import shard_map

__all__ = ['ring_attention', 'ring_attention_sharded']

_NEG = -1e30


def _local_ring_attention(q, k, v, axis_name, causal, scale):
    """Per-shard body. q: [B, H, Tl, D]; k/v: [B, Hkv, Tl, D] local blocks
    (Tl = T / n_dev).  Hkv may divide H (GQA): K/V blocks rotate the ring
    at Hkv width — the repeated-head view is never materialized, so ICI
    traffic per hop stays at the grouped size."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    Hkv = k.shape[1]
    g = H // Hkv
    qg = (q * scale).reshape(B, Hkv, g, Tl, D)

    # global positions of this device's query rows
    q_pos = idx * Tl + jnp.arange(Tl)  # [Tl]

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # k_blk arrived from device (idx + i) mod n
        src = (idx + i) % n
        k_pos = src * Tl + jnp.arange(Tl)  # [Tl]
        s = jnp.einsum('bhgqd,bhkd->bhgqk', qg, k_blk,
                       preferred_element_type=jnp.float32)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [Tl, Tl]
            s = jnp.where(mask[None, None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))          # [B,Hkv,g,Tl]
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            'bhgqk,bhkd->bhgqd', p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        # rotate K/V to the next device (neighbour hop on ICI)
        perm = [(j, (j - 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((B, Hkv, g, Tl, D), jnp.float32)
    m0 = jnp.full((B, Hkv, g, Tl), _NEG, jnp.float32)
    l0 = jnp.zeros((B, Hkv, g, Tl), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(B, H, Tl, D).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name='seq', causal=False,
                   scale=None):
    """Exact attention with q/k/v sharded on the sequence dim.

    q, k, v: [B, H, T, D] jax arrays (global view), T divisible by the size
    of `axis_name` in `mesh`.  Batch stays on 'data' if that axis exists.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    data = 'data' if 'data' in mesh.axis_names else None
    spec = P(data, None, axis_name, None)
    fn = shard_map(
        functools.partial(_local_ring_attention, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ring_attention_sharded(axis_name='seq', causal=False, scale=None):
    """Variant for use INSIDE an existing shard_map region: takes local
    [B, H, Tl, D] blocks directly."""
    def fn(q, k, v):
        s = scale if scale is not None else q.shape[-1] ** -0.5
        return _local_ring_attention(q, k, v, axis_name, causal, s)
    return fn
