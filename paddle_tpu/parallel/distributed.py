"""Multi-host runtime: jax.distributed bootstrap + rank helpers.

Parity: the reference's NCCL2/gRPC trainer bootstrap (transpiler nccl2
mode + paddle/fluid/platform/nccl_helper.h, PADDLE_TRAINER_* env
convention).  TPU-native: every host runs the SAME SPMD program; this
module only brings up the JAX distributed runtime (coordination service +
cross-host device visibility) and exposes rank/size.  Collectives
themselves are XLA ops (psum/ppermute/...) emitted by GSPMD from sharding
annotations — there is no NCCL communicator object to manage.

Env convention (reference-compatible):
  PADDLE_TRAINER_ID        -> process_id
  PADDLE_TRAINERS_NUM      -> num_processes
  PADDLE_TRAINER_ENDPOINTS -> comma list; first entry = coordinator
  PADDLE_CURRENT_ENDPOINT  -> this host (used to infer id if unset)
"""
import os

__all__ = ['init_parallel_env', 'get_rank', 'get_world_size', 'barrier',
           'global_mesh', 'is_initialized']

_state = {'initialized': False, 'rank': 0, 'world': 1}


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return default


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Bring up jax.distributed across hosts.  No-op (returns rank 0/world
    1) when neither args nor PADDLE_TRAINER_* / JAX envs describe a
    multi-process job."""
    import jax

    eps = _env('PADDLE_TRAINER_ENDPOINTS')
    if coordinator_address is None and eps:
        coordinator_address = eps.replace('\n', ',').split(',')[0]
    if num_processes is None:
        n = _env('PADDLE_TRAINERS_NUM')
        if n:
            num_processes = int(n)
        elif eps:
            num_processes = len(
                [e for e in eps.replace('\n', ',').split(',') if e])
    if process_id is None:
        tid = _env('PADDLE_TRAINER_ID')
        if tid is not None:
            process_id = int(tid)
        elif eps and _env('PADDLE_CURRENT_ENDPOINT'):
            ep_list = [e for e in eps.replace('\n', ',').split(',') if e]
            cur = _env('PADDLE_CURRENT_ENDPOINT')
            process_id = ep_list.index(cur) if cur in ep_list else 0

    if not coordinator_address or not num_processes or num_processes <= 1:
        _state.update(initialized=True, rank=0, world=1)
        return 0, 1

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)
    _state.update(initialized=True, rank=jax.process_index(),
                  world=jax.process_count())
    return _state['rank'], _state['world']


def is_initialized():
    return _state['initialized']


def get_rank():
    import jax
    return jax.process_index() if _state['initialized'] else 0


def get_world_size():
    import jax
    return jax.process_count() if _state['initialized'] else 1


def global_mesh(model=1, pipe=1, seq=1):
    """Mesh over ALL processes' devices (jax.devices() is global after
    init): data axis absorbs whatever the other axes don't."""
    from .mesh import make_mesh
    return make_mesh(model=model, pipe=pipe, seq=seq)


def barrier(name='barrier'):
    """Block until every process arrives (psum of 1 over all devices)."""
    import jax
    import jax.numpy as jnp
    if get_world_size() <= 1:
        return
    out = jax.pmap(lambda x: jax.lax.psum(x, 'i'), axis_name='i')(
        jnp.ones((jax.local_device_count(),)))
    out.block_until_ready()
