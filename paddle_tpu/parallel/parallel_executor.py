"""ParallelExecutor — data-parallel training over the device mesh.

Parity: reference python/paddle/fluid/parallel_executor.py + C++
framework/details/ SSA-graph executor.  The reference clones the graph per
GPU and threads NCCL all_reduce ops between them; here the SAME lowered
XLA computation runs SPMD: feeds are sharded on the batch dim over the
'data' mesh axis and `exe.run()` is still one device launch.

Constructing a ParallelExecutor declares the mesh on the main program
(`Program.set_mesh_axes`), which arms the GSPMD-style shard pass
(core/passes/shard.py): sharding specs complete over the whole program,
every gradient reduction becomes one explicit `grad_allreduce` op,
optimizer state is ZeRO-sharded over the data axis (PT_SHARD_ZERO=1,
the default), and every remaining layout seam is an explicit `reshard`
carrying its estimated bytes — nothing is blanket-replicated and no
collective is implicit.  `PT_SHARD=0` restores the old behavior
(parameters replicated, GSPMD inserts whatever it likes).
"""
import numpy as np

from ..core.executor import Executor, global_scope
from ..core.framework import default_main_program
from .mesh import make_mesh

__all__ = ['ParallelExecutor']


class ParallelExecutor(object):
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, mesh=None):
        self._main_program = main_program or default_main_program()
        self._loss_name = loss_name
        self._scope = scope or global_scope()
        import jax
        self._mesh = mesh or make_mesh(data=len(jax.devices()))
        # declare the mesh on the program: this is what arms the shard
        # pass (an already-declared mesh — e.g. a deliberately different
        # logical layout — wins)
        if self._main_program.mesh_axes() is None:
            self._main_program.set_mesh_axes(self._mesh)
        self._exe = Executor(mesh=self._mesh)
        # tag every span this executor records with the mesh/shard layout,
        # so a timeline mixing single-chip and mesh launches stays legible
        self._exe._obs_tags = {
            'mesh_axes': ','.join(str(a) for a in self._mesh.axis_names),
            'mesh_shape': 'x'.join(str(s)
                                   for s in self._mesh.devices.shape),
            'devices': int(np.prod(self._mesh.devices.shape)),
        }
        if share_vars_from is not None:
            self._scope = share_vars_from._scope

    @property
    def device_count(self):
        return int(np.prod(self._mesh.devices.shape))

    @property
    def mesh(self):
        """The device mesh this executor launches over — Checkpointer
        records it in the manifest so an elastic restore can tell a
        reshard from a same-shape resume."""
        return self._mesh

    # Checkpointer duck-type: full bitwise-resume state lives in the
    # wrapped Executor's RNG/run counters
    def rng_state(self):
        return self._exe.rng_state()

    def set_rng_state(self, state):
        return self._exe.set_rng_state(state)

    # deferred-nan duck-type (core/executor.py): recovery resets the
    # verdict window through the Checkpointer's executor handle, and
    # checkpoint alignment asks nan_clean() — both must reach the inner
    # Executor that actually accumulates the verdicts
    def nan_clean(self):
        return self._exe.nan_clean()

    def poll_nan(self):
        return self._exe.poll_nan()

    def reset_nan_window(self):
        return self._exe.reset_nan_window()

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True,
            as_futures=False):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._main_program, feed=feed,
                             fetch_list=list(fetch_list),
                             scope=self._scope, return_numpy=return_numpy,
                             as_futures=as_futures)

    def prepare(self, program=None, feed=None, fetch_list=None, scope=None,
                steps=None):
        """AOT pre-warm over the device mesh: compile (or load from the
        persistent cache) the mesh-sharded executable for this feed
        signature without running a step.  The fingerprint includes the
        mesh layout, so single-chip and mesh artifacts never collide."""
        return self._exe.prepare(program or self._main_program, feed=feed,
                                 fetch_list=list(fetch_list or []),
                                 scope=self._scope, steps=steps)

    def run_steps(self, program=None, feed_list=None, fetch_list=None,
                  steps=None, return_numpy=True, **kwargs):
        """K iterations per launch over the device mesh: the same jitted
        lax.scan as the single-chip path, with the stacked feeds sharded
        [None, 'data', ...] so the in-scan batch sharding matches the
        single-step mesh layout exactly (see core/executor._lower)."""
        kwargs.pop('scope', None)   # the PE owns its scope
        return self._exe.run_steps(program or self._main_program,
                                   feed_list=feed_list,
                                   fetch_list=list(fetch_list or []),
                                   steps=steps, scope=self._scope,
                                   return_numpy=return_numpy, **kwargs)
