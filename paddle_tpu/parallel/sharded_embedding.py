"""Sharded embedding — TPU replacement for the distributed lookup table.

Parity: reference transpiler/distribute_lookup_table.py +
operators/lookup_table_op (the sparse pserver path, where huge embeddings
are split by row across parameter servers and trainers send prefetch
RPCs).  On TPU the table lives sharded over the mesh: annotate the
parameter P(axis, None) (vocab-sharded) and GSPMD turns the gather into an
all-gather-free one-hot matmul / collective lookup over ICI.  The API
below attaches the annotation to an existing `layers.embedding` parameter.
"""
from jax.sharding import PartitionSpec as P

__all__ = ['shard_embedding', 'sharded_embedding']


def shard_embedding(program, param_name, axis='model'):
    """Mark embedding `param_name` ([V, D]) as row(vocab)-sharded."""
    program.set_sharding(param_name, P(axis, None))
    return program


def sharded_embedding(input, size, param_attr=None, dtype='float32',
                      is_sparse=False, is_distributed=True, axis='model',
                      padding_idx=None):
    """Drop-in for fluid.layers.embedding(is_distributed=True): build the
    embedding and annotate its weight over the model axis of the default
    program."""
    from .. import layers
    from ..core.framework import default_main_program
    from ..param_attr import ParamAttr
    param_attr = ParamAttr._to_attr(param_attr)
    out = layers.embedding(input, size, is_sparse=is_sparse,
                           is_distributed=is_distributed,
                           padding_idx=padding_idx,
                           param_attr=param_attr, dtype=dtype)
    prog = default_main_program()
    # the embedding layer registered exactly one new parameter; find it
    # via the op that produced `out`
    w_name = out.op.inputs['W'][0]
    shard_embedding(prog, w_name, axis=axis)
    return out
