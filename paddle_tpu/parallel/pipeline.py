"""Pipeline parallelism: GPipe-style microbatching over the 'pipe' mesh axis.

TPU-native design (no reference equivalent — Fluid scaled only via data
parallel + pservers): stage s holds its layer weights (leading stage dim
sharded over 'pipe'); activations flow stage→stage via `lax.ppermute`
neighbour hops on ICI inside a `lax.scan` over M + S - 1 ticks.  All
stages run the SAME traced program SPMD-style — there is no per-stage
Python code, so one compile serves every device.

Constraint (standard for scan pipelines): every stage maps activations of
one fixed shape/dtype to the same shape/dtype (transformer layer stacks).
Embedding / head live outside the pipelined region.

Differentiable end-to-end (scan + ppermute transpose exactly).
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import shard_map

__all__ = ['pipeline_apply', 'stack_stage_params']


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> one pytree with leading stage dim (shard it
    P('pipe', ...))."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_apply(mesh, stage_fn, stacked_params, x, n_micro,
                   axis_name='pipe', data_axis=None):
    """Run `stage_fn(stage_params, act) -> act` as an S-stage pipeline.

    stacked_params: pytree, leaves [S, ...] (stage-major).
    x: [B, ...] global batch; B divisible by n_micro (and by the 'data'
    axis size if data_axis given).  Returns [B, ...] outputs.
    """
    S = mesh.shape[axis_name]
    B = x.shape[0]
    assert B % n_micro == 0, (B, n_micro)
    n_stage = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    assert n_stage == S, (
        'stacked_params has %d stages but mesh axis %r has %d devices '
        '(one stage per pipeline device)' % (n_stage, axis_name, S))

    def local(wstack, xm):
        # wstack leaves: [1, ...] (this stage's slice); xm: [M, mb, ...]
        w = jax.tree_util.tree_map(lambda a: a[0], wstack)
        sid = lax.axis_index(axis_name)
        M = xm.shape[0]
        state = jnp.zeros_like(xm[0])
        outputs = jnp.zeros_like(xm)

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t; later stages use what arrived
            inp = jnp.where(sid == 0, xm[jnp.clip(t, 0, M - 1)], state)
            y = stage_fn(w, inp)
            oidx = t - (S - 1)
            keep = (sid == S - 1) & (oidx >= 0)
            slot = jnp.clip(oidx, 0, M - 1)
            outputs = outputs.at[slot].set(
                jnp.where(keep, y, outputs[slot]))
            nxt = lax.ppermute(y, axis_name,
                               [(i, (i + 1) % S) for i in range(S)])
            return (nxt, outputs), None

        (_, outputs), _ = lax.scan(tick, (state, outputs),
                                   jnp.arange(M + S - 1))
        # only the last stage holds real outputs; psum replicates them
        # (every other stage contributes zeros)
        return lax.psum(outputs, axis_name)

    xm = x.reshape((n_micro, B // n_micro) + x.shape[1:])
    w_specs = jax.tree_util.tree_map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params)
    act_spec = P(None, data_axis) if data_axis else P()
    out = shard_map(local, mesh=mesh,
                    in_specs=(w_specs, act_spec), out_specs=act_spec)(stacked_params, xm)
    return out.reshape((B,) + out.shape[2:])
