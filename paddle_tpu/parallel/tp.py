"""Tensor parallelism via GSPMD sharding annotations.

TPU-native replacement for the reference's model-parallel story (the
reference had none beyond the sparse distributed lookup table —
transpiler/distribute_lookup_table.py); on TPU, tensor parallelism is the
first-class way to scale beyond data parallel.  Instead of rewriting the
program into send/recv ops, we annotate parameter shardings over the
'model' mesh axis and let XLA GSPMD propagate and insert the all-reduces
over ICI.

Megatron-style layout for transformer blocks:
  attention q/k/v proj   [d, d]        -> P(None, 'model')   (head-sharded)
  attention out proj     [d, d]        -> P('model', None)   (row; AR after)
  ffn fc1                [d, 4d]       -> P(None, 'model')   (column)
  ffn fc2                [4d, d]       -> P('model', None)   (row; AR after)
  embedding              [V, d]        -> P('model', None)   (vocab-sharded)
  output proj            [d, V]        -> P(None, 'model')
Biases of column-parallel layers follow their output dim; layer norms stay
replicated.
"""
import re

from jax.sharding import PartitionSpec as P

__all__ = ['shard_program_tp', 'annotate']

# (regex on parameter name, spec factory given ndim)
_RULES = [
    (re.compile(r'.*(_q|_k|_v)_w$'), lambda nd: P(None, 'model')),
    # fused projections (transformer.py r5: q,k,v as one d x 3d GEMM /
    # cross-attention k,v as d x 2d) — column-parallel like the parts
    (re.compile(r'.*(_qkv|_kv)_w$'), lambda nd: P(None, 'model')),
    (re.compile(r'.*_o_w$'), lambda nd: P('model', None)),
    (re.compile(r'.*_fc1_w$'), lambda nd: P(None, 'model')),
    (re.compile(r'.*_fc1_b$'), lambda nd: P('model')),
    (re.compile(r'.*_fc2_w$'), lambda nd: P('model', None)),
    (re.compile(r'.*_emb$'), lambda nd: P('model', None)),
    (re.compile(r'.*proj_w$'), lambda nd: P(None, 'model')),
    (re.compile(r'.*proj_b$'), lambda nd: P('model')),
]


def annotate(program, name, spec):
    """Attach an explicit PartitionSpec to variable `name`."""
    program.set_sharding(name, spec)
    return program


def shard_program_tp(program, extra_rules=None, axis='model'):
    """Walk the program's parameters and annotate transformer-style weights
    over the tensor-parallel mesh axis.  Optimizer accumulators (moment_*,
    velocity_*, …) inherit their parameter's spec so the whole optimizer
    state is sharded too (ZeRO-ish for the model axis).

    Returns the list of (name, spec) annotations applied."""
    rules = list(_RULES) + list(extra_rules or [])
    block = program.global_block()
    applied = []

    def match(name, ndim):
        for rx, mk in rules:
            if rx.match(name):
                spec = mk(ndim)
                if axis != 'model':
                    spec = P(*[axis if p == 'model' else p for p in spec])
                return spec
        return None

    from ..core.framework import Parameter
    params = {n: v for n, v in block.vars.items()
              if isinstance(v, Parameter) or v.persistable}
    for name, v in params.items():
        base = name
        # optimizer accumulators are named e.g. moment1_0.w_0 or
        # <param>_moment_0; match on the embedded parameter name
        spec = match(base, len(v.shape or ()))
        if spec is None:
            for pname in params:
                if pname != base and pname in base and match(
                        pname, len(v.shape or ())) is not None and \
                        tuple(v.shape or ()) == tuple(
                            block.vars[pname].shape or ()):
                    spec = match(pname, len(v.shape or ()))
                    break
        if spec is not None and name not in program._sharding:
            program.set_sharding(name, spec)
            applied.append((name, spec))
    return applied
