"""Mixture-of-Experts with expert parallelism (GShard/Switch-style).

No reference equivalent (Fluid predates MoE); first-class here because
expert parallelism is a core TPU scaling axis (SURVEY §2.4).  Design is
the GSPMD dense-dispatch formulation: routing is expressed as dispatch /
combine einsums over a capacity-bounded buffer, experts are stacked with a
leading E dim sharded over the expert mesh axis, and XLA GSPMD turns the
dispatch einsums into all-to-alls over ICI.  Static shapes throughout
(capacity factor bounds the per-expert token count), so one compile.
"""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['moe_ffn', 'top2_gating', 'init_moe_params']


def top2_gating(logits, capacity):
    """GShard top-2 gating.  logits: [G, S, E] (groups × tokens × experts).
    Returns (dispatch [G,S,E,C] bool-ish float, combine [G,S,E,C] float,
    aux_loss scalar)."""
    G, S, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    g1_idx = jnp.argmax(probs, axis=-1)                      # [G,S]
    mask1 = jax.nn.one_hot(g1_idx, E, dtype=probs.dtype)
    probs2 = probs * (1.0 - mask1)
    g2_idx = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(g2_idx, E, dtype=probs.dtype)

    # load-balancing auxiliary loss (Switch eq. 4): E * <fraction routed
    # to e> . <mean gate prob of e>
    density = mask1.mean(axis=1)                             # [G,E]
    density_proxy = probs.mean(axis=1)
    aux = (density * density_proxy).sum(axis=-1).mean() * E

    # positions within each expert's capacity buffer (running count)
    pos1 = (jnp.cumsum(mask1, axis=1) - mask1)               # [G,S,E]
    mask1 = mask1 * (pos1 < capacity)
    pos2 = (jnp.cumsum(mask2, axis=1) - mask2
            + mask1.sum(axis=1, keepdims=True))
    mask2 = mask2 * (pos2 < capacity)

    g1 = (probs * mask1).sum(axis=-1)                        # [G,S]
    g2 = (probs * mask2).sum(axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    loc1 = jax.nn.one_hot((pos1 * mask1).sum(-1).astype(jnp.int32),
                          capacity, dtype=probs.dtype)       # [G,S,C]
    loc2 = jax.nn.one_hot((pos2 * mask2).sum(-1).astype(jnp.int32),
                          capacity, dtype=probs.dtype)
    combine = (g1[..., None, None] * mask1[..., None] * loc1[:, :, None]
               + g2[..., None, None] * mask2[..., None] * loc2[:, :, None])
    dispatch = (combine > 0).astype(probs.dtype)             # [G,S,E,C]
    return dispatch, combine, aux


def init_moe_params(key, d_model, d_ff, n_expert, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = d_model ** -0.5
    return {
        'gate_w': jax.random.normal(k1, (d_model, n_expert), dtype) * s1,
        'wi': jax.random.normal(k2, (n_expert, d_model, d_ff), dtype) * s1,
        'wo': jax.random.normal(k3, (n_expert, d_ff, d_model),
                                dtype) * (d_ff ** -0.5),
    }


def moe_ffn(params, x, capacity_factor=2.0):
    """MoE feed-forward.  x: [G, S, D] (G groups = batch rows or shards).

    Pure-JAX path: annotate `params['wi']/['wo']` with P(expert_axis, ..)
    (see `shard_moe`) and GSPMD emits the all-to-alls.  Returns
    (y [G,S,D], aux_loss)."""
    G, S, D = x.shape
    E = params['wi'].shape[0]
    C = int(capacity_factor * S / E) or 1
    logits = jnp.einsum('gsd,de->gse', x, params['gate_w'])
    dispatch, combine, aux = top2_gating(logits, C)
    # all-to-all happens here under GSPMD (tokens → their expert's shard)
    xe = jnp.einsum('gsec,gsd->egcd', dispatch, x)           # [E,G,C,D]
    h = jnp.einsum('egcd,edf->egcf', xe, params['wi'])
    h = jax.nn.relu(h)
    ye = jnp.einsum('egcf,efd->egcd', h, params['wo'])
    y = jnp.einsum('gsec,egcd->gsd', combine, ye)
    return y, aux


def shard_moe(program, names=('wi', 'wo'), expert_axis='model'):
    """Annotate stacked expert weights: leading E dim over the expert
    axis."""
    for n in names:
        program.set_sharding(n, P(expert_axis, None, None))
    return program
