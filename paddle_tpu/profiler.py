"""Profiler (parity: python/paddle/fluid/profiler.py) over jax.profiler.

cuda_profiler/profiler/start_profiler map to XLA trace capture; traces are
viewable in TensorBoard / Perfetto (xplane), replacing the reference's
nvprof/chrome-tracing output.
"""
import contextlib
import time

__all__ = ['cuda_profiler', 'reset_profiler', 'profiler', 'start_profiler',
           'stop_profiler']

_trace_dir = ['/tmp/paddle_tpu_profile']
_active = [False]


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    with profiler('All', output_file=output_file):
        yield


def reset_profiler():
    pass


def start_profiler(state='All', tracer_option=None):
    import jax
    if not _active[0]:
        jax.profiler.start_trace(_trace_dir[0])
        _active[0] = True


def stop_profiler(sorted_key=None, profile_path=None):
    import jax
    if _active[0]:
        jax.profiler.stop_trace()
        _active[0] = False
        print('[paddle_tpu.profiler] trace written to %s' % _trace_dir[0])


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path=None,
             output_file=None):
    if profile_path or output_file:
        _trace_dir[0] = profile_path or output_file
    start_profiler(state)
    t0 = time.time()
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
        print('[paddle_tpu.profiler] wall %.3fs' % (time.time() - t0))
