"""Profiler (parity: python/paddle/fluid/profiler.py) over jax.profiler +
the in-process observability recorder (paddle_tpu/observability/).

cuda_profiler/profiler/start_profiler map to XLA trace capture; traces are
viewable in TensorBoard / Perfetto (xplane).  On stop the recorder's own
span timeline is ALSO written as `paddle_tpu_trace.json` into the trace
dir — a Chrome-trace file that loads directly in ui.perfetto.dev or
chrome://tracing, replacing the reference's chrome-tracing output.

`profiler(sorted_key=...)` prints the reference-style sorted summary
table (Event / Calls / Total / Min / Max / Ave / Ratio) aggregated from
the recorded spans; `reset_profiler()` clears recorded spans, counters,
and retrace reports (reference parity: it clears the event buffers).
"""
import contextlib
import os
import time

from . import observability as _obs

__all__ = ['cuda_profiler', 'reset_profiler', 'profiler', 'start_profiler',
           'stop_profiler', 'print_summary']

_trace_dir = ['/tmp/paddle_tpu_profile']
_active = [False]

_SORT_FIELDS = {'calls': 'calls', 'total': 'total_us', 'min': 'min_us',
                'max': 'max_us', 'ave': 'ave_us'}


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    with profiler('All', output_file=output_file):
        yield


def reset_profiler():
    """Clear every recorded span, counter, and retrace report."""
    _obs.reset()


def start_profiler(state='All', tracer_option=None):
    import jax
    if not _active[0]:
        jax.profiler.start_trace(_trace_dir[0])
        _active[0] = True


def stop_profiler(sorted_key=None, profile_path=None):
    import jax
    if _active[0]:
        jax.profiler.stop_trace()
        _active[0] = False
        try:
            _obs.export_chrome_trace(
                os.path.join(_trace_dir[0], 'paddle_tpu_trace.json'))
        except OSError:
            pass  # trace dir unwritable: the xplane dump already failed too
        print('[paddle_tpu.profiler] trace written to %s' % _trace_dir[0])
    if sorted_key:
        print_summary(sorted_key)


def print_summary(sorted_key='total', limit=50):
    """Reference-style sorted op-stat table over the recorded spans.

    sorted_key: 'calls' | 'total' | 'min' | 'max' | 'ave' (the reference
    profiler's sorted_key values); anything else keeps insertion order.
    """
    summary = _obs.span_summary()
    rows = list(summary.items())
    field = _SORT_FIELDS.get(sorted_key)
    if field:
        rows.sort(key=lambda kv: kv[1][field], reverse=True)
    grand_total = sum(s['total_us'] for _, s in rows) or 1.0
    print('------------------------->'
          '     Profiling Report     <-------------------------')
    print('%-32s %8s %12s %12s %12s %12s %8s'
          % ('Event', 'Calls', 'Total(ms)', 'Min(ms)', 'Max(ms)',
             'Ave(ms)', 'Ratio'))
    for name, s in rows[:limit]:
        print('%-32s %8d %12.3f %12.3f %12.3f %12.3f %7.2f%%'
              % (name[:32], s['calls'], s['total_us'] / 1e3,
                 s['min_us'] / 1e3, s['max_us'] / 1e3, s['ave_us'] / 1e3,
                 100.0 * s['total_us'] / grand_total))
    if not rows:
        print('  <no spans recorded>')


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path=None,
             output_file=None):
    # _trace_dir is restored on exit: a scoped profile_path must not
    # permanently redirect every later start_profiler() call
    old_dir = _trace_dir[0]
    if profile_path or output_file:
        _trace_dir[0] = profile_path or output_file
    start_profiler(state)
    t0 = time.time()
    try:
        yield
    finally:
        try:
            stop_profiler(sorted_key, profile_path)
            print('[paddle_tpu.profiler] wall %.3fs' % (time.time() - t0))
        finally:
            _trace_dir[0] = old_dir
