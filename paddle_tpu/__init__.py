"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid (reference: BillXW/Paddle @ /root/reference).

Architecture (TPU-first, NOT a port):
  * declarative Program/Block/Op graph API (`paddle_tpu.layers`) — source
    compatible with fluid model code
  * whole-block lowering to ONE XLA executable per train step
    (core/executor.py), autodiff via jax.vjp (core/backward.py)
  * ragged sequences as padded+lengths (core/lod.py), RNNs as lax.scan
  * data/model parallel via jax.sharding Mesh + GSPMD (parallel/)

Use `import paddle_tpu as fluid` for fluid-style code, or
`import paddle_tpu.paddle_compat as paddle` for `paddle.*` dataset/batch
helpers.
"""
from .core import framework
from .core.framework import (  # noqa
    Program, Block, Operator, Variable, Parameter, program_guard,
    default_main_program, default_startup_program, switch_main_program,
    name_scope, CPUPlace, CUDAPlace, TPUPlace, CUDAPinnedPlace, cpu_places,
    cuda_places, tpu_places, is_compiled_with_cuda, get_flags, set_flags)
from .core.executor import Executor, Scope, scope_guard, global_scope  # noqa
from .core.async_runtime import FetchFuture  # noqa
from .core.backward import append_backward, gradients, calc_gradient  # noqa
from .core import unique_name  # noqa
from .core.lod import (LoDTensor, create_lod_tensor,  # noqa
                       create_random_int_lodtensor)
from .core import backward  # noqa
from . import layers  # noqa
from . import nets  # noqa
from . import initializer  # noqa
from .initializer import force_init_on_cpu, init_on_cpu  # noqa
from . import optimizer  # noqa
from . import regularizer  # noqa
from . import clip  # noqa
from .clip import set_gradient_clip  # noqa
from . import metrics  # noqa
from . import io  # noqa
from . import profiler  # noqa
from . import param_attr  # noqa
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa
from .data_feeder import DataFeeder, FeedPrefetcher, FeedBucketer  # noqa
from . import reader  # noqa
from .batch import batch  # noqa
from .io import (save_inference_model, load_inference_model,  # noqa
                 save_params, load_params, save_persistables,
                 load_persistables)
from . import compiler  # noqa
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa
from .parallel.parallel_executor import ParallelExecutor  # noqa
from . import transpiler  # noqa
from .transpiler import (DistributeTranspiler,  # noqa
                         DistributeTranspilerConfig, memory_optimize,
                         release_memory, InferenceTranspiler)
from . import dataset  # noqa
from . import imperative  # noqa
from . import debugger  # noqa
from . import inference  # noqa
from . import serving  # noqa
from . import train  # noqa
from . import average  # noqa
from . import evaluator  # noqa
from . import contrib  # noqa
from . import trainer  # noqa
from . import inferencer  # noqa
from .trainer import Trainer, BeginEpochEvent, EndEpochEvent, \
    BeginStepEvent, EndStepEvent, CheckpointConfig  # noqa
from .inferencer import Inferencer  # noqa
from . import annotations  # noqa
from . import analysis  # noqa
from . import net_drawer  # noqa
from . import recordio_writer  # noqa
from . import async_executor  # noqa
from .async_executor import AsyncExecutor  # noqa
from .data_feed_desc import DataFeedDesc  # noqa


from .core.framework import recompute_scope  # noqa

# submodule aliases for reference-style imports (`from paddle.fluid
# import executor`, `fluid.lod_tensor.create_lod_tensor(...)`, ...)
from .core import executor  # noqa
from .core import layer_helper  # noqa
from .core import lod as lod_tensor  # noqa
from .parallel import parallel_executor  # noqa


def recompute(fn, *args, **kwargs):
    """jax.checkpoint for raw JAX callables (graph programs use
    `recompute_scope()`); SURVEY §2.1 memory_optimize replacement."""
    import jax
    return jax.checkpoint(fn, *args, **kwargs)


def memory_optimize_hint(*a, **k):
    return None


__version__ = '0.1.0'
