"""Token streaming through the engine reply path.

A :class:`TokenStream` is the ServeFuture of a generation request,
extended with incremental per-token delivery: the scheduler ``_push``es
each sampled token as its decode window completes, and the client
iterates ``tokens()`` while the request is still running.  The terminal
reply keeps the PR-8 contract exactly — one ServeResult, resolved once
(EOS, max-token, cancel, deadline, error, or shutdown shed), which also
closes the token iterator.  ``cancel()`` is a client-side flag the
scheduler sweeps at the next round boundary, retiring the request as a
``shed`` reply with reason ``cancelled``.

Memory pressure surfaces here too: a stream the paged KV pool cannot
grow mid-flight is closed with a terminal ``error`` reply carrying
reason ``kv_oom`` — the tokens already streamed stay readable via
``tokens_so_far()``, and the output is NEVER silently truncated into a
fake ``ok``.
"""
import queue
import threading

from ..engine import ServeFuture

__all__ = ['TokenStream']

_DONE = object()


class TokenStream(ServeFuture):
    """Client handle for one generation request: iterate ``tokens()``
    for live delivery, then (or instead) block on ``result()`` for the
    terminal reply.  ``ok`` results carry ``reason`` ``'eos'`` or
    ``'max_tokens'`` and ``outputs=[generated_ids]``."""
    __slots__ = ('_tokens_q', '_emitted', '_cancelled')

    def __init__(self):
        ServeFuture.__init__(self)
        self._tokens_q = queue.Queue()
        self._emitted = []
        self._cancelled = threading.Event()

    # ------------------------------------------------- scheduler side
    def _push(self, token):
        self._emitted.append(int(token))
        self._tokens_q.put(int(token))

    def _resolve(self, result):
        first = ServeFuture._resolve(self, result)
        if first:
            self._tokens_q.put(_DONE)   # close any live tokens() iterator
        return first

    # ---------------------------------------------------- client side
    def cancel(self):
        """Ask the scheduler to stop decoding this request.  Swept at
        the next round boundary; already-terminal requests ignore it."""
        self._cancelled.set()

    @property
    def cancelled(self):
        return self._cancelled.is_set()

    def tokens(self, timeout=None):
        """Yield token ids as they arrive until the terminal reply.
        ``timeout`` bounds the wait for EACH token (TimeoutError)."""
        while True:
            try:
                item = self._tokens_q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError('no token within %r s' % (timeout,))
            if item is _DONE:
                return
            yield item

    def tokens_so_far(self):
        """Snapshot of everything streamed so far (no blocking)."""
        return list(self._emitted)
