"""Paged KV cache: a shared page pool + per-slot block tables.

One preallocated pair of ``[pages, layers, kv_heads, page_len,
head_dim]`` pools holds every in-flight request's keys/values; a
request owns a *slot* (its row in the fixed-width decode batch) and a
list of *pages* its block table maps, so its memory footprint is
``ceil(len / page_len)`` pages instead of a dense ``max_len`` strip.
Allocation is a host-side free list over pages (`PagePool`); device
state is the pool pair plus per-slot ``lengths`` / ``tok`` vectors,
threaded as DONATED carry through the fused decode loop (decode.py).
Block tables are plain per-launch DATA (int32 ``[slots, max_pages]``
arrays), never part of an executable signature.

Page 0 is the reserved GARBAGE page: unmapped block-table entries are
0, so an inactive slot's masked ride-along write lands there and is
never attended (the positional mask ``kpos <= qpos`` already makes any
row beyond a slot's live length unreachable).  Freed pages are never
zeroed — reuse is metadata-only, O(1), zero device work.

``quant='int8'`` stores the pools as int8 with one float32 scale per
written row (per token, per kv head): ``scale = amax/127`` on write,
dequantized inside the attention window (decode.py) with float32
accumulation.  Kill switch: ``PT_KV_QUANT=0``.

`PrefixCache` maps chain-hashed FULL prompt pages to refcounted page
ids so requests sharing a prompt prefix map the same read-only pages
instead of re-prefilling them.  Shared pages are full by construction,
so a request's own writes (its prompt tail and generated tokens)
always land in freshly allocated pages — copy-on-extend needs no copy.
Kill switch: ``PT_PREFIX_CACHE=0``.
"""
import hashlib
import threading
from collections import OrderedDict

import numpy as np

from ... import observability as _obs
from ...testing import faults as _faults

__all__ = ['CacheConfig', 'SlotAllocator', 'PagePool', 'PrefixCache',
           'init_state', 'default_page_len']


def default_page_len(max_len, want=8):
    """Largest divisor of ``max_len`` that is <= ``want`` (page length
    must tile the context window exactly)."""
    max_len, want = int(max_len), int(want)
    for d in range(min(want, max_len), 0, -1):
        if max_len % d == 0:
            return d
    return 1


class CacheConfig(object):
    """Geometry of the paged KV pool.

    ``slots`` is the decode-batch width (rows of ``lengths``/``tok``
    and of the block table); ``pages`` the pool depth INCLUDING the
    reserved garbage page 0; ``page_len`` tokens per page (must divide
    ``max_len``); ``quant`` is ``'none'`` or ``'int8'``.
    """
    __slots__ = ('slots', 'layers', 'kv_heads', 'max_len', 'head_dim',
                 'dtype', 'page_len', 'pages', 'quant')

    def __init__(self, slots, layers, kv_heads, max_len, head_dim,
                 dtype='float32', page_len=None, pages=None, quant='none'):
        if int(slots) < 1:
            raise ValueError('kv cache needs >= 1 slot, got %r' % (slots,))
        self.slots = int(slots)
        self.layers = int(layers)
        self.kv_heads = int(kv_heads)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.dtype = str(dtype)
        self.page_len = (default_page_len(self.max_len) if page_len is None
                         else int(page_len))
        if self.page_len < 1 or self.max_len % self.page_len:
            raise ValueError('page_len=%r must divide max_len=%d'
                             % (page_len, self.max_len))
        # default pool: dense-equivalent capacity (every slot can grow
        # to max_len) + the garbage page — callers shrink it to create
        # real memory pressure
        self.pages = (self.slots * self.max_pages + 1 if pages is None
                      else int(pages))
        if self.pages < 2:
            raise ValueError('kv pool needs >= 2 pages (page 0 is the '
                             'reserved garbage page), got %r' % (pages,))
        self.quant = str(quant or 'none')
        if self.quant not in ('none', 'int8'):
            raise ValueError("quant must be 'none' or 'int8', got %r"
                             % (quant,))

    @property
    def max_pages(self):
        """Block-table width: pages needed for a max_len sequence."""
        return self.max_len // self.page_len

    @property
    def store_dtype(self):
        return 'int8' if self.quant == 'int8' else self.dtype

    @property
    def pool_shape(self):
        return (self.pages, self.layers, self.kv_heads, self.page_len,
                self.head_dim)

    @property
    def scale_shape(self):
        """Per-row dequant scales (int8 mode): one f32 per written
        (page, layer, kv head, row)."""
        return (self.pages, self.layers, self.kv_heads, self.page_len)

    @property
    def page_shape(self):
        """Back-compat alias: the K (or V) pool shape."""
        return self.pool_shape

    def pages_for(self, n_tokens):
        """Pages a sequence of ``n_tokens`` occupies."""
        return -(-max(0, int(n_tokens)) // self.page_len)

    def page_bytes(self):
        """Bytes ONE page costs across both pools (K+V, plus the scale
        rows when quantized) — the unit of the kv_bytes gauges."""
        per = int(np.dtype(self.store_dtype).itemsize)
        elems = self.layers * self.kv_heads * self.page_len * self.head_dim
        b = 2 * per * elems
        if self.quant == 'int8':
            b += 2 * 4 * self.layers * self.kv_heads * self.page_len
        return b

    def bytes(self):
        """Total K+V pool bytes (capacity-planning helper)."""
        return self.pages * self.page_bytes()

    def dense_slot_bytes(self):
        """What ONE slot would reserve under the dense PR-11 layout (a
        full float32 max_len strip) — the denominator of the density
        headline."""
        per = int(np.dtype(self.dtype).itemsize)
        return 2 * per * (self.layers * self.kv_heads * self.max_len *
                          self.head_dim)

    def spec(self):
        """Declarative blob for the AOT cache fingerprint."""
        return {'slots': self.slots, 'layers': self.layers,
                'kv_heads': self.kv_heads, 'max_len': self.max_len,
                'head_dim': self.head_dim, 'dtype': self.dtype,
                'page_len': self.page_len, 'pages': self.pages,
                'quant': self.quant}


def init_state(cache_cfg):
    """Fresh device-side decode state: the K/V page pools plus per-slot
    ``lengths`` (tokens written so far) and ``tok`` (the next token to
    feed — set by prefill, advanced by every decode step).  int8 mode
    adds the per-row dequant scale pools."""
    import jax.numpy as jnp
    k = jnp.zeros(cache_cfg.pool_shape, jnp.dtype(cache_cfg.store_dtype))
    st = {'k': k, 'v': jnp.zeros_like(k),
          'lengths': jnp.zeros((cache_cfg.slots,), jnp.int32),
          'tok': jnp.zeros((cache_cfg.slots,), jnp.int32)}
    if cache_cfg.quant == 'int8':
        ks = jnp.zeros(cache_cfg.scale_shape, jnp.float32)
        st['k_scale'] = ks
        st['v_scale'] = jnp.zeros_like(ks)
    return st


class SlotAllocator(object):
    """Free-list slot allocation.  Lowest-index-first for deterministic
    placement (the same admission order always lands on the same slots,
    which keeps soak runs reproducible).  Exports the live occupancy as
    the ``generation.kv_slots_in_use`` gauge.  Slots are cheap batch
    rows — the MEMORY gate is the PagePool."""

    def __init__(self, slots):
        self._capacity = int(slots)
        self._free = list(range(self._capacity))
        self._lock = threading.Lock()
        _obs.metrics.gauge('generation.kv_slots_in_use').set(0)

    @property
    def capacity(self):
        return self._capacity

    def free_count(self):
        with self._lock:
            return len(self._free)

    def in_use(self):
        return self._capacity - self.free_count()

    def alloc(self):
        """Claim the lowest free slot, or None when fully occupied."""
        with self._lock:
            if not self._free:
                return None
            slot = min(self._free)
            self._free.remove(slot)
            used = self._capacity - len(self._free)
        _obs.metrics.gauge('generation.kv_slots_in_use').set(used)
        return slot

    def free(self, slot):
        slot = int(slot)
        with self._lock:
            if not 0 <= slot < self._capacity:
                raise ValueError('slot %d out of range [0, %d)'
                                 % (slot, self._capacity))
            if slot in self._free:
                raise ValueError('double free of kv slot %d' % slot)
            self._free.append(slot)
            used = self._capacity - len(self._free)
        _obs.metrics.gauge('generation.kv_slots_in_use').set(used)

    def reset(self):
        with self._lock:
            self._free = list(range(self._capacity))
        _obs.metrics.gauge('generation.kv_slots_in_use').set(0)


class PagePool(object):
    """Refcounted free-list allocation over the KV page pool.

    Page 0 is reserved (the garbage page) and never handed out.
    ``alloc`` is all-or-nothing and lowest-index-first (deterministic
    placement); when short it asks the optional ``evict`` callback
    (the PrefixCache) to drop unreferenced cached pages, oldest first.
    Shared pages (prefix-cache hits) carry one refcount per holder and
    return to the free list only when the LAST holder releases.

    Exhaustion is a clean ``None`` — the scheduler turns it into
    admission backpressure (stay queued) or a terminal ``kv_oom``
    reply, never a truncation.  The ``kv_oom`` fault site forces the
    next allocation(s) to report exhaustion on demand.

    Gauges: ``generation.kv_pages_in_use``, ``generation.
    kv_bytes_reserved`` (fixed pool footprint) and ``generation.
    kv_bytes_live`` (pages in use x page_bytes).
    """

    def __init__(self, cache_cfg):
        self._cfg = cache_cfg
        self._page_bytes = cache_cfg.page_bytes()
        self._capacity = cache_cfg.pages - 1      # page 0 reserved
        self._free = list(range(1, cache_cfg.pages))
        self._refs = {}
        self._lock = threading.RLock()
        _obs.metrics.gauge('generation.kv_bytes_reserved').set(
            cache_cfg.bytes())
        self._set_gauges(0)

    def _set_gauges(self, used):
        _obs.metrics.gauge('generation.kv_pages_in_use').set(used)
        _obs.metrics.gauge('generation.kv_bytes_live').set(
            used * self._page_bytes)

    @property
    def capacity(self):
        """Allocatable pages (the garbage page excluded)."""
        return self._capacity

    @property
    def page_bytes(self):
        return self._page_bytes

    def free_count(self):
        with self._lock:
            return len(self._free)

    def in_use(self):
        return self._capacity - self.free_count()

    def alloc(self, n, evict=None):
        """Claim ``n`` pages (refcount 1 each) or None — all or
        nothing.  ``evict`` is called repeatedly (under the pool lock;
        it may re-enter release()) while the free list is short."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            if _faults.any_active() and _faults.fire('kv_oom'):
                return None
            while len(self._free) < n and evict is not None:
                if not evict():
                    break
            if len(self._free) < n:
                return None
            self._free.sort()
            got, self._free = self._free[:n], self._free[n:]
            for p in got:
                self._refs[p] = 1
            self._set_gauges(self._capacity - len(self._free))
        return got

    def retain(self, pages):
        """One more holder for already-allocated pages (shared prefix
        hits)."""
        with self._lock:
            for p in pages:
                if p not in self._refs:
                    raise ValueError('retain of unallocated kv page %d'
                                     % int(p))
                self._refs[p] += 1

    def release(self, pages):
        """Drop one holder per page; pages reaching refcount 0 return
        to the free list (never zeroed — positional masking makes stale
        rows unreachable)."""
        with self._lock:
            for p in pages:
                p = int(p)
                c = self._refs.get(p)
                if c is None:
                    raise ValueError('release of free kv page %d' % p)
                if c > 1:
                    self._refs[p] = c - 1
                else:
                    del self._refs[p]
                    self._free.append(p)
            self._set_gauges(self._capacity - len(self._free))

    def refcount(self, page):
        with self._lock:
            return self._refs.get(int(page), 0)

    def reset(self):
        with self._lock:
            self._free = list(range(1, self._cfg.pages))
            self._refs.clear()
            self._set_gauges(0)


def _chain_digest(prev, tokens):
    h = hashlib.sha1(prev)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.digest()


class PrefixCache(object):
    """Fingerprinted prefix -> pages map at FULL-page granularity.

    Keys are chained page digests: ``h_j = sha1(h_{j-1} || tokens of
    page j)``, so a depth-j entry certifies the whole prefix, not one
    page.  Each entry holds its own refcount (via PagePool.retain) on
    every page of its chain; `match` retains the matched pages again
    FOR THE CALLER, so a cached page is pinned while any stream maps
    it and survives (cached) after all streams retire.

    Matching is capped at ``(prompt_len - 1) // page_len`` pages so at
    least one suffix token always prefills — the final chunk's forward
    pass is what produces the request's first-token logits.  Shared
    pages hold bitwise-identical K/V to a cold prefill (position-
    absolute RoPE, deterministic per-row math), which is what makes
    hit-vs-cold streams bitwise equal (pinned in tests).

    Eviction is deterministic: `evict_one` drops the OLDEST entry (its
    retains; pages free only once unreferenced) — wired as PagePool's
    under-pressure callback.
    """

    def __init__(self, pool, page_len):
        self._pool = pool
        self._page_len = int(page_len)
        self._entries = OrderedDict()     # digest -> tuple(pages)
        self._lock = threading.Lock()

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def _keys_for(self, prompt, depth):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        keys, h = [], b'pt-prefix-v1'
        for j in range(depth):
            h = _chain_digest(
                h, prompt[j * self._page_len:(j + 1) * self._page_len])
            keys.append(h)
        return keys

    def match(self, prompt):
        """Longest cached full-page prefix of ``prompt``.  Returns the
        page list (retained for the caller — release them with the rest
        of the request's pages) — [] on a miss."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cap = max(0, (prompt.size - 1) // self._page_len)
        if cap == 0:
            return []
        keys = self._keys_for(prompt, cap)
        with self._lock:
            for j in range(cap, 0, -1):
                pages = self._entries.get(keys[j - 1])
                if pages is not None:
                    self._pool.retain(pages)
                    _obs.metrics.counter('generation.prefix_hits').inc()
                    _obs.metrics.counter(
                        'generation.prefix_pages_reused').inc(len(pages))
                    return list(pages)
        return []

    def insert(self, prompt, pages):
        """Publish a freshly-prefilled request's FULL pages (``pages``
        = its block-table prefix).  Every depth 1..full gets an entry
        so later prompts sharing a shorter prefix still hit; existing
        entries are kept (first writer wins — contents are bitwise
        identical by construction)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        full = prompt.size // self._page_len
        full = min(full, len(pages))
        if full == 0:
            return 0
        keys = self._keys_for(prompt, full)
        added = 0
        with self._lock:
            for j in range(1, full + 1):
                if keys[j - 1] in self._entries:
                    continue
                chain = tuple(int(p) for p in pages[:j])
                self._pool.retain(chain)
                self._entries[keys[j - 1]] = chain
                added += 1
        if added:
            _obs.metrics.counter('generation.prefix_inserts').inc(added)
        return added

    def evict_one(self):
        """Drop the oldest entry (deterministic).  Returns True when an
        entry was dropped — its pages free only if nothing else holds
        them, so PagePool.alloc keeps calling until satisfied or
        empty."""
        with self._lock:
            if not self._entries:
                return False
            _key, pages = self._entries.popitem(last=False)
        self._pool.release(pages)
        _obs.metrics.counter('generation.prefix_evictions').inc()
        return True

    def reset(self):
        while self.evict_one():
            pass
