"""Slotted per-request KV cache.

One preallocated pair of ``[slots, layers, kv_heads, max_len, head_dim]``
pages holds every in-flight request's keys/values; a request owns one
slot index from admission to termination (prefill and decode write the
same row — migration is a no-op by construction).  Allocation is a
host-side free list; device state is the page pair plus per-slot
``lengths`` (tokens written) and ``tok`` (next token to feed) vectors,
threaded as DONATED carry through the fused decode loop (decode.py).

Masking is positional, not zeroing: a freed slot's stale rows are never
cleared — the next occupant's prefill SETS ``lengths[slot]`` and
overwrites positions from 0, and attention masks ``kpos <= qpos``, so
stale garbage beyond the live prefix is unreachable.  That keeps
slot turnover O(1) with zero device work.
"""
import threading

import numpy as np

from ... import observability as _obs

__all__ = ['CacheConfig', 'SlotAllocator', 'init_state']


class CacheConfig(object):
    """Geometry of the slotted cache pages."""
    __slots__ = ('slots', 'layers', 'kv_heads', 'max_len', 'head_dim',
                 'dtype')

    def __init__(self, slots, layers, kv_heads, max_len, head_dim,
                 dtype='float32'):
        if int(slots) < 1:
            raise ValueError('kv cache needs >= 1 slot, got %r' % (slots,))
        self.slots = int(slots)
        self.layers = int(layers)
        self.kv_heads = int(kv_heads)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.dtype = str(dtype)

    @property
    def page_shape(self):
        return (self.slots, self.layers, self.kv_heads, self.max_len,
                self.head_dim)

    def bytes(self):
        """Total K+V page bytes (capacity-planning helper)."""
        per = int(np.dtype(self.dtype).itemsize)
        return 2 * per * int(np.prod(self.page_shape))

    def spec(self):
        """Declarative blob for the AOT cache fingerprint."""
        return {'slots': self.slots, 'layers': self.layers,
                'kv_heads': self.kv_heads, 'max_len': self.max_len,
                'head_dim': self.head_dim, 'dtype': self.dtype}


def init_state(cache_cfg):
    """Fresh device-side decode state: the K/V pages plus per-slot
    ``lengths`` (tokens written so far) and ``tok`` (the next token to
    feed — set by prefill, advanced by every decode step)."""
    import jax.numpy as jnp
    k = jnp.zeros(cache_cfg.page_shape, jnp.dtype(cache_cfg.dtype))
    return {'k': k, 'v': jnp.zeros_like(k),
            'lengths': jnp.zeros((cache_cfg.slots,), jnp.int32),
            'tok': jnp.zeros((cache_cfg.slots,), jnp.int32)}


class SlotAllocator(object):
    """Free-list slot allocation.  Lowest-index-first for deterministic
    placement (the same admission order always lands on the same slots,
    which keeps soak runs reproducible).  Exports the live occupancy as
    the ``generation.kv_slots_in_use`` gauge."""

    def __init__(self, slots):
        self._capacity = int(slots)
        self._free = list(range(self._capacity))
        self._lock = threading.Lock()
        _obs.metrics.gauge('generation.kv_slots_in_use').set(0)

    @property
    def capacity(self):
        return self._capacity

    def free_count(self):
        with self._lock:
            return len(self._free)

    def in_use(self):
        return self._capacity - self.free_count()

    def alloc(self):
        """Claim the lowest free slot, or None when fully occupied."""
        with self._lock:
            if not self._free:
                return None
            slot = min(self._free)
            self._free.remove(slot)
            used = self._capacity - len(self._free)
        _obs.metrics.gauge('generation.kv_slots_in_use').set(used)
        return slot

    def free(self, slot):
        slot = int(slot)
        with self._lock:
            if not 0 <= slot < self._capacity:
                raise ValueError('slot %d out of range [0, %d)'
                                 % (slot, self._capacity))
            if slot in self._free:
                raise ValueError('double free of kv slot %d' % slot)
            self._free.append(slot)
            used = self._capacity - len(self._free)
        _obs.metrics.gauge('generation.kv_slots_in_use').set(used)

    def reset(self):
        with self._lock:
            self._free = list(range(self._capacity))
        _obs.metrics.gauge('generation.kv_slots_in_use').set(0)
