"""GenerationEngine — prefill/decode continuous batching on the serving
engine.

The PR-8 ServingEngine coalesces same-signature one-shot requests into
superbatches; generation requests are long-lived instead, so this
subclass replaces the dispatch loop with a round-based scheduler over
the DecodeRuntime's KV slots:

  round := sweep (cancel / deadline / TTFT / ITL)
         → claim queued requests into free slots WITH pages
         → ONE prefill chunk for the oldest still-prefilling request
         → ONE fused decode (or speculative verify) window for ALL
           decoding slots

Memory admission is PAGED (kv_cache.PagePool): a queued request is
claimed only when a slot AND the pages for its prompt plus one decode
window are both available — pool shortage leaves it QUEUED
(``generation.kv_backpressure``), it is never truncated.  A request
whose prompt+max_new could not fit even an idle pool is refused at
admission with reason ``kv_oom``; a stream whose window cannot grow
mid-flight retires with a terminal ``error``/``kv_oom`` reply and a
flight dump carrying the pool gauge snapshot.  Prefix-cache hits skip
straight to their first unshared chunk (`DecodeRuntime.try_begin`) and
completed prompts are published for later requests (`promote_prefix`).

With ``GenerationConfig.speculative`` (default off; env
``PT_SPEC_DECODE``) the decode window becomes draft-propose + fused
VERIFY: a host-side n-gram draft proposes K-1 tokens per stream, one
batched verify pass samples the target model at every position, and
each stream keeps the longest accepted prefix
(``generation.spec_proposed`` / ``spec_accepted``) — greedy streams
are bitwise identical to non-speculative decode.

Long prompts advance one bounded chunk per round, interleaved with
full-width decode windows — a prompt of any length never stalls token
delivery for running requests (``generation.mixed_dispatches`` counts
rounds that did both).  A request lives in one slot from prefill
through decode (migration is in place by construction) and every
admitted request keeps the PR-8 guarantee: exactly one terminal reply —
``ok`` (reason ``eos`` / ``max_tokens``), ``deadline_exceeded`` (queue
wait, overall deadline, TTFT or ITL budget), ``shed`` (cancel, drain),
``rejected`` (admission), or ``error`` (decode fault / mid-stream
``kv_oom``) — through drain, stop, and injected ``decode_step`` /
``kv_oom`` faults alike.

Token-level SLOs: ``serving.ttft_ms`` observes submit→first-token per
request, ``serving.itl_ms`` the amortized inter-token gap; both export
through telemetry_snapshot('serving') (docs/generation.md).
"""
import time

import numpy as np

from ... import observability as _obs
from ...observability import flight as _flight
from ...observability import trace_context as _tc
from ...testing import faults as _faults
from ..engine import (DEADLINE_EXCEEDED, DRAINING, ERROR, OK, SHED,
                      ServingEngine, _Request)
from .sampling import SamplingParams
from .streaming import TokenStream

__all__ = ['GenerationConfig', 'GenerationEngine']


class GenerationConfig(object):
    """Generation-side knobs (the queue/rate/breaker knobs stay on
    ServingConfig).  ``decode_window`` is K, the tokens-per-launch of
    the fused decode scan; ``ttft_timeout_s`` / ``itl_timeout_s`` are
    the default per-token SLO budgets (overridable per request);
    ``speculative`` swaps the decode window for draft + fused verify
    (default off; env ``PT_SPEC_DECODE=1`` turns it on, ``=0`` is a
    hard kill switch over an explicit True)."""

    def __init__(self, decode_window=4, eos_id=None, max_new_default=16,
                 ttft_timeout_s=None, itl_timeout_s=None,
                 speculative=None):
        import os
        if int(decode_window) < 1:
            raise ValueError('decode_window must be >= 1')
        self.decode_window = int(decode_window)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.max_new_default = int(max_new_default)
        self.ttft_timeout_s = ttft_timeout_s
        self.itl_timeout_s = itl_timeout_s
        env = os.environ.get('PT_SPEC_DECODE', '').strip().lower()
        if env in ('0', 'off', 'false'):
            self.speculative = False
        elif speculative is None:
            self.speculative = env in ('1', 'on', 'true')
        else:
            self.speculative = bool(speculative)


class _GenRequest(_Request):
    __slots__ = ('prompt', 'max_new', 'params', 'ttft_timeout',
                 'itl_timeout', 'slot', 'offset', 'produced',
                 't_last_token')

    def __init__(self, prompt, max_new, params, deadline, t_submit,
                 ttft_timeout=None, itl_timeout=None, trace=None,
                 t_pc=None):
        _Request.__init__(self, {'prompt': prompt}, 1,
                          ('generate',), deadline, t_submit,
                          trace=trace, t_pc=t_pc)
        self.future = TokenStream()   # streaming reply handle
        if trace is not None:
            self.future.traceparent = trace.to_traceparent()
        self.prompt = prompt
        self.max_new = int(max_new)
        self.params = params
        self.ttft_timeout = ttft_timeout
        self.itl_timeout = itl_timeout
        self.slot = None
        self.offset = 0          # prompt tokens prefilled so far
        self.produced = 0        # tokens streamed so far
        self.t_last_token = None


class GenerationEngine(ServingEngine):
    """Streaming decode server over one :class:`DecodeRuntime`.

        engine = GenerationEngine(runtime).start()
        stream = engine.generate([1, 2, 3], max_new=32, temperature=0.8,
                                 top_k=40, seed=7)
        for tok in stream.tokens():
            ...
        reply = stream.result()       # ServeResult, reason='eos'/...

    Admission (queue bound, overflow policy, rate limit, drain gate) is
    inherited; ``submit()`` is closed off — generation requests go
    through :meth:`generate`.
    """

    def __init__(self, runtime, config=None, gen_config=None,
                 clock=time.monotonic):
        ServingEngine.__init__(self, self._no_backend, bucketer=None,
                               config=config, clock=clock)
        self.runtime = runtime
        self._gen = gen_config or GenerationConfig()
        self._active = []        # slot-holding requests, admission order

    @staticmethod
    def _no_backend(feed):
        raise TypeError('GenerationEngine has no one-shot backend; '
                        'requests go through generate()')

    def submit(self, feed, timeout_s=None):
        raise TypeError('GenerationEngine serves token streams — use '
                        'generate(prompt_ids, ...) instead of submit()')

    # ----------------------------------------------------- admission
    def _rejected_gen(self, t_submit, reason, message, trace, t_pc):
        # the base _rejected builds a plain ServeFuture; generation
        # refusals must still hand back an (already-closed) TokenStream
        from ..engine import REJECTED, ServeResult
        fut = TokenStream()
        if trace is not None:
            fut.traceparent = trace.to_traceparent()
        fut._resolve(ServeResult(REJECTED, error=message, reason=reason,
                                 latency_s=self._clock() - t_submit))
        _obs.metrics.counter('serving.rejected').inc()
        _obs.metrics.counter('serving.rejected.%s' % reason).inc()
        self._emit_root_span(trace, t_pc, REJECTED, reason=reason)
        return fut

    def generate(self, prompt_ids, max_new=None, temperature=0.0, top_k=0,
                 seed=0, timeout_s=None, ttft_timeout_s=None,
                 itl_timeout_s=None):
        """Admit one generation request; always returns a
        :class:`TokenStream` (refusals come back already terminal with a
        named reason, never an exception and never silence)."""
        t_submit = self._clock()
        obs_on = _obs.enabled()
        trace = _tc.TraceContext.new() if obs_on else None
        t_pc = time.perf_counter() if obs_on else None
        _obs.metrics.counter('serving.submitted').inc()
        try:
            prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
            params = SamplingParams(temperature=temperature, top_k=top_k,
                                    seed=seed)
        except Exception as e:  # noqa: BLE001 - refusal, not crash
            return self._rejected_gen(t_submit, 'bad_request',
                                      'unusable request: %r' % (e,),
                                      trace, t_pc)
        if prompt.size == 0:
            return self._rejected_gen(t_submit, 'bad_request',
                                      'empty prompt', trace, t_pc)
        if max_new is None:
            max_new = self._gen.max_new_default
        if int(max_new) < 1:
            return self._rejected_gen(t_submit, 'bad_request',
                                      'max_new must be >= 1, got %r'
                                      % (max_new,), trace, t_pc)
        limit = self.runtime.max_len
        if prompt.size + int(max_new) > limit:
            # the hard context ceiling: refuse with the arithmetic
            # spelled out — a prompt is NEVER silently truncated
            return self._rejected_gen(
                t_submit, 'too_long',
                'prompt of %d tokens + max_new=%d exceeds the runtime '
                'context window max_len=%d; shorten the prompt or lower '
                'max_new — nothing is silently truncated'
                % (prompt.size, int(max_new), limit), trace, t_pc)
        never_fits = getattr(self.runtime, 'never_fits', None)
        if never_fits is not None and never_fits(prompt.size, int(max_new)):
            # transient pool pressure means WAIT (backpressure), but a
            # request bigger than the whole pool can never run: refuse
            # with the arithmetic spelled out rather than deadlock it
            return self._rejected_gen(
                t_submit, 'kv_oom',
                'prompt of %d tokens + max_new=%d needs more KV pages '
                'than the entire pool holds (%d pages of %d tokens); '
                'nothing is silently truncated'
                % (prompt.size, int(max_new), self.runtime.pool.capacity,
                   self.runtime.cache.page_len), trace, t_pc)
        if timeout_s is None:
            timeout_s = self._cfg.default_timeout_s
        deadline = None
        if timeout_s is not None:
            if timeout_s <= 0:
                return self._rejected_gen(
                    t_submit, 'deadline',
                    'deadline already expired at admission '
                    '(timeout_s=%r)' % timeout_s, trace, t_pc)
            deadline = t_submit + float(timeout_s)
        if self._rate is not None and not self._rate.try_acquire():
            return self._rejected_gen(
                t_submit, 'rate', 'token-bucket rate limit exceeded '
                '(rate_qps=%r)' % self._cfg.rate_qps, trace, t_pc)
        req = _GenRequest(
            prompt, int(max_new), params, deadline, t_submit,
            ttft_timeout=(self._gen.ttft_timeout_s if ttft_timeout_s is None
                          else ttft_timeout_s),
            itl_timeout=(self._gen.itl_timeout_s if itl_timeout_s is None
                         else itl_timeout_s),
            trace=trace, t_pc=t_pc)
        fut = self._admit(req, t_submit)
        if trace is not None:
            t_now = time.perf_counter()
            _obs.tracing.recorder().add_complete(
                'serving.submit', t_pc, t_now, cat='serving',
                args=trace.span_args(prompt_tokens=int(prompt.size),
                                     max_new=int(max_new)))
            _obs.tracing.add_flow(trace.trace_id[:16], 's', t_pc,
                                  name='serving.link', cat='serving')
        return fut

    # ----------------------------------------------------- scheduling
    def _loop(self):
        try:
            while self._round():
                pass
        finally:
            # slot-holding requests get their terminal (shed) reply
            # BEFORE the base deadlock audit counts stragglers
            self._shed_active()
            self._finish_stop()

    def _round(self):
        """One scheduler round; False means the loop should exit."""
        with self._cond:
            while not self._queue and not self._active:
                if self._stopping or self._state == DRAINING:
                    return False
                self._cond.wait(0.05)
            if self._stopping:
                return False
            now = self._clock()
            expired, dropped = [], []
            for r in list(self._queue):
                if r.deadline is not None and r.deadline <= now:
                    expired.append(r)
                elif r.future.cancelled:
                    dropped.append(r)
            if expired or dropped:
                gone = set(map(id, expired + dropped))
                self._queue = type(self._queue)(
                    r for r in self._queue if id(r) not in gone)
            while self._queue:
                nxt = self._queue[0]
                slot = self.runtime.alloc_slot()
                if slot is None:
                    break
                start = self.runtime.try_begin(slot, nxt.prompt,
                                               self._gen.decode_window)
                if start is None:
                    # pool shortage: the request STAYS QUEUED (admission
                    # backpressure) and the slot goes back — retiring
                    # streams free pages and the next round re-claims
                    self.runtime.free_slot(slot)
                    _obs.metrics.counter(
                        'generation.kv_backpressure').inc()
                    break
                r = self._queue.popleft()
                r.slot = slot
                r.offset = int(start)   # prefix-cache hits skip ahead
                self._active.append(r)
            _obs.metrics.gauge('serving.queue_depth').set(len(self._queue))
            self._cond.notify_all()
        for r in expired:
            self._resolve(r, DEADLINE_EXCEEDED, reason='queue_wait',
                          error='deadline expired while queued; dropped '
                                'pre-dispatch (no compute was spent)')
        for r in dropped:
            _obs.metrics.counter('generation.cancelled').inc()
            self._resolve(r, SHED, reason='cancelled',
                          error='cancelled while queued')
        self._sweep_active()
        did_prefill = self._prefill_step()
        did_decode = self._decode_step()
        if did_prefill and did_decode:
            _obs.metrics.counter('generation.mixed_dispatches').inc()
        return True

    def _sweep_active(self):
        """Terminal conditions checked at every round boundary."""
        now = self._clock()
        for r in list(self._active):
            if r.future.cancelled:
                _obs.metrics.counter('generation.cancelled').inc()
                self._retire(r, SHED, reason='cancelled',
                             error='cancelled by the client mid-stream')
            elif r.deadline is not None and r.deadline <= now:
                self._retire(r, DEADLINE_EXCEEDED, reason='deadline',
                             error='overall deadline expired mid-stream')
            elif r.ttft_timeout is not None and r.produced == 0 \
                    and now - r.t_submit > r.ttft_timeout:
                self._retire(r, DEADLINE_EXCEEDED, reason='ttft',
                             error='no first token within the TTFT '
                                   'budget (%gs)' % r.ttft_timeout)
            elif r.itl_timeout is not None and r.produced > 0 \
                    and now - r.t_last_token > r.itl_timeout:
                self._retire(r, DEADLINE_EXCEEDED, reason='itl',
                             error='inter-token gap exceeded the ITL '
                                   'budget (%gs)' % r.itl_timeout)

    def _prefill_step(self):
        """Advance the OLDEST still-prefilling request by one chunk (or
        one ring shot).  Bounded work per round: long prompts cannot
        starve the decode batch."""
        rt = self.runtime
        pre = [r for r in self._active if r.offset < r.prompt.size]
        if not pre:
            return False
        r = min(pre, key=lambda x: x.t_submit)
        t0 = time.perf_counter()
        use_ring = (rt.mesh is not None and r.offset == 0
                    and r.prompt.size >= rt.ring_min_len)
        try:
            if use_ring:
                first, _logits = rt.prefill_ring(r.slot, r.prompt, r.params)
                r.offset = int(r.prompt.size)
            else:
                chunk = r.prompt[r.offset:r.offset + rt.prefill_chunk]
                first, _logits = rt.prefill(r.slot, chunk, r.offset,
                                            r.params)
                r.offset += int(chunk.size)
        except BaseException as e:  # noqa: BLE001 - replied per request
            self.breaker.record_failure()
            _obs.metrics.counter('serving.batch_failures').inc()
            _flight.record('serving.prefill_failure', error=repr(e)[:300])
            self._retire(r, ERROR, error=e, reason='prefill')
            _flight.maybe_dump('serving_prefill_failure')
            return True
        _obs.metrics.counter('generation.prefill_chunks').inc()
        if r.trace is not None:
            _obs.tracing.recorder().add_complete(
                'serving.prefill', t0, time.perf_counter(), cat='serving',
                args={'trace_id': r.trace.trace_id,
                      'parent_span_id': r.trace.span_id,
                      'slot': int(r.slot), 'offset': int(r.offset),
                      'ring': bool(use_ring)})
        if r.offset >= r.prompt.size:
            # prompt complete: publish its full pages for later
            # prefix-sharing requests, then emit the final chunk's
            # sample — the first token (TTFT)
            self.runtime.promote_prefix(r.slot, r.prompt)
            self._emit_tokens(r, [int(first)])
        return True

    def _decode_step(self):
        """One fused K-token window (plain decode or speculative
        verify) over every decoding slot."""
        rt = self.runtime
        dec = [r for r in self._active if r.offset >= r.prompt.size]
        if not dec:
            return False
        S, K = rt.slots, self._gen.decode_window
        # grow every stream's block table to cover this window FIRST: a
        # stream the pool cannot grow gets a terminal kv_oom reply (it
        # is never truncated and never silently stalled) and its freed
        # pages may rescue the streams after it
        for r in list(dec):
            if rt.ensure_capacity(r.slot, int(rt.host_len[r.slot]) + K):
                continue
            _obs.metrics.counter('generation.kv_oom').inc()
            snap = rt.pool_snapshot()
            _flight.record('serving.kv_oom', slot=int(r.slot),
                           produced=int(r.produced), **snap)
            dec.remove(r)
            self._retire(
                r, ERROR, reason='kv_oom',
                error='KV page pool exhausted mid-stream (%d/%d pages '
                      'live); partial output is in tokens_so_far()'
                      % (snap['pages_in_use'], snap['pages_capacity']))
            _flight.maybe_dump('kv_oom', extra={'kv_pool': snap})
        if not dec:
            return False
        active = np.zeros(S, bool)
        seeds = np.zeros(S, np.int32)
        temps = np.zeros(S, np.float32)
        topks = np.zeros(S, np.int32)
        for r in dec:
            active[r.slot] = True
            seeds[r.slot] = r.params.seed
            temps[r.slot] = r.params.temperature
            topks[r.slot] = r.params.top_k
        speculative = self._gen.speculative and K > 1
        t0 = time.perf_counter()
        try:
            if _faults.any_active():
                _faults.maybe_fail('decode_step')
            if speculative:
                emitted = self._verify_step(dec, K, active, seeds, temps,
                                            topks)
            else:
                toks = rt.decode_window(K, active, seeds, temps, topks)
                emitted = {id(r): [int(t) for t in toks[r.slot]]
                           for r in dec}
        except BaseException as e:  # noqa: BLE001 - replied per request
            self.breaker.record_failure()
            _obs.metrics.counter('serving.batch_failures').inc()
            _flight.record('serving.decode_failure', error=repr(e)[:300],
                           requests=len(dec), steps=int(K))
            for r in dec:
                self._retire(r, ERROR, error=e, reason='decode_step')
            _flight.maybe_dump('serving_decode_failure')
            return False
        self.breaker.record_success(cold=False)
        _obs.metrics.counter('generation.decode_windows').inc()
        if _obs.enabled():
            links = [r.trace.trace_id for r in dec if r.trace is not None]
            _obs.tracing.recorder().add_complete(
                'serving.decode_step', t0, time.perf_counter(),
                cat='serving', args={'steps': int(K), 'requests': len(dec),
                                     'speculative': bool(speculative),
                                     'links': links})
        for r in list(dec):
            self._emit_tokens(r, emitted[id(r)])
        return True

    def _verify_step(self, dec, K, active, seeds, temps, topks):
        """One speculative window: build each stream's fed row (last
        emitted token + n-gram draft), run the fused verify, keep the
        longest accepted prefix per stream, and roll the runtime back
        to the committed lengths.  Returns {id(request): tokens}."""
        from .sampling import draft_ngram
        rt = self.runtime
        S = rt.slots
        fed = np.zeros((S, K), np.int32)
        for r in dec:
            fed[r.slot, 0] = rt.host_tok[r.slot]
            ctx = np.concatenate([
                r.prompt, np.asarray(r.future.tokens_so_far(), np.int32)])
            fed[r.slot, 1:] = draft_ngram(ctx, K - 1)
        g = rt.verify_window(K, fed, active, seeds, temps, topks)
        emitted, accepted, kept = {}, {}, 0
        for r in dec:
            row = g[r.slot]
            m = 1
            while m < K and fed[r.slot, m] == row[m - 1]:
                m += 1
            accepted[r.slot] = (m, int(row[m - 1]))
            emitted[id(r)] = [int(t) for t in row[:m]]
            kept += m - 1
        _obs.metrics.counter('generation.spec_proposed').inc(
            (K - 1) * len(dec))
        _obs.metrics.counter('generation.spec_accepted').inc(kept)
        # commit BEFORE emitting: finishing streams retire (and free
        # their pages) with the runtime already consistent
        rt.commit_speculation(accepted)
        return emitted

    # ----------------------------------------------------- token path
    def _emit_tokens(self, r, toks):
        """Stream tokens to the client; finish on EOS / max_tokens."""
        now = self._clock()
        first = r.produced == 0
        if first:
            _obs.metrics.histogram('serving.ttft_ms').observe(
                max(0.0, (now - r.t_submit) * 1e3))
        elif toks:
            # the fused window delivers K tokens at once: observe the
            # amortized per-token gap K times so the ITL histogram
            # weighs every token, not every window
            gap_ms = max(0.0, (now - r.t_last_token) * 1e3) / len(toks)
            h = _obs.metrics.histogram('serving.itl_ms')
            for _ in range(min(len(toks), r.max_new - r.produced)):
                h.observe(gap_ms)
        finish = None
        for tok in toks:
            r.future._push(tok)
            r.produced += 1
            _obs.metrics.counter('generation.tokens').inc()
            if r.trace is not None:
                _obs.tracing.instant(
                    'serving.token', cat='serving',
                    args={'trace_id': r.trace.trace_id,
                          'index': int(r.produced)})
            if self._gen.eos_id is not None and tok == self._gen.eos_id:
                finish = 'eos'
                break
            if r.produced >= r.max_new:
                finish = 'max_tokens'
                break
        r.t_last_token = now
        if finish is not None:
            ids = np.asarray(r.future.tokens_so_far(), np.int64)
            self._retire(r, OK, outputs=[ids], reason=finish)

    def _retire(self, r, status, outputs=None, error=None, reason=None):
        """Terminal resolution for a slot-holding request: drop it from
        the round-robin, release the KV slot, resolve the stream."""
        if r in self._active:
            self._active.remove(r)
        if r.slot is not None:
            self.runtime.free_slot(r.slot)
            r.slot = None
        self._resolve(r, status, outputs=outputs, error=error,
                      reason=reason)

    def _shed_active(self):
        for r in list(self._active):
            self._retire(r, SHED, reason='shutdown',
                         error='engine stopped mid-stream; partial output '
                               'is in tokens_so_far()')
