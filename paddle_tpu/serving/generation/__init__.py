"""paddle_tpu.serving.generation — streaming autoregressive decode.

The generation subsystem turns the ServingEngine into a streaming
decode server (docs/generation.md):

  * `kv_cache` — PAGED KV storage: one shared page pool
    ``[pages, layers, kv_heads, page_len, head_dim]`` plus per-slot
    block tables, refcounted free-list page allocation (`PagePool`),
    optional int8 quantization (``PT_KV_QUANT``) and a fingerprinted
    shared-prefix page cache (`PrefixCache`, ``PT_PREFIX_CACHE``) — a
    stream's footprint is ceil(len/page_len) pages, not max_len rows.
  * `decode` — the fused prefill/decode/verify executables: K decode
    tokens launch as ONE `lax.scan` with the page pools as donated
    carry (no host round-trips inside the window); block tables are
    per-launch DATA, so one warm executable serves every page
    assignment; chunked/ring prefill; speculative verify windows;
    AOT-compiled and persisted through the compile-cache disk tier.
  * `sampling` — greedy / temperature / top-k draws keyed by
    ``(request seed, absolute position)`` only, so fused and sequential
    decode sample bitwise-identical streams (ops/sampling.py).
  * `scheduler` — mixed prefill+decode continuous batching on the
    PR-8 engine: prompts prefill one chunk per round, interleaved with
    full-width decode (or speculative draft+verify) windows; page-pool
    shortage is admission BACKPRESSURE, never truncation, and a stream
    that cannot grow retires with a terminal ``kv_oom`` reply.
  * `streaming` — per-token delivery through the engine reply path
    with TTFT/ITL SLOs and EOS / max-token / cancel termination, all
    resolving the terminal-reply invariant exactly once.

    from paddle_tpu.serving import generation
    engine = generation.GenerationEngine(runtime).start()
    stream = engine.generate(prompt_ids, max_new=32, temperature=0.8,
                             top_k=40, seed=7)
    for tok in stream.tokens():
        ...
    result = stream.result()          # ServeResult, reason='eos'/...
"""
from .kv_cache import (CacheConfig, PagePool, PrefixCache,  # noqa
                       SlotAllocator, default_page_len, init_state)
from .decode import (DecodeRuntime, dense_reference,  # noqa
                     random_weights, weight_names)
from .sampling import SamplingParams, draft_ngram  # noqa
from .streaming import TokenStream  # noqa
from .scheduler import GenerationConfig, GenerationEngine  # noqa

__all__ = ['CacheConfig', 'PagePool', 'PrefixCache', 'SlotAllocator',
           'default_page_len', 'init_state', 'DecodeRuntime',
           'dense_reference', 'random_weights', 'weight_names',
           'SamplingParams', 'draft_ngram', 'TokenStream',
           'GenerationConfig', 'GenerationEngine']
