"""paddle_tpu.serving.generation — streaming autoregressive decode.

The generation subsystem turns the ServingEngine into a streaming
decode server (docs/generation.md):

  * `kv_cache` — slotted per-request KV cache: preallocated
    ``[slots, layers, kv_heads, max_len, head_dim]`` pages, free-list
    slot allocation, per-slot length masks.
  * `decode` — the fused prefill/decode executables: K decode tokens
    launch as ONE `lax.scan` with the cache as donated carry (no host
    round-trips inside the window), chunked/ring prefill, AOT-compiled
    and persisted through the compile-cache disk tier.
  * `sampling` — greedy / temperature / top-k draws keyed by
    ``(request seed, absolute position)`` only, so fused and sequential
    decode sample bitwise-identical streams (ops/sampling.py).
  * `scheduler` — mixed prefill+decode continuous batching on the
    PR-8 engine: prompts prefill one chunk per round, interleaved with
    full-width decode windows, requests migrating prefill→decode slot
    in place.
  * `streaming` — per-token delivery through the engine reply path
    with TTFT/ITL SLOs and EOS / max-token / cancel termination, all
    resolving the terminal-reply invariant exactly once.

    from paddle_tpu.serving import generation
    engine = generation.GenerationEngine(runtime).start()
    stream = engine.generate(prompt_ids, max_new=32, temperature=0.8,
                             top_k=40, seed=7)
    for tok in stream.tokens():
        ...
    result = stream.result()          # ServeResult, reason='eos'/...
"""
from .kv_cache import CacheConfig, SlotAllocator, init_state  # noqa
from .decode import DecodeRuntime, dense_reference, weight_names  # noqa
from .sampling import SamplingParams  # noqa
from .streaming import TokenStream  # noqa
from .scheduler import GenerationConfig, GenerationEngine  # noqa

__all__ = ['CacheConfig', 'SlotAllocator', 'init_state', 'DecodeRuntime',
           'dense_reference', 'weight_names', 'SamplingParams',
           'TokenStream', 'GenerationConfig', 'GenerationEngine']
