"""Per-request sampling parameters for the streaming decode runtime.

The math lives in ops/sampling.py (pure, position-keyed, registered as
the `sample_tokens` Program op); this module carries the per-request
knobs through the scheduler and packs them into the fixed-width per-slot
vectors the decode executable takes — sampling parameters are DATA, not
part of the executable signature, so a greedy request and a top-k
request share one warm executable (zero per-request retraces).

Determinism contract (pinned by tests/test_generation.py): token at
absolute position ``p`` of a request with seed ``s`` is drawn with key
``fold_in(key(s), p)`` — independent of batch composition, window size
K, scheduler interleaving, and fresh-vs-restored executables.
"""
import numpy as np

from ...ops.sampling import (sample_logits, sample_tokens_at,  # noqa
                             token_key)

__all__ = ['SamplingParams', 'draft_ngram', 'sample_logits',
           'sample_tokens_at', 'token_key']


def draft_ngram(context, k):
    """Prompt-lookup draft for speculative decode: propose the ``k``
    tokens that followed the most recent PRIOR occurrence of the
    context's last token (padding with that token when history runs
    out).  Pure host-side and deterministic — the proposal quality only
    affects the accept rate, never correctness: the fused verify window
    samples the target model at every position and the stream keeps
    exactly the tokens the target would have produced anyway."""
    context = np.asarray(context, np.int32).reshape(-1)
    k = int(k)
    if k <= 0:
        return np.zeros(0, np.int32)
    out = np.full(k, context[-1] if context.size else 0, np.int32)
    if context.size >= 2:
        last = context[-1]
        hits = np.flatnonzero(context[:-1] == last)
        if hits.size:
            start = int(hits[-1]) + 1
            follow = context[start:start + k]
            out[:follow.size] = follow
    return out


class SamplingParams(object):
    """One request's sampling knobs.  ``temperature <= 0`` is greedy;
    ``top_k > 0`` restricts the draw to the k highest logits; ``seed``
    is the request's whole entropy (same seed -> same stream)."""
    __slots__ = ('temperature', 'top_k', 'seed')

    def __init__(self, temperature=0.0, top_k=0, seed=0):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        if self.top_k < 0:
            raise ValueError('top_k must be >= 0, got %r' % (top_k,))
        self.seed = int(seed)

    def __repr__(self):
        return ('SamplingParams(temperature=%g, top_k=%d, seed=%d)'
                % (self.temperature, self.top_k, self.seed))
