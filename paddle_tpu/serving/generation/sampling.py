"""Per-request sampling parameters for the streaming decode runtime.

The math lives in ops/sampling.py (pure, position-keyed, registered as
the `sample_tokens` Program op); this module carries the per-request
knobs through the scheduler and packs them into the fixed-width per-slot
vectors the decode executable takes — sampling parameters are DATA, not
part of the executable signature, so a greedy request and a top-k
request share one warm executable (zero per-request retraces).

Determinism contract (pinned by tests/test_generation.py): token at
absolute position ``p`` of a request with seed ``s`` is drawn with key
``fold_in(key(s), p)`` — independent of batch composition, window size
K, scheduler interleaving, and fresh-vs-restored executables.
"""
from ...ops.sampling import (sample_logits, sample_tokens_at,  # noqa
                             token_key)

__all__ = ['SamplingParams', 'sample_logits', 'sample_tokens_at',
           'token_key']


class SamplingParams(object):
    """One request's sampling knobs.  ``temperature <= 0`` is greedy;
    ``top_k > 0`` restricts the draw to the k highest logits; ``seed``
    is the request's whole entropy (same seed -> same stream)."""
    __slots__ = ('temperature', 'top_k', 'seed')

    def __init__(self, temperature=0.0, top_k=0, seed=0):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        if self.top_k < 0:
            raise ValueError('top_k must be >= 0, got %r' % (top_k,))
        self.seed = int(seed)

    def __repr__(self):
        return ('SamplingParams(temperature=%g, top_k=%d, seed=%d)'
                % (self.temperature, self.top_k, self.seed))
