"""Fused prefill/decode executables over the slotted KV cache.

The decode hot loop is the few-large-fused-primitives shape: one AOT
executable advances ALL cache slots K tokens as a single `lax.scan`
with the cache pages as DONATED carry — no per-token Python dispatch,
no host round-trips inside the window.  Inactive slots ride along under
a mask (their writes land at their own row's next free position, which
is overwritten before it is ever attended), so the executable signature
never depends on which requests are live: one warm executable serves
every batch composition forever (zero per-token retraces).

Prefill is chunked: each chunk writes its K/V into the request's slot at
its absolute offset and attends against the whole cache row with the
positional mask ``kpos <= qpos`` (ops/attention.cached_attention), so a
long prompt advances one bounded-cost chunk per scheduler round and
never stalls the decode batch.  With a mesh carrying a >1 ``seq`` axis
the runtime instead prefills long prompts in ONE shot through the exact
ppermute ring (parallel/ring_attention.py) — same cache writes, same
first-token logits (parity pinned at 1e-5 in tests/test_generation.py).

Every executable is compiled ahead of time and persisted through the
compile-cache disk tier (core/compile_cache.callable_fingerprint), so a
restarted server warm-starts its decode loop from disk; fused-vs-
sequential and fresh-vs-restored decode are bitwise-identical.
"""
import threading

import numpy as np

from ... import observability as _obs
from ...core import compile_cache as _cc
from ...ops.attention import cached_attention, write_cache
from ...ops.sampling import sample_logits, sample_tokens_at, token_key
from .kv_cache import CacheConfig, SlotAllocator, init_state

__all__ = ['DecodeRuntime', 'dense_reference', 'weight_names',
           'random_weights']

_WEIGHT_SLOTS = ('att_q_w', 'att_k_w', 'att_v_w', 'att_o_w', 'att_norm',
                 'ffn_norm', 'ffn_fc1_w', 'ffn_fc2_w', 'ffn_fc3_w')


def weight_names(cfg):
    """The decode-side parameter names — the same names a trained llama
    program leaves in its scope (models/llama.py layout)."""
    names = ['tok_emb', 'final_norm', 'lm_proj_w']
    for i in range(int(cfg['n_layer'])):
        names.extend('layer_%d_%s' % (i, s) for s in _WEIGHT_SLOTS)
    return names


def random_weights(cfg, seed=0, scale=0.08):
    """Random-init weight dict with the llama layout (tests/soaks that
    exercise the runtime without training a model first)."""
    rng = np.random.RandomState(seed)
    d, v, h = int(cfg['d_model']), int(cfg['vocab']), int(cfg['n_head'])
    hkv, f = int(cfg['n_kv_head']), int(cfg['d_ffn'])
    dh = d // h
    shapes = {'tok_emb': (v, d), 'final_norm': (d,), 'lm_proj_w': (d, v)}
    for i in range(int(cfg['n_layer'])):
        p = 'layer_%d_' % i
        shapes.update({p + 'att_q_w': (d, h * dh), p + 'att_k_w': (d, hkv * dh),
                       p + 'att_v_w': (d, hkv * dh), p + 'att_o_w': (d, d),
                       p + 'att_norm': (d,), p + 'ffn_norm': (d,),
                       p + 'ffn_fc1_w': (d, f), p + 'ffn_fc3_w': (d, f),
                       p + 'ffn_fc2_w': (f, d)})
    out = {}
    for n, s in shapes.items():
        if n.endswith('norm'):
            out[n] = np.ones(s, np.float32)
        else:
            out[n] = (scale * rng.randn(*s)).astype(np.float32)
    return out


# ------------------------------------------------------- forward pieces

def _rms(x, scale):
    import jax
    import jax.numpy as jnp
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * scale


def _rope_at(x, pos, theta):
    """x: [B, h, T, dh]; pos: [B, T] absolute positions (per-row — decode
    slots all sit at different lengths)."""
    import jax.numpy as jnp
    dh = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, dh // 2) * 2.0 / dh)
    ang = pos[:, None, :, None].astype(jnp.float32) * freqs
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    return jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                     axis=-1).reshape(x.shape)


def _qkv(w, cfg, h, i):
    """h: [B, T, D] -> q [B, H, T, dh], k/v [B, Hkv, T, dh] (pre-rope)."""
    B, T = h.shape[0], h.shape[1]
    H, Hkv = int(cfg['n_head']), int(cfg['n_kv_head'])
    dh = int(cfg['d_model']) // H
    p = 'layer_%d_' % i
    q = (h @ w[p + 'att_q_w']).reshape(B, T, H, dh).transpose(0, 2, 1, 3)
    k = (h @ w[p + 'att_k_w']).reshape(B, T, Hkv, dh).transpose(0, 2, 1, 3)
    v = (h @ w[p + 'att_v_w']).reshape(B, T, Hkv, dh).transpose(0, 2, 1, 3)
    return q, k, v


def _ffn(w, x, i):
    import jax
    p = 'layer_%d_' % i
    hh = _rms(x, w[p + 'ffn_norm'])
    gate = jax.nn.silu(hh @ w[p + 'ffn_fc1_w'])
    return x + (gate * (hh @ w[p + 'ffn_fc3_w'])) @ w[p + 'ffn_fc2_w']


def _prefill_fn(cfg, chunk, ring_mesh=None):
    """Build the one-chunk (or one-shot ring) prefill function.

    Writes the chunk's K/V into one slot's cache row at ``offset``,
    attends the chunk queries against the whole row (positional mask),
    SETS lengths[slot] = offset + true_count (no stale-state reset is
    ever needed), samples the would-be next token at its absolute
    position, and stores it in tok[slot].  Intermediate chunks' samples
    are placeholders the next chunk overwrites — only the final chunk's
    draw (the request's FIRST token, the TTFT token) survives.
    """
    import jax
    import jax.numpy as jnp
    L = int(cfg['n_layer'])
    Hkv = int(cfg['n_kv_head'])
    dh = int(cfg['d_model']) // int(cfg['n_head'])
    theta = float(cfg['theta'])
    Tmax = int(cfg['max_len'])

    if ring_mesh is not None:
        from ...parallel.ring_attention import ring_attention

    def prefill(w, kc, vc, lengths, tok, tokens, slot, offset, true_count,
                seed, temperature, top_k):
        pos = (offset + jnp.arange(chunk))[None]          # [1, C]
        x = w['tok_emb'][tokens][None]                    # [1, C, D]
        for i in range(L):
            h = _rms(x, w['layer_%d_att_norm' % i])
            q, k, v = _qkv(w, cfg, h, i)
            q = _rope_at(q, pos, theta)
            k = _rope_at(k, pos, theta)
            kc, vc = write_cache(kc, vc, k[0], v[0], slot, i, offset)
            if ring_mesh is not None:
                # one-shot long-context prefill (offset == 0): the exact
                # ppermute ring over the whole prompt
                att = ring_attention(q, k, v, ring_mesh, causal=True)
            else:
                row = (jax.lax.dynamic_slice(
                    kc, (slot, i, 0, 0, 0), (1, 1, Hkv, Tmax, dh))[:, 0],
                    jax.lax.dynamic_slice(
                    vc, (slot, i, 0, 0, 0), (1, 1, Hkv, Tmax, dh))[:, 0])
                att = cached_attention(q, row[0], row[1], pos)
            B, H, T = att.shape[0], att.shape[1], att.shape[2]
            att = att.transpose(0, 2, 1, 3).reshape(B, T, H * dh)
            x = x + att @ w['layer_%d_att_o_w' % i]
            x = _ffn(w, x, i)
        x = _rms(x, w['final_norm'])
        last = jax.lax.dynamic_slice_in_dim(x[0], true_count - 1, 1)[0]
        logits = last @ w['lm_proj_w']                    # [V] f32
        new_len = offset + true_count
        nxt = sample_logits(logits, token_key(seed, new_len),
                            temperature, top_k)
        lengths = lengths.at[slot].set(new_len)
        tok = tok.at[slot].set(nxt)
        return kc, vc, lengths, tok, nxt, logits

    return prefill


def _decode_fn(cfg, steps):
    """Build the K-step fused decode window over ALL slots.

    Each step feeds every slot's ``tok`` at its own ``lengths`` position
    (write K/V, attend against the row, sample the next token with the
    position-keyed stream), then advances ACTIVE slots only.  Inactive
    slots compute masked garbage: their write lands at their row's next
    free position — overwritten before any query can reach it — and
    their tok/lengths do not move.  The whole window is one `lax.scan`;
    the cache/state arrays are donated carry.
    """
    import jax
    import jax.numpy as jnp
    L = int(cfg['n_layer'])
    theta = float(cfg['theta'])
    dh = int(cfg['d_model']) // int(cfg['n_head'])

    def step(w, kc, vc, lengths, tok, active, seeds, temps, topks):
        S = kc.shape[0]
        pos = lengths                                     # [S] write pos
        x = w['tok_emb'][tok][:, None, :]                 # [S, 1, D]
        for i in range(L):
            h = _rms(x, w['layer_%d_att_norm' % i])
            q, k, v = _qkv(w, cfg, h, i)
            q = _rope_at(q, pos[:, None], theta)
            k = _rope_at(k, pos[:, None], theta)
            write = jax.vmap(
                lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (0, p, 0)))
            kci = write(kc[:, i], k.astype(kc.dtype), pos)
            vci = write(vc[:, i], v.astype(vc.dtype), pos)
            kc = kc.at[:, i].set(kci)
            vc = vc.at[:, i].set(vci)
            att = cached_attention(q, kci, vci, pos[:, None])
            H = att.shape[1]
            att = att.transpose(0, 2, 1, 3).reshape(S, 1, H * dh)
            x = x + att @ w['layer_%d_att_o_w' % i]
            x = _ffn(w, x, i)
        x = _rms(x, w['final_norm'])
        logits = x[:, 0] @ w['lm_proj_w']                 # [S, V]
        nxt = sample_tokens_at(logits, seeds, lengths + 1, temps, topks)
        new_tok = jnp.where(active, nxt, tok)
        new_len = jnp.where(active, lengths + 1, lengths)
        return kc, vc, new_len, new_tok

    def window(w, kc, vc, lengths, tok, active, seeds, temps, topks):
        def body(carry, _):
            kc, vc, lengths, tok = carry
            kc, vc, lengths, tok = step(w, kc, vc, lengths, tok, active,
                                        seeds, temps, topks)
            return (kc, vc, lengths, tok), tok
        (kc, vc, lengths, tok), toks = jax.lax.scan(
            body, (kc, vc, lengths, tok), None, length=steps)
        return kc, vc, lengths, tok, toks.T               # [S, K]

    return window


def dense_reference(weights, cfg, prompt):
    """Independent prefill reference: ordinary dense causal attention
    over the whole prompt — no cache pages, no positional masking, no
    chunking (an intentionally different code path from
    `cached_attention`).  Returns (k [L, Hkv, P, dh], v, last-position
    logits [V]) for the parity tests."""
    import jax
    import jax.numpy as jnp
    w = {n: jnp.asarray(weights[n]) for n in weight_names(cfg)}
    L = int(cfg['n_layer'])
    theta = float(cfg['theta'])
    P = int(np.asarray(prompt).shape[-1])
    pos = jnp.arange(P)[None]
    x = w['tok_emb'][jnp.asarray(prompt, jnp.int32).reshape(1, P)]
    ks, vs = [], []
    for i in range(L):
        h = _rms(x, w['layer_%d_att_norm' % i])
        q, k, v = _qkv(w, cfg, h, i)
        q = _rope_at(q, pos, theta)
        k = _rope_at(k, pos, theta)
        ks.append(k[0])
        vs.append(v[0])
        H, Hkv, dh = q.shape[1], k.shape[1], q.shape[-1]
        qg = q.reshape(1, Hkv, H // Hkv, P, dh)
        s = jnp.einsum('bhgqd,bhkd->bhgqk', qg, k,
                       preferred_element_type=jnp.float32) * (dh ** -0.5)
        mask = jnp.tril(jnp.ones((P, P), bool))
        s = jnp.where(mask[None, None, None], s, -1e30)
        att = jnp.einsum('bhgqk,bhkd->bhgqd', jax.nn.softmax(s, axis=-1), v,
                         preferred_element_type=jnp.float32)
        att = att.reshape(1, H, P, dh).transpose(0, 2, 1, 3)
        x = x + att.reshape(1, P, H * dh) @ w['layer_%d_att_o_w' % i]
        x = _ffn(w, x, i)
    x = _rms(x, w['final_norm'])
    logits = x[0, P - 1] @ w['lm_proj_w']
    return (np.asarray(jnp.stack(ks)), np.asarray(jnp.stack(vs)),
            np.asarray(logits))


class DecodeRuntime(object):
    """The device half of the streaming decode server: slotted KV cache
    state + AOT prefill/decode executables over one weight set.

    ``weights`` maps llama parameter names to arrays (a trained scope
    via models.llama.generation_weights, or `random_weights` for tests);
    ``cfg`` is the model config dict.  ``mesh`` (optional, with a >1
    ``seq`` axis) enables one-shot ring prefill for prompts of at least
    ``ring_min_len`` tokens.
    """

    def __init__(self, weights, cfg, slots=4, prefill_chunk=8,
                 cache_dtype='float32', mesh=None, ring_min_len=None):
        import jax.numpy as jnp
        self.cfg = dict(cfg)
        self.w = {n: jnp.asarray(weights[n]) for n in weight_names(cfg)}
        H = int(cfg['n_head'])
        self.cache = CacheConfig(
            slots=slots, layers=int(cfg['n_layer']),
            kv_heads=int(cfg['n_kv_head']), max_len=int(cfg['max_len']),
            head_dim=int(cfg['d_model']) // H, dtype=cache_dtype)
        self.allocator = SlotAllocator(self.cache.slots)
        self.state = init_state(self.cache)
        self.prefill_chunk = int(prefill_chunk)
        if not 0 < self.prefill_chunk <= self.cache.max_len:
            raise ValueError('prefill_chunk must be in (0, max_len]')
        self.mesh = mesh
        self.ring_min_len = (int(ring_min_len) if ring_min_len is not None
                             else 2 * self.prefill_chunk)
        self._execs = {}
        self._lock = threading.Lock()
        _obs.metrics.gauge('generation.kv_cache_bytes').set(
            self.cache.bytes())

    # ------------------------------------------------------- geometry
    @property
    def slots(self):
        return self.cache.slots

    @property
    def max_len(self):
        return self.cache.max_len

    def free_slots(self):
        return self.allocator.free_count()

    def alloc_slot(self):
        return self.allocator.alloc()

    def free_slot(self, slot):
        self.allocator.free(slot)

    def reset(self):
        """Fresh state + allocator (the weights and warm executables
        stay)."""
        self.allocator.reset()
        self.state = init_state(self.cache)

    # ---------------------------------------------------------- AOT
    def _param_specs(self):
        return {n: (tuple(a.shape), str(a.dtype))
                for n, a in self.w.items()}

    def _compiled(self, key, build):
        """One executable per (kind, shape) key: AOT-lowered, donated
        state, persisted through the compile-cache disk tier so a fresh
        process warm-starts the decode loop without compiling."""
        with self._lock:
            call = self._execs.get(key)
        if call is not None:
            return call
        _cc.ensure_xla_cache_backstop()
        spec = {'fn': key[0], 'shape': list(key[1:]), 'cfg': self.cfg,
                'cache': self.cache.spec(),
                'mesh': _cc._mesh_blob(self.mesh) if key[0].endswith(
                    'ring') else None}
        fp = _cc.callable_fingerprint('generation', spec,
                                      param_specs=self._param_specs())
        call = None
        if _cc.disk_enabled():
            call, _tier = _cc.disk_cache().load(fp)
            _obs.metrics.counter(
                'compile_cache.disk_hits' if call is not None
                else 'compile_cache.disk_misses').inc()
        if call is None:
            jitted, args = build()
            lowered = jitted.lower(*args)
            call = lowered.compile()
            _obs.metrics.counter('generation.compiles').inc()
            if _cc.disk_enabled():
                _cc.disk_cache().store(fp, compiled=call, lowered=lowered,
                                       meta={'kind': 'generation',
                                             'fn': key[0]})
        with self._lock:
            self._execs[key] = call
        return call

    def _sds(self, shape, dtype):
        """Arg struct for AOT lowering.  With a mesh every executable is
        compiled for REPLICATED NamedSharding state, so the ring-prefill
        and decode executables hand the donated cache back and forth
        without a resharding mismatch."""
        import jax
        if self.mesh is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(self.mesh,
                                                 PartitionSpec()))

    def _state_structs(self):
        st = self.state
        return [self._sds(a.shape, a.dtype)
                for a in (st['k'], st['v'], st['lengths'], st['tok'])]

    def _prefill_exec(self, chunk, ring=False):
        import jax

        def build():
            fn = _prefill_fn(self.cfg, chunk,
                             ring_mesh=self.mesh if ring else None)
            jitted = jax.jit(fn, donate_argnums=(1, 2, 3, 4))
            i32 = self._sds((), jax.numpy.int32)
            f32 = self._sds((), jax.numpy.float32)
            params = {n: self._sds(a.shape, a.dtype)
                      for n, a in self.w.items()}
            toks = self._sds((chunk,), jax.numpy.int32)
            args = [params] + self._state_structs() + \
                [toks, i32, i32, i32, i32, f32, i32]
            return jitted, args

        return self._compiled(('prefill_ring' if ring else 'prefill',
                               chunk), build)

    def _decode_exec(self, steps):
        import jax

        def build():
            fn = _decode_fn(self.cfg, steps)
            jitted = jax.jit(fn, donate_argnums=(1, 2, 3, 4))
            S = self.cache.slots
            vec = lambda dt: self._sds((S,), dt)  # noqa: E731
            params = {n: self._sds(a.shape, a.dtype)
                      for n, a in self.w.items()}
            args = [params] + self._state_structs() + \
                [vec(jax.numpy.bool_), vec(jax.numpy.int32),
                 vec(jax.numpy.float32), vec(jax.numpy.int32)]
            return jitted, args

        return self._compiled(('decode', steps), build)

    def warmup(self, steps=None):
        """Compile (or disk-load) the steady-state executables up front
        so the first request pays no compile latency."""
        self._prefill_exec(self.prefill_chunk)
        if steps:
            self._decode_exec(int(steps))

    # -------------------------------------------------------- prefill
    def prefill(self, slot, tokens, offset, params):
        """Run ONE prefill chunk for ``slot``: tokens[offset:offset+C]
        of the prompt (the final chunk may be short — it is padded to
        the chunk width and masked by ``true_count``).  Returns
        (next_token, logits) — meaningful only on the final chunk.
        ``params`` is a SamplingParams."""
        import jax.numpy as jnp
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        if not 0 < n <= self.prefill_chunk:
            raise ValueError('chunk of %d tokens does not fit the %d-wide '
                             'prefill executable' % (n, self.prefill_chunk))
        if offset + n > self.cache.max_len:
            raise ValueError('prefill past max_len=%d' % self.cache.max_len)
        buf = np.zeros(self.prefill_chunk, np.int32)
        buf[:n] = tokens
        call = self._prefill_exec(self.prefill_chunk)
        st = self.state
        k, v, lengths, tok, nxt, logits = call(
            self.w, st['k'], st['v'], st['lengths'], st['tok'],
            jnp.asarray(buf), jnp.int32(slot), jnp.int32(offset),
            jnp.int32(n), jnp.int32(params.seed),
            jnp.float32(params.temperature), jnp.int32(params.top_k))
        self.state = {'k': k, 'v': v, 'lengths': lengths, 'tok': tok}
        return int(nxt), np.asarray(logits)

    def ring_pad(self, n):
        """Padded one-shot ring prefill width for an n-token prompt:
        the next multiple of prefill_chunk (also a multiple of the ring
        size when prefill_chunk is)."""
        c = self.prefill_chunk
        return min(((int(n) + c - 1) // c) * c, self.cache.max_len)

    def prefill_ring(self, slot, prompt, params):
        """One-shot long-context prefill through ring attention: the
        whole (padded) prompt in a single launch.  Requires ``mesh``."""
        import jax.numpy as jnp
        if self.mesh is None:
            raise ValueError('ring prefill needs a mesh with a seq axis')
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = prompt.shape[0]
        width = self.ring_pad(n)
        if n > width:
            raise ValueError('prompt of %d exceeds max_len=%d'
                             % (n, self.cache.max_len))
        buf = np.zeros(width, np.int32)
        buf[:n] = prompt
        call = self._prefill_exec(width, ring=True)
        st = self.state
        k, v, lengths, tok, nxt, logits = call(
            self.w, st['k'], st['v'], st['lengths'], st['tok'],
            jnp.asarray(buf), jnp.int32(slot), jnp.int32(0),
            jnp.int32(n), jnp.int32(params.seed),
            jnp.float32(params.temperature), jnp.int32(params.top_k))
        self.state = {'k': k, 'v': v, 'lengths': lengths, 'tok': tok}
        return int(nxt), np.asarray(logits)

    # --------------------------------------------------------- decode
    def decode_window(self, steps, active, seeds, temps, topks):
        """Advance every ACTIVE slot ``steps`` tokens in one fused
        launch.  active/seeds/temps/topks are per-slot vectors (plain
        data — they never retrace).  Returns the [slots, steps] token
        matrix; inactive rows are garbage by contract."""
        import jax.numpy as jnp
        call = self._decode_exec(int(steps))
        st = self.state
        S = self.cache.slots
        k, v, lengths, tok, toks = call(
            self.w, st['k'], st['v'], st['lengths'], st['tok'],
            jnp.asarray(np.asarray(active, bool).reshape(S)),
            jnp.asarray(np.asarray(seeds, np.int32).reshape(S)),
            jnp.asarray(np.asarray(temps, np.float32).reshape(S)),
            jnp.asarray(np.asarray(topks, np.int32).reshape(S)))
        self.state = {'k': k, 'v': v, 'lengths': lengths, 'tok': tok}
        return np.asarray(toks)

    # ----------------------------------------------- test conveniences
    def cache_row(self, slot):
        """Host copies (k [L, Hkv, Tmax, dh], v, length) of one slot."""
        st = self.state
        return (np.asarray(st['k'][slot]), np.asarray(st['v'][slot]),
                int(np.asarray(st['lengths'][slot])))

    def generate(self, prompt, max_new, params=None, steps_per_window=4,
                 use_ring=False):
        """Single-request convenience decode (tests, parity references):
        prefill the prompt, then advance in fused windows; returns the
        generated ids (list, length max_new).  steps_per_window=1 IS the
        sequential single-token reference path."""
        from .sampling import SamplingParams
        params = params or SamplingParams()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size + int(max_new) > self.cache.max_len:
            raise ValueError(
                'prompt of %d + max_new=%d exceeds max_len=%d — requests '
                'are never truncated; shorten the prompt or lower max_new'
                % (prompt.size, max_new, self.cache.max_len))
        slot = self.alloc_slot()
        if slot is None:
            raise RuntimeError('no free kv slot')
        try:
            if use_ring:
                first, _ = self.prefill_ring(slot, prompt, params)
            else:
                first = None
                for off in range(0, prompt.size, self.prefill_chunk):
                    chunk = prompt[off:off + self.prefill_chunk]
                    first, _ = self.prefill(slot, chunk, off, params)
            out = [int(first)]
            S = self.cache.slots
            active = np.zeros(S, bool)
            active[slot] = True
            seeds = np.zeros(S, np.int32)
            temps = np.zeros(S, np.float32)
            topks = np.zeros(S, np.int32)
            seeds[slot] = params.seed
            temps[slot] = params.temperature
            topks[slot] = params.top_k
            while len(out) < int(max_new):
                toks = self.decode_window(int(steps_per_window), active,
                                          seeds, temps, topks)
                out.extend(int(t) for t in toks[slot])
            return out[:int(max_new)]
        finally:
            self.free_slot(slot)
