"""Fused prefill/decode executables over the PAGED KV pool.

The decode hot loop keeps the few-large-fused-primitives shape: one AOT
executable advances ALL cache slots K tokens as a single `lax.scan`
with the page pools as DONATED carry — no per-token Python dispatch, no
host round-trips inside the window.  K/V now live in a shared page pool
(kv_cache.py); every read and write goes through a per-slot BLOCK TABLE
passed as a plain ``[slots, max_pages]`` int32 argument.  Block tables
are DATA, never part of an executable signature: one warm executable
serves every batch composition and every page assignment forever (the
``generation.compiles == 2`` pin survives paging untouched).

Inactive slots ride along under a mask with their write target forced
to the GARBAGE page 0 (a freed slot's pages may already belong to
someone else — most importantly a shared prefix page — so the old
"write into your own row's next free position" trick is replaced by an
explicitly harmless destination).  Active slots past their reservation
also fall through to page 0: unmapped block-table entries are 0 by
construction.

Prefill is chunked exactly as before, but each chunk scatters its K/V
rows into the pages its block table maps and attends against the
GATHERED logical row (pages reassembled to ``[Hkv, max_len, head_dim]``
inside the executable, positional mask ``kpos <= qpos`` unchanged).
With ``quant='int8'`` rows are stored as int8 with one float32 scale
per (token, kv head), quantized on write and dequantized inside the
gather — attention math stays float32.

`_verify_fn` is the speculative-decode twin of the decode window: the
same step body, but each scan step feeds a HOST-PROVIDED token (last
emitted token + draft proposals) instead of the carry token, and the
returned per-step samples are the target model's verdicts.  The
rerun-deterministic ``(seed, position)`` sampling makes acceptance
replay-stable: a verified prefix is bitwise what sequential decode
would have produced.

Every executable is compiled ahead of time and persisted through the
compile-cache disk tier (core/compile_cache.callable_fingerprint) — the
cache spec now carries page_len/pages/quant, so geometry changes get
fresh fingerprints.  `dense_reference` is the independent, page-free
parity oracle.
"""
import os
import threading

import numpy as np

from ... import observability as _obs
from ...core import compile_cache as _cc
from ...ops.attention import cached_attention
from ...ops.sampling import sample_logits, sample_tokens_at, token_key
from .kv_cache import (CacheConfig, PagePool, PrefixCache, SlotAllocator,
                       init_state)

__all__ = ['DecodeRuntime', 'dense_reference', 'weight_names',
           'random_weights']

_WEIGHT_SLOTS = ('att_q_w', 'att_k_w', 'att_v_w', 'att_o_w', 'att_norm',
                 'ffn_norm', 'ffn_fc1_w', 'ffn_fc2_w', 'ffn_fc3_w')


def weight_names(cfg):
    """The decode-side parameter names — the same names a trained llama
    program leaves in its scope (models/llama.py layout)."""
    names = ['tok_emb', 'final_norm', 'lm_proj_w']
    for i in range(int(cfg['n_layer'])):
        names.extend('layer_%d_%s' % (i, s) for s in _WEIGHT_SLOTS)
    return names


def random_weights(cfg, seed=0, scale=0.08):
    """Random-init weight dict with the llama layout (tests/soaks that
    exercise the runtime without training a model first)."""
    rng = np.random.RandomState(seed)
    d, v, h = int(cfg['d_model']), int(cfg['vocab']), int(cfg['n_head'])
    hkv, f = int(cfg['n_kv_head']), int(cfg['d_ffn'])
    dh = d // h
    shapes = {'tok_emb': (v, d), 'final_norm': (d,), 'lm_proj_w': (d, v)}
    for i in range(int(cfg['n_layer'])):
        p = 'layer_%d_' % i
        shapes.update({p + 'att_q_w': (d, h * dh), p + 'att_k_w': (d, hkv * dh),
                       p + 'att_v_w': (d, hkv * dh), p + 'att_o_w': (d, d),
                       p + 'att_norm': (d,), p + 'ffn_norm': (d,),
                       p + 'ffn_fc1_w': (d, f), p + 'ffn_fc3_w': (d, f),
                       p + 'ffn_fc2_w': (f, d)})
    out = {}
    for n, s in shapes.items():
        if n.endswith('norm'):
            out[n] = np.ones(s, np.float32)
        else:
            out[n] = (scale * rng.randn(*s)).astype(np.float32)
    return out


# ------------------------------------------------------- forward pieces

def _rms(x, scale):
    import jax
    import jax.numpy as jnp
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * scale


def _rope_at(x, pos, theta):
    """x: [B, h, T, dh]; pos: [B, T] absolute positions (per-row — decode
    slots all sit at different lengths)."""
    import jax.numpy as jnp
    dh = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, dh // 2) * 2.0 / dh)
    ang = pos[:, None, :, None].astype(jnp.float32) * freqs
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    return jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                     axis=-1).reshape(x.shape)


def _qkv(w, cfg, h, i):
    """h: [B, T, D] -> q [B, H, T, dh], k/v [B, Hkv, T, dh] (pre-rope)."""
    B, T = h.shape[0], h.shape[1]
    H, Hkv = int(cfg['n_head']), int(cfg['n_kv_head'])
    dh = int(cfg['d_model']) // H
    p = 'layer_%d_' % i
    q = (h @ w[p + 'att_q_w']).reshape(B, T, H, dh).transpose(0, 2, 1, 3)
    k = (h @ w[p + 'att_k_w']).reshape(B, T, Hkv, dh).transpose(0, 2, 1, 3)
    v = (h @ w[p + 'att_v_w']).reshape(B, T, Hkv, dh).transpose(0, 2, 1, 3)
    return q, k, v


def _ffn(w, x, i):
    import jax
    p = 'layer_%d_' % i
    hh = _rms(x, w[p + 'ffn_norm'])
    gate = jax.nn.silu(hh @ w[p + 'ffn_fc1_w'])
    return x + (gate * (hh @ w[p + 'ffn_fc3_w'])) @ w[p + 'ffn_fc2_w']


# -------------------------------------------------- paged read / write

def _quantize_rows(x):
    """x [..., dh] f32 -> (int8 rows, f32 per-row scale).  amax/127
    scaling, eps-clamped so an all-zero row round-trips to zeros."""
    import jax.numpy as jnp
    s = jnp.maximum(jnp.max(jnp.abs(x), axis=-1) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def _write_rows(st, i, pg, rw, k_new, v_new, quant):
    """Scatter per-token K/V rows into layer ``i`` of the pools.

    pg/rw: [N] page ids and in-page rows; k_new/v_new: [N, Hkv, dh]
    float32.  Rows routed to page 0 (masked/invalid targets) are
    write-only garbage — never attended.  Returns the new state dict.
    """
    st = dict(st)
    if quant:
        qk, sk = _quantize_rows(k_new)
        qv, sv = _quantize_rows(v_new)
        st['k'] = st['k'].at[pg, i, :, rw, :].set(qk)
        st['v'] = st['v'].at[pg, i, :, rw, :].set(qv)
        st['k_scale'] = st['k_scale'].at[pg, i, :, rw].set(sk)
        st['v_scale'] = st['v_scale'].at[pg, i, :, rw].set(sv)
    else:
        st['k'] = st['k'].at[pg, i, :, rw, :].set(k_new.astype(st['k'].dtype))
        st['v'] = st['v'].at[pg, i, :, rw, :].set(v_new.astype(st['v'].dtype))
    return st


def _logical_rows(st, bt, i, cache):
    """Gather layer ``i``'s logical dense rows through the block table.

    bt: [B, max_pages] -> (k, v) each [B, Hkv, max_len, dh].  Unmapped
    entries (0) pull the garbage page — those positions sit at or past
    every live length, so the positional mask already hides them.  int8
    pools are dequantized here; attention math stays float32.
    """
    import jax.numpy as jnp
    B, M = bt.shape
    Hkv, PL, dh = cache.kv_heads, cache.page_len, cache.head_dim

    def assemble(pool, scale):
        rows = pool[bt, i]                     # [B, M, Hkv, PL, dh]
        rows = rows.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, M * PL, dh)
        if scale is None:
            return rows
        sc = scale[bt, i]                      # [B, M, Hkv, PL]
        sc = sc.transpose(0, 2, 1, 3).reshape(B, Hkv, M * PL)
        return rows.astype(jnp.float32) * sc[..., None]

    if cache.quant == 'int8':
        return (assemble(st['k'], st['k_scale']),
                assemble(st['v'], st['v_scale']))
    return assemble(st['k'], None), assemble(st['v'], None)


def _prefill_fn(cfg, cache, chunk, ring_mesh=None):
    """Build the one-chunk (or one-shot ring) prefill function.

    Scatters the chunk's K/V rows into the pages ``bt_row`` maps at the
    chunk's absolute positions (invalid tail rows of a short final
    chunk go to the garbage page), attends the chunk queries against
    the gathered logical row, SETS lengths[slot] = offset + true_count,
    samples the would-be next token at its absolute position, and
    stores it in tok[slot].  Only the final chunk's draw (the request's
    FIRST token, the TTFT token) survives.
    """
    import jax.numpy as jnp
    L = int(cfg['n_layer'])
    theta = float(cfg['theta'])
    dh = int(cfg['d_model']) // int(cfg['n_head'])
    M, PL = cache.max_pages, cache.page_len
    quant = cache.quant == 'int8'

    if ring_mesh is not None:
        from ...parallel.ring_attention import ring_attention

    def prefill(w, st, bt_row, tokens, slot, offset, true_count,
                seed, temperature, top_k):
        pos = (offset + jnp.arange(chunk))[None]          # [1, C]
        p_abs = offset + jnp.arange(chunk)                # [C]
        valid = jnp.arange(chunk) < true_count
        pg = jnp.where(valid,
                       bt_row[jnp.clip(p_abs // PL, 0, M - 1)], 0)
        rw = p_abs % PL
        x = w['tok_emb'][tokens][None]                    # [1, C, D]
        for i in range(L):
            h = _rms(x, w['layer_%d_att_norm' % i])
            q, k, v = _qkv(w, cfg, h, i)
            q = _rope_at(q, pos, theta)
            k = _rope_at(k, pos, theta)
            st = _write_rows(st, i, pg, rw, k[0].transpose(1, 0, 2),
                             v[0].transpose(1, 0, 2), quant)
            if ring_mesh is not None:
                # one-shot long-context prefill (offset == 0): the exact
                # ppermute ring over the whole prompt
                att = ring_attention(q, k, v, ring_mesh, causal=True)
            else:
                kl, vl = _logical_rows(st, bt_row[None], i, cache)
                att = cached_attention(q, kl, vl, pos)
            B, H, T = att.shape[0], att.shape[1], att.shape[2]
            att = att.transpose(0, 2, 1, 3).reshape(B, T, H * dh)
            x = x + att @ w['layer_%d_att_o_w' % i]
            x = _ffn(w, x, i)
        import jax
        x = _rms(x, w['final_norm'])
        last = jax.lax.dynamic_slice_in_dim(x[0], true_count - 1, 1)[0]
        logits = last @ w['lm_proj_w']                    # [V] f32
        new_len = offset + true_count
        nxt = sample_logits(logits, token_key(seed, new_len),
                            temperature, top_k)
        st = dict(st)
        st['lengths'] = st['lengths'].at[slot].set(new_len)
        st['tok'] = st['tok'].at[slot].set(nxt)
        return st, nxt, logits

    return prefill


def _step_fn(cfg, cache):
    """One fused decode/verify step over ALL slots: write the fed token's
    K/V through the block table, attend against the gathered logical
    rows, sample each slot's next token with the position-keyed stream,
    advance ACTIVE slots only.  Inactive slots compute masked garbage
    routed to page 0."""
    import jax.numpy as jnp
    L = int(cfg['n_layer'])
    theta = float(cfg['theta'])
    dh = int(cfg['d_model']) // int(cfg['n_head'])
    M, PL = cache.max_pages, cache.page_len
    quant = cache.quant == 'int8'

    def step(w, st, bt, fed, active, seeds, temps, topks):
        S = bt.shape[0]
        pos = st['lengths']                               # [S] write pos
        pg = bt[jnp.arange(S), jnp.clip(pos // PL, 0, M - 1)]
        pg = jnp.where(active, pg, 0)
        rw = pos % PL
        x = w['tok_emb'][fed][:, None, :]                 # [S, 1, D]
        for i in range(L):
            h = _rms(x, w['layer_%d_att_norm' % i])
            q, k, v = _qkv(w, cfg, h, i)
            q = _rope_at(q, pos[:, None], theta)
            k = _rope_at(k, pos[:, None], theta)
            st = _write_rows(st, i, pg, rw, k[:, :, 0, :], v[:, :, 0, :],
                             quant)
            kl, vl = _logical_rows(st, bt, i, cache)
            att = cached_attention(q, kl, vl, pos[:, None])
            H = att.shape[1]
            att = att.transpose(0, 2, 1, 3).reshape(S, 1, H * dh)
            x = x + att @ w['layer_%d_att_o_w' % i]
            x = _ffn(w, x, i)
        x = _rms(x, w['final_norm'])
        logits = x[:, 0] @ w['lm_proj_w']                 # [S, V]
        nxt = sample_tokens_at(logits, seeds, pos + 1, temps, topks)
        st = dict(st)
        st['tok'] = jnp.where(active, nxt, st['tok'])
        st['lengths'] = jnp.where(active, pos + 1, pos)
        return st, nxt

    return step


def _decode_fn(cfg, cache, steps):
    """K-step fused decode window: each step feeds every slot's own
    carry token.  One `lax.scan`; the state dict is donated carry; the
    block table is closed-over DATA (an ordinary traced argument)."""
    import jax

    step = _step_fn(cfg, cache)

    def window(w, st, bt, active, seeds, temps, topks):
        def body(carry, _):
            carry, nxt = step(w, carry, bt, carry['tok'], active, seeds,
                              temps, topks)
            return carry, nxt
        st, toks = jax.lax.scan(body, st, None, length=steps)
        return st, toks.T                                 # [S, K]

    return window


def _verify_fn(cfg, cache, steps):
    """K-step speculative VERIFY window: identical step body, but step j
    feeds ``fed[j]`` (host-built: last emitted token, then the draft's
    proposals) and the returned samples are the target model's verdicts
    g_j at each position.  Same `(seed, position)` sampling as decode —
    an accepted prefix is bitwise the sequential stream."""
    import jax

    step = _step_fn(cfg, cache)

    def window(w, st, bt, fed, active, seeds, temps, topks):
        def body(carry, fed_t):
            carry, nxt = step(w, carry, bt, fed_t, active, seeds, temps,
                              topks)
            return carry, nxt
        st, toks = jax.lax.scan(body, st, fed)            # fed: [K, S]
        return st, toks.T                                 # [S, K]

    return window


def dense_reference(weights, cfg, prompt):
    """Independent prefill reference: ordinary dense causal attention
    over the whole prompt — no cache pages, no positional masking, no
    chunking (an intentionally different code path from
    `cached_attention`).  Returns (k [L, Hkv, P, dh], v, last-position
    logits [V]) for the parity tests."""
    import jax
    import jax.numpy as jnp
    w = {n: jnp.asarray(weights[n]) for n in weight_names(cfg)}
    L = int(cfg['n_layer'])
    theta = float(cfg['theta'])
    P = int(np.asarray(prompt).shape[-1])
    pos = jnp.arange(P)[None]
    x = w['tok_emb'][jnp.asarray(prompt, jnp.int32).reshape(1, P)]
    ks, vs = [], []
    for i in range(L):
        h = _rms(x, w['layer_%d_att_norm' % i])
        q, k, v = _qkv(w, cfg, h, i)
        q = _rope_at(q, pos, theta)
        k = _rope_at(k, pos, theta)
        ks.append(k[0])
        vs.append(v[0])
        H, Hkv, dh = q.shape[1], k.shape[1], q.shape[-1]
        qg = q.reshape(1, Hkv, H // Hkv, P, dh)
        s = jnp.einsum('bhgqd,bhkd->bhgqk', qg, k,
                       preferred_element_type=jnp.float32) * (dh ** -0.5)
        mask = jnp.tril(jnp.ones((P, P), bool))
        s = jnp.where(mask[None, None, None], s, -1e30)
        att = jnp.einsum('bhgqk,bhkd->bhgqd', jax.nn.softmax(s, axis=-1), v,
                         preferred_element_type=jnp.float32)
        att = att.reshape(1, H, P, dh).transpose(0, 2, 1, 3)
        x = x + att.reshape(1, P, H * dh) @ w['layer_%d_att_o_w' % i]
        x = _ffn(w, x, i)
    x = _rms(x, w['final_norm'])
    logits = x[0, P - 1] @ w['lm_proj_w']
    return (np.asarray(jnp.stack(ks)), np.asarray(jnp.stack(vs)),
            np.asarray(logits))


def _env_quant(kv_quant):
    if kv_quant is not None:
        q = str(kv_quant)
    else:
        q = os.environ.get('PT_KV_QUANT', 'none')
    return 'none' if q.strip().lower() in ('', '0', 'none', 'off',
                                           'false') else q.strip().lower()


def _env_prefix(prefix_cache):
    if prefix_cache is not None:
        return bool(prefix_cache)
    return os.environ.get('PT_PREFIX_CACHE', '1').strip().lower() not in (
        '0', 'off', 'false', '')


class DecodeRuntime(object):
    """The device half of the streaming decode server: the paged KV
    pool + block tables + AOT prefill/decode/verify executables over one
    weight set.

    ``weights`` maps llama parameter names to arrays (a trained scope
    via models.llama.generation_weights, or `random_weights` for tests);
    ``cfg`` is the model config dict.  ``mesh`` (optional, with a >1
    ``seq`` axis) enables one-shot ring prefill for prompts of at least
    ``ring_min_len`` tokens.

    Paging knobs: ``page_len`` (default: largest divisor of max_len
    <= 8), ``pages`` (pool depth incl. the garbage page; default =
    dense-equivalent capacity), ``kv_quant`` ('none'/'int8', default
    env PT_KV_QUANT), ``prefix_cache`` (default env PT_PREFIX_CACHE,
    on).  A slot is a batch row; PAGES are the memory: admission goes
    through `try_begin` (prefix-cache match + all-or-nothing page
    claim) and per-window `ensure_capacity`, both of which report
    shortage as a clean False/None the scheduler turns into
    backpressure or a terminal ``kv_oom``.
    """

    def __init__(self, weights, cfg, slots=4, prefill_chunk=8,
                 cache_dtype='float32', mesh=None, ring_min_len=None,
                 page_len=None, pages=None, kv_quant=None,
                 prefix_cache=None):
        import jax.numpy as jnp
        self.cfg = dict(cfg)
        self.w = {n: jnp.asarray(weights[n]) for n in weight_names(cfg)}
        H = int(cfg['n_head'])
        self.cache = CacheConfig(
            slots=slots, layers=int(cfg['n_layer']),
            kv_heads=int(cfg['n_kv_head']), max_len=int(cfg['max_len']),
            head_dim=int(cfg['d_model']) // H, dtype=cache_dtype,
            page_len=page_len, pages=pages, quant=_env_quant(kv_quant))
        self.allocator = SlotAllocator(self.cache.slots)
        self.pool = PagePool(self.cache)
        self.prefix = (PrefixCache(self.pool, self.cache.page_len)
                       if _env_prefix(prefix_cache) else None)
        S = self.cache.slots
        self.block_tables = np.zeros((S, self.cache.max_pages), np.int32)
        self.owned = [[] for _ in range(S)]
        self.host_len = np.zeros(S, np.int32)
        self.host_tok = np.zeros(S, np.int32)
        self.state = init_state(self.cache)
        self.prefill_chunk = int(prefill_chunk)
        if not 0 < self.prefill_chunk <= self.cache.max_len:
            raise ValueError('prefill_chunk must be in (0, max_len]')
        self.mesh = mesh
        self.ring_min_len = (int(ring_min_len) if ring_min_len is not None
                             else 2 * self.prefill_chunk)
        self._execs = {}
        self._lock = threading.Lock()
        _obs.metrics.gauge('generation.kv_cache_bytes').set(
            self.cache.bytes())

    # ------------------------------------------------------- geometry
    @property
    def slots(self):
        return self.cache.slots

    @property
    def max_len(self):
        return self.cache.max_len

    def free_slots(self):
        return self.allocator.free_count()

    def alloc_slot(self):
        return self.allocator.alloc()

    def free_slot(self, slot):
        """Retire a slot: release every page its block table maps (a
        shared prefix page survives in the cache / other streams) and
        unmap the row.  Pages are never zeroed — positional masking
        keeps stale rows unreachable."""
        slot = int(slot)
        pages, self.owned[slot] = self.owned[slot], []
        if pages:
            self.pool.release(pages)
        self.block_tables[slot] = 0
        self.host_len[slot] = 0
        self.host_tok[slot] = 0
        self.allocator.free(slot)

    def reset(self):
        """Fresh state + allocators (the weights and warm executables
        stay)."""
        for s in range(self.cache.slots):
            self.owned[s] = []
        if self.prefix is not None:
            self.prefix.reset()
        self.allocator.reset()
        self.pool.reset()
        self.block_tables[:] = 0
        self.host_len[:] = 0
        self.host_tok[:] = 0
        self.state = init_state(self.cache)

    # ------------------------------------------------ page accounting
    def never_fits(self, prompt_len, max_new):
        """True when prompt+max_new could not run even on an idle pool —
        the admission-time terminal ``kv_oom``."""
        span = min(int(prompt_len) + int(max_new), self.cache.max_len)
        return self.cache.pages_for(span) > self.pool.capacity

    def try_begin(self, slot, prompt, window):
        """Claim pages for ``prompt`` plus one decode window on
        ``slot``: longest shared-prefix match first (those pages are
        mapped read-only — full by construction, so the request's own
        writes start in its first fresh page), then an all-or-nothing
        claim of the remainder.  Returns the PREFILL START OFFSET
        (matched tokens are skipped), or None on page shortage with
        nothing leaked — the scheduler's backpressure signal."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        span = min(prompt.size + max(1, int(window)), self.cache.max_len)
        need = self.cache.pages_for(span)
        matched = self.prefix.match(prompt) if self.prefix is not None else []
        evict = self.prefix.evict_one if self.prefix is not None else None
        fresh = self.pool.alloc(max(0, need - len(matched)), evict=evict)
        if fresh is None:
            if matched:
                self.pool.release(matched)
            return None
        pages = list(matched) + list(fresh)
        self.owned[slot] = pages
        self.block_tables[slot] = 0
        self.block_tables[slot, :len(pages)] = pages
        self.host_len[slot] = 0
        self.host_tok[slot] = 0
        return len(matched) * self.cache.page_len

    def ensure_capacity(self, slot, target_len):
        """Grow ``slot``'s block table to cover ``target_len`` tokens.
        True when already covered or grown; False on pool exhaustion
        (mid-stream ``kv_oom`` — the caller retires the stream with a
        terminal reply, never truncates silently)."""
        slot = int(slot)
        need = self.cache.pages_for(min(int(target_len),
                                        self.cache.max_len))
        have = len(self.owned[slot])
        if need <= have:
            return True
        evict = self.prefix.evict_one if self.prefix is not None else None
        fresh = self.pool.alloc(need - have, evict=evict)
        if fresh is None:
            return False
        self.owned[slot].extend(fresh)
        self.block_tables[slot, have:need] = fresh
        return True

    def promote_prefix(self, slot, prompt):
        """Publish a freshly-prefilled prompt's full pages into the
        prefix cache (no-op when prefix caching is off)."""
        if self.prefix is None:
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        full = prompt.size // self.cache.page_len
        return self.prefix.insert(prompt, self.owned[int(slot)][:full])

    def pool_snapshot(self):
        """Host-side pool gauges (flight-dump payload on kv_oom/breaker
        trips)."""
        return {'pages_capacity': self.pool.capacity,
                'pages_in_use': self.pool.in_use(),
                'page_bytes': self.pool.page_bytes,
                'bytes_reserved': self.cache.bytes(),
                'bytes_live': self.pool.in_use() * self.pool.page_bytes,
                'prefix_entries': (len(self.prefix)
                                   if self.prefix is not None else 0),
                'slots_in_use': self.allocator.in_use()}

    # ---------------------------------------------------------- AOT
    def _param_specs(self):
        return {n: (tuple(a.shape), str(a.dtype))
                for n, a in self.w.items()}

    def _compiled(self, key, build):
        """One executable per (kind, shape) key: AOT-lowered, donated
        state, persisted through the compile-cache disk tier so a fresh
        process warm-starts the decode loop without compiling."""
        with self._lock:
            call = self._execs.get(key)
        if call is not None:
            return call
        _cc.ensure_xla_cache_backstop()
        spec = {'fn': key[0], 'shape': list(key[1:]), 'cfg': self.cfg,
                'cache': self.cache.spec(),
                'mesh': _cc._mesh_blob(self.mesh) if key[0].endswith(
                    'ring') else None}
        fp = _cc.callable_fingerprint('generation', spec,
                                      param_specs=self._param_specs())
        call = None
        if _cc.disk_enabled():
            call, _tier = _cc.disk_cache().load(fp)
            _obs.metrics.counter(
                'compile_cache.disk_hits' if call is not None
                else 'compile_cache.disk_misses').inc()
        if call is None:
            jitted, args = build()
            lowered = jitted.lower(*args)
            call = lowered.compile()
            _obs.metrics.counter('generation.compiles').inc()
            if _cc.disk_enabled():
                _cc.disk_cache().store(fp, compiled=call, lowered=lowered,
                                       meta={'kind': 'generation',
                                             'fn': key[0]})
        with self._lock:
            self._execs[key] = call
        return call

    def _sds(self, shape, dtype):
        """Arg struct for AOT lowering.  With a mesh every executable is
        compiled for REPLICATED NamedSharding state, so the ring-prefill
        and decode executables hand the donated cache back and forth
        without a resharding mismatch."""
        import jax
        if self.mesh is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(self.mesh,
                                                 PartitionSpec()))

    def _state_structs(self):
        return {n: self._sds(a.shape, a.dtype)
                for n, a in self.state.items()}

    def _bt_struct(self, rows):
        import jax
        return self._sds((rows, self.cache.max_pages), jax.numpy.int32)

    def _prefill_exec(self, chunk, ring=False):
        import jax

        def build():
            fn = _prefill_fn(self.cfg, self.cache, chunk,
                             ring_mesh=self.mesh if ring else None)
            jitted = jax.jit(fn, donate_argnums=(1,))
            i32 = self._sds((), jax.numpy.int32)
            f32 = self._sds((), jax.numpy.float32)
            params = {n: self._sds(a.shape, a.dtype)
                      for n, a in self.w.items()}
            toks = self._sds((chunk,), jax.numpy.int32)
            bt_row = self._sds((self.cache.max_pages,), jax.numpy.int32)
            args = [params, self._state_structs(), bt_row, toks,
                    i32, i32, i32, i32, f32, i32]
            return jitted, args

        return self._compiled(('prefill_ring' if ring else 'prefill',
                               chunk), build)

    def _window_exec(self, kind, steps):
        import jax

        def build():
            if kind == 'verify':
                fn = _verify_fn(self.cfg, self.cache, steps)
            else:
                fn = _decode_fn(self.cfg, self.cache, steps)
            jitted = jax.jit(fn, donate_argnums=(1,))
            S = self.cache.slots
            vec = lambda dt: self._sds((S,), dt)  # noqa: E731
            params = {n: self._sds(a.shape, a.dtype)
                      for n, a in self.w.items()}
            args = [params, self._state_structs(), self._bt_struct(S)]
            if kind == 'verify':
                args.append(self._sds((steps, S), jax.numpy.int32))
            args += [vec(jax.numpy.bool_), vec(jax.numpy.int32),
                     vec(jax.numpy.float32), vec(jax.numpy.int32)]
            return jitted, args

        return self._compiled((kind, steps), build)

    def _decode_exec(self, steps):
        return self._window_exec('decode', steps)

    def _verify_exec(self, steps):
        return self._window_exec('verify', steps)

    def warmup(self, steps=None, speculative=False):
        """Compile (or disk-load) the steady-state executables up front
        so the first request pays no compile latency.  With
        ``speculative`` the verify window is warmed too."""
        self._prefill_exec(self.prefill_chunk)
        if steps:
            self._decode_exec(int(steps))
            if speculative:
                self._verify_exec(int(steps))

    # -------------------------------------------------------- prefill
    def prefill(self, slot, tokens, offset, params):
        """Run ONE prefill chunk for ``slot``: tokens[offset:offset+C]
        of the prompt (the final chunk may be short — it is padded to
        the chunk width and masked by ``true_count``).  Returns
        (next_token, logits) — meaningful only on the final chunk.
        ``params`` is a SamplingParams.  The slot's block table must
        already cover the chunk (`try_begin`/`ensure_capacity`)."""
        import jax.numpy as jnp
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.shape[0]
        if not 0 < n <= self.prefill_chunk:
            raise ValueError('chunk of %d tokens does not fit the %d-wide '
                             'prefill executable' % (n, self.prefill_chunk))
        if offset + n > self.cache.max_len:
            raise ValueError('prefill past max_len=%d' % self.cache.max_len)
        buf = np.zeros(self.prefill_chunk, np.int32)
        buf[:n] = tokens
        call = self._prefill_exec(self.prefill_chunk)
        st, nxt, logits = call(
            self.w, self.state, jnp.asarray(self.block_tables[slot]),
            jnp.asarray(buf), jnp.int32(slot), jnp.int32(offset),
            jnp.int32(n), jnp.int32(params.seed),
            jnp.float32(params.temperature), jnp.int32(params.top_k))
        self.state = st
        self.host_len[slot] = offset + n
        self.host_tok[slot] = int(nxt)
        return int(nxt), np.asarray(logits)

    def ring_pad(self, n):
        """Padded one-shot ring prefill width for an n-token prompt:
        the next multiple of prefill_chunk (also a multiple of the ring
        size when prefill_chunk is)."""
        c = self.prefill_chunk
        return min(((int(n) + c - 1) // c) * c, self.cache.max_len)

    def prefill_ring(self, slot, prompt, params):
        """One-shot long-context prefill through ring attention: the
        whole (padded) prompt in a single launch.  Requires ``mesh``."""
        import jax.numpy as jnp
        if self.mesh is None:
            raise ValueError('ring prefill needs a mesh with a seq axis')
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = prompt.shape[0]
        width = self.ring_pad(n)
        if n > width:
            raise ValueError('prompt of %d exceeds max_len=%d'
                             % (n, self.cache.max_len))
        buf = np.zeros(width, np.int32)
        buf[:n] = prompt
        call = self._prefill_exec(width, ring=True)
        st, nxt, logits = call(
            self.w, self.state, jnp.asarray(self.block_tables[slot]),
            jnp.asarray(buf), jnp.int32(slot), jnp.int32(0),
            jnp.int32(n), jnp.int32(params.seed),
            jnp.float32(params.temperature), jnp.int32(params.top_k))
        self.state = st
        self.host_len[slot] = n
        self.host_tok[slot] = int(nxt)
        return int(nxt), np.asarray(logits)

    # --------------------------------------------------------- decode
    def _vecs(self, active, seeds, temps, topks):
        import jax.numpy as jnp
        S = self.cache.slots
        return (jnp.asarray(np.asarray(active, bool).reshape(S)),
                jnp.asarray(np.asarray(seeds, np.int32).reshape(S)),
                jnp.asarray(np.asarray(temps, np.float32).reshape(S)),
                jnp.asarray(np.asarray(topks, np.int32).reshape(S)))

    def decode_window(self, steps, active, seeds, temps, topks):
        """Advance every ACTIVE slot ``steps`` tokens in one fused
        launch.  active/seeds/temps/topks are per-slot vectors (plain
        data — they never retrace); so is the block table.  Returns the
        [slots, steps] token matrix; inactive rows are garbage by
        contract."""
        import jax.numpy as jnp
        call = self._decode_exec(int(steps))
        act = np.asarray(active, bool).reshape(self.cache.slots)
        st, toks = call(self.w, self.state, jnp.asarray(self.block_tables),
                        *self._vecs(act, seeds, temps, topks))
        self.state = st
        out = np.asarray(toks)
        self.host_len[act] = np.minimum(
            self.host_len[act] + int(steps), np.iinfo(np.int32).max)
        self.host_tok[act] = out[act, -1]
        return out

    def verify_window(self, steps, fed, active, seeds, temps, topks):
        """Speculative verify: feed ``fed`` [slots, steps] (host-built
        per-slot rows: last emitted token then draft proposals) through
        the fused window; returns the [slots, steps] TARGET samples
        g_0..g_{K-1}.  Device lengths advance K for active slots — the
        caller MUST follow with `commit_speculation` (the host-side
        rollback) before any other launch."""
        import jax.numpy as jnp
        call = self._verify_exec(int(steps))
        fed = np.asarray(fed, np.int32).reshape(self.cache.slots,
                                                int(steps))
        st, toks = call(self.w, self.state, jnp.asarray(self.block_tables),
                        jnp.asarray(fed.T),
                        *self._vecs(active, seeds, temps, topks))
        self.state = st
        return np.asarray(toks)

    def commit_speculation(self, accepted):
        """Roll the post-verify state back to the accepted prefix.

        ``accepted`` maps slot -> (m, last_token): m tokens of the
        window were emitted (1 <= m <= K) and ``last_token`` (g_{m-1})
        is the next token to feed.  Every ACTIVE slot of the verify
        window must appear.  Rejected positions' K/V rows stay in the
        pool but sit at/past the committed length — unreachable under
        the positional mask and overwritten by the next window (pages
        are never shared at write positions).  Pure host-side metadata:
        the [slots] lengths/tok vectors are re-uploaded, no executable
        runs, nothing retraces."""
        import jax.numpy as jnp
        for slot, (m, last_tok) in accepted.items():
            self.host_len[int(slot)] += int(m)
            self.host_tok[int(slot)] = int(last_tok)
        st = dict(self.state)
        st['lengths'] = jnp.asarray(self.host_len.astype(np.int32))
        st['tok'] = jnp.asarray(self.host_tok.astype(np.int32))
        self.state = st

    # ----------------------------------------------- test conveniences
    def cache_row(self, slot):
        """Host copies (k [L, Hkv, Tmax, dh], v, length) of one slot's
        LOGICAL row, reassembled (and dequantized) through its block
        table."""
        st = self.state
        bt = self.block_tables[int(slot)]
        L, Hkv = self.cache.layers, self.cache.kv_heads
        Tmax, dh = self.cache.max_len, self.cache.head_dim

        def assemble(pool, scale):
            rows = np.asarray(pool)[bt]        # [M, L, Hkv, PL, dh]
            rows = rows.transpose(1, 2, 0, 3, 4).reshape(L, Hkv, Tmax, dh)
            if scale is None:
                return rows
            sc = np.asarray(scale)[bt]         # [M, L, Hkv, PL]
            sc = sc.transpose(1, 2, 0, 3).reshape(L, Hkv, Tmax)
            return rows.astype(np.float32) * sc[..., None]

        if self.cache.quant == 'int8':
            k = assemble(st['k'], st['k_scale'])
            v = assemble(st['v'], st['v_scale'])
        else:
            k, v = assemble(st['k'], None), assemble(st['v'], None)
        return k, v, int(np.asarray(st['lengths'][int(slot)]))

    def generate(self, prompt, max_new, params=None, steps_per_window=4,
                 use_ring=False, speculative=False):
        """Single-request convenience decode (tests, parity references):
        prefill the prompt, then advance in fused windows; returns the
        generated ids (list, length max_new).  steps_per_window=1 IS the
        sequential single-token reference path.  ``speculative`` runs
        draft-propose + fused-verify windows instead of plain decode
        (greedy streams are bitwise identical either way)."""
        from .sampling import SamplingParams, draft_ngram
        params = params or SamplingParams()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size + int(max_new) > self.cache.max_len:
            raise ValueError(
                'prompt of %d + max_new=%d exceeds max_len=%d — requests '
                'are never truncated; shorten the prompt or lower max_new'
                % (prompt.size, max_new, self.cache.max_len))
        slot = self.alloc_slot()
        if slot is None:
            raise RuntimeError('no free kv slot')
        started = False
        try:
            start = self.try_begin(slot, prompt, int(max_new))
            if start is None:
                raise RuntimeError(
                    'kv_oom: pool of %d pages cannot hold prompt of %d + '
                    'max_new=%d' % (self.pool.capacity, prompt.size,
                                    max_new))
            started = True
            first = None
            if use_ring:
                first, _ = self.prefill_ring(slot, prompt, params)
            else:
                for off in range(start, prompt.size, self.prefill_chunk):
                    chunk = prompt[off:off + self.prefill_chunk]
                    first, _ = self.prefill(slot, chunk, off, params)
            self.promote_prefix(slot, prompt)
            out = [int(first)]
            S = self.cache.slots
            active = np.zeros(S, bool)
            active[slot] = True
            seeds = np.zeros(S, np.int32)
            temps = np.zeros(S, np.float32)
            topks = np.zeros(S, np.int32)
            seeds[slot] = params.seed
            temps[slot] = params.temperature
            topks[slot] = params.top_k
            K = int(steps_per_window)
            while len(out) < int(max_new):
                if not self.ensure_capacity(
                        slot, self.host_len[slot] + K):
                    raise RuntimeError('kv_oom: pool exhausted mid-stream')
                if speculative:
                    ctx = np.concatenate([prompt, np.asarray(out,
                                                             np.int32)])
                    fed = np.zeros((S, K), np.int32)
                    fed[slot, 0] = out[-1]
                    fed[slot, 1:] = draft_ngram(ctx, K - 1)
                    g = self.verify_window(K, fed, active, seeds, temps,
                                           topks)[slot]
                    m = 1
                    while m < K and fed[slot, m] == g[m - 1]:
                        m += 1
                    _obs.metrics.counter(
                        'generation.spec_proposed').inc(K - 1)
                    _obs.metrics.counter(
                        'generation.spec_accepted').inc(m - 1)
                    self.commit_speculation({slot: (m, int(g[m - 1]))})
                    out.extend(int(t) for t in g[:m])
                else:
                    toks = self.decode_window(K, active, seeds, temps,
                                              topks)
                    out.extend(int(t) for t in toks[slot])
            return out[:int(max_new)]
        finally:
            if started:
                self.free_slot(slot)
            else:
                self.allocator.free(slot)
