"""paddle_tpu.serving — robust inference serving runtime.

Continuous batching over the Predictor/AOT-cache/FeedBucketer stack
with admission control, per-request deadlines, load shedding, a circuit
breaker, and chaos-tested graceful degradation.  See docs/serving.md.

    from paddle_tpu import serving

    engine = serving.ServingEngine.from_predictor(
        predictor, bucketer=fluid.FeedBucketer(boundaries=[1, 2, 4, 8]),
        config=serving.ServingConfig(max_queue=128,
                                     overflow_policy='shed_oldest',
                                     default_timeout_s=0.5))
    engine.start()
    engine.install_signal_handlers()          # SIGTERM -> graceful drain
    result = engine.infer({'x': batch}, timeout_s=0.2)
    if result.ok:
        probs = result.outputs[0]

The ``generation`` subpackage layers streaming autoregressive decode on
top of this engine — slotted KV cache, fused decode windows, mixed
prefill/decode batching, per-token TTFT/ITL SLOs (docs/generation.md)::

    from paddle_tpu.serving.generation import GenerationEngine
"""
from .admission import TokenBucket, OVERFLOW_POLICIES  # noqa
from .breaker import CircuitBreaker, CLOSED, HALF_OPEN, OPEN  # noqa
from .engine import (ServingConfig, ServingEngine, ServeFuture,  # noqa
                     ServeResult, STARTING, READY, DEGRADED, DRAINING,
                     STOPPED, OK, REJECTED, SHED, DEADLINE_EXCEEDED,
                     ERROR)

__all__ = ['ServingConfig', 'ServingEngine', 'ServeFuture', 'ServeResult',
           'TokenBucket', 'CircuitBreaker', 'OVERFLOW_POLICIES',
           'STARTING', 'READY', 'DEGRADED', 'DRAINING', 'STOPPED',
           'OK', 'REJECTED', 'SHED', 'DEADLINE_EXCEEDED', 'ERROR',
           'CLOSED', 'HALF_OPEN', 'OPEN']
