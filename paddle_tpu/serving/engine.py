"""ServingEngine — continuous batching over the Predictor/AOT-cache/
FeedBucketer stack, built to degrade instead of collapse.

Shape of the thing (docs/serving.md has the full architecture):

  * Clients ``submit()`` single feed dicts (leading dim = rows) and get
    a :class:`ServeFuture`.  Every admitted request is GUARANTEED a
    terminal reply — result, deadline-exceeded, shed, or error — even
    through drain and engine stop; a request that never resolves is a
    bug and is counted as ``serving.deadlocks``.
  * A dedicated dispatch thread coalesces queued requests with the same
    feed signature into one superbatch, pads it onto a FeedBucketer
    boundary (so every batch hits a warm AOT executable), runs the
    backend once, and scatters per-request row slices back out.
  * Admission control happens in the CLIENT's thread, before a request
    costs the dispatcher anything: state gate (draining engines refuse),
    shape sanity (batch=0 and bigger-than-the-largest-bucket requests
    are rejected with a clear error, never truncated), per-request
    deadlines (an already-expired deadline is refused at the door;
    queued requests past deadline are dropped PRE-dispatch — compute is
    never spent on an answer nobody is waiting for), a token-bucket
    rate limiter, and a bounded queue with a configurable overflow
    policy (``reject`` / ``block`` / ``shed_oldest``).
  * A :class:`~paddle_tpu.serving.breaker.CircuitBreaker` trips on
    consecutive batch failures or compile-miss storms and flips the
    engine to a one-request-at-a-time slow path until a probe batch
    succeeds; health moves ``STARTING → READY → (DEGRADED) → DRAINING
    → STOPPED``, and SIGTERM begins a drain that finishes in-flight
    work while refusing new requests (chained with the PR-6 checkpoint
    flush handlers via core/signals.py).

Chaos-tested: the ``serve_dispatch`` / ``serve_slow_batch`` /
``queue_overflow`` / ``compile_storm`` PT_FAULT sites break each layer
deterministically, and ``tools/serve_soak.py`` asserts the SLOs while
they fire.
"""
import collections
import contextlib
import signal as _sigmod
import threading
import time

import numpy as np

from .. import observability as _obs
from ..observability import flight as _flight
from ..observability import trace_context as _tc
from ..core import signals as _signals
from ..testing import faults as _faults
from .admission import OVERFLOW_POLICIES, TokenBucket
from .breaker import CLOSED, CircuitBreaker

__all__ = ['ServingConfig', 'ServingEngine', 'ServeFuture', 'ServeResult',
           'STARTING', 'READY', 'DEGRADED', 'DRAINING', 'STOPPED',
           'OK', 'REJECTED', 'SHED', 'DEADLINE_EXCEEDED', 'ERROR']

# engine health states
STARTING, READY, DEGRADED = 'starting', 'ready', 'degraded'
DRAINING, STOPPED = 'draining', 'stopped'
_STATE_GAUGE = {STARTING: 0, READY: 1, DEGRADED: 2, DRAINING: 3, STOPPED: 4}

# terminal reply statuses
OK, REJECTED, SHED = 'ok', 'rejected', 'shed'
DEADLINE_EXCEEDED, ERROR = 'deadline_exceeded', 'error'


class ServingConfig(object):
    """Knobs for one engine.  Everything has a serving-shaped default;
    the env-var table lives in docs/serving.md."""

    def __init__(self, max_queue=64, overflow_policy='reject',
                 block_timeout_s=1.0, max_batch_rows=64,
                 batch_linger_s=0.0, default_timeout_s=None,
                 rate_qps=None, rate_burst=None,
                 breaker_failure_threshold=3, breaker_storm_threshold=3,
                 breaker_cooldown_s=0.25, drain_timeout_s=10.0,
                 metrics_port=None):
        if overflow_policy not in OVERFLOW_POLICIES:
            raise ValueError('overflow_policy must be one of %s, got %r'
                             % (OVERFLOW_POLICIES, overflow_policy))
        if int(max_queue) < 1:
            raise ValueError('max_queue must be >= 1')
        if int(max_batch_rows) < 1:
            raise ValueError('max_batch_rows must be >= 1')
        self.max_queue = int(max_queue)
        self.overflow_policy = overflow_policy
        self.block_timeout_s = float(block_timeout_s)
        self.max_batch_rows = int(max_batch_rows)
        self.batch_linger_s = float(batch_linger_s)
        self.default_timeout_s = default_timeout_s
        self.rate_qps = rate_qps
        self.rate_burst = rate_burst
        self.breaker_failure_threshold = int(breaker_failure_threshold)
        self.breaker_storm_threshold = int(breaker_storm_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.drain_timeout_s = float(drain_timeout_s)
        # /metrics endpoint port: explicit int beats PT_METRICS_PORT
        # (0 = ephemeral, for tests); None + no env = no server
        self.metrics_port = metrics_port


class ServeResult(object):
    """One terminal reply.  ``status`` is one of ``ok`` / ``rejected`` /
    ``shed`` / ``deadline_exceeded`` / ``error``; ``outputs`` is the
    per-request list of fetch arrays (``ok`` only); ``error`` carries
    the exception (``error``) or a human-readable refusal message
    (``rejected`` / ``shed``); ``reason`` is the machine-readable
    refusal tag mirrored in ``serving.rejected.<reason>``."""
    __slots__ = ('status', 'outputs', 'error', 'reason', 'latency_s')

    def __init__(self, status, outputs=None, error=None, reason=None,
                 latency_s=None):
        self.status = status
        self.outputs = outputs
        self.error = error
        self.reason = reason
        self.latency_s = latency_s

    @property
    def ok(self):
        return self.status == OK

    def __repr__(self):
        return ('ServeResult(%s%s%s)'
                % (self.status,
                   ', reason=%r' % self.reason if self.reason else '',
                   ', latency=%.1fms' % (self.latency_s * 1e3)
                   if self.latency_s is not None else ''))


class ServeFuture(object):
    """Client handle: blocks in ``result()`` until the terminal reply.
    ``traceparent`` is the request's W3C trace header (None with
    PT_OBS=0) — the id to look up in a Perfetto export."""
    __slots__ = ('_event', '_result', '_lock', 'traceparent')

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._lock = threading.Lock()
        self.traceparent = None

    def _resolve(self, result):
        with self._lock:
            if self._result is not None:
                return False
            self._result = result
        self._event.set()
        return True

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError('serving reply not ready within %r s'
                               % timeout)
        return self._result

    @property
    def status(self):
        return self._result.status if self._result is not None else None


class _Request(object):
    __slots__ = ('feed', 'rows', 'signature', 'deadline', 'future',
                 't_submit', 'trace', 't_pc')

    def __init__(self, feed, rows, signature, deadline, t_submit,
                 trace=None, t_pc=None):
        self.feed = feed
        self.rows = rows
        self.signature = signature
        self.deadline = deadline
        self.future = ServeFuture()
        self.t_submit = t_submit
        # tracing: the request's root TraceContext and the perf_counter
        # submit mark its spans measure from (both None with PT_OBS=0)
        self.trace = trace
        self.t_pc = t_pc
        if trace is not None:
            self.future.traceparent = trace.to_traceparent()


class ServingEngine(object):
    """See module docstring.  ``backend`` is any callable
    ``feed_dict -> list of per-row output arrays`` — usually a
    :class:`~paddle_tpu.inference.Predictor` (whose per-shape AOT cache
    + single-flight compile lock this engine was built around), but a
    plain function works, which is how the unit tests chaos-test the
    engine without compiling anything."""

    def __init__(self, backend, bucketer=None, config=None,
                 clock=time.monotonic):
        self._backend = backend
        self._bucketer = bucketer
        self._cfg = config or ServingConfig()
        self._clock = clock
        self._cond = threading.Condition()
        self._queue = collections.deque()
        self._state = STARTING
        self._stopping = False
        self._thread = None
        self._stopped = threading.Event()
        self._out_lock = threading.Lock()
        self._outstanding = set()
        self._rate = (TokenBucket(self._cfg.rate_qps, self._cfg.rate_burst,
                                  clock=clock)
                      if self._cfg.rate_qps else None)
        self.breaker = CircuitBreaker(
            failure_threshold=self._cfg.breaker_failure_threshold,
            storm_threshold=self._cfg.breaker_storm_threshold,
            cooldown_s=self._cfg.breaker_cooldown_s, clock=clock)
        # the hard per-request size ceiling: a request that cannot fit
        # the largest bucket would silently retrace per shape (or worse,
        # invite truncation); refuse it at the door instead
        self._row_limit = self._cfg.max_batch_rows
        if bucketer is not None:
            self._row_limit = min(self._row_limit,
                                  int(bucketer.boundaries[-1]))
        self._http = None
        _obs.metrics.gauge('serving.state').set(_STATE_GAUGE[STARTING])

    @classmethod
    def from_predictor(cls, predictor, bucketer=None, config=None, **kw):
        eng = cls(predictor.run, bucketer=bucketer, config=config, **kw)
        eng._predictor = predictor
        return eng

    # ----------------------------------------------------------- state
    def _set_state(self, state):
        self._state = state
        _obs.metrics.gauge('serving.state').set(_STATE_GAUGE[state])
        _obs.tracing.instant('serving.state', cat='serving',
                             args={'state': state})

    @property
    def state(self):
        """Health state; READY shows as DEGRADED while the breaker is
        not closed (still serving, but on the slow path)."""
        with self._cond:
            s = self._state
        if s == READY and self.breaker.state != CLOSED:
            return DEGRADED
        return s

    def ready(self):
        """Readiness probe: accepting new requests?"""
        return self.state in (READY, DEGRADED)

    def health(self):
        with self._cond:
            depth = len(self._queue)
        with self._out_lock:
            outstanding = len(self._outstanding)
        return {'state': self.state, 'queue_depth': depth,
                'outstanding': outstanding, 'breaker': self.breaker.state,
                'accepting': self.ready()}

    # ----------------------------------------------------- lifecycle
    def start(self):
        with self._cond:
            if self._thread is not None:
                return self
            self._thread = threading.Thread(target=self._loop,
                                            name='ServingDispatch',
                                            daemon=True)
            self._set_state(READY)
            self._thread.start()
        self._start_metrics_server()
        return self

    def _start_metrics_server(self):
        """/metrics + /healthz + /varz, engine-owned: up at start(),
        down at stop().  Enabled by ServingConfig.metrics_port or
        PT_METRICS_PORT; inert under PT_OBS=0."""
        if self._http is not None or not _obs.enabled():
            return
        port = _obs.export.resolve_metrics_port(self._cfg.metrics_port)
        if port is None:
            return
        self._http = _obs.export.start_http_server(port, engine=self)

    @property
    def metrics_port(self):
        """Bound /metrics port, or None when no server is running."""
        return self._http.port if self._http is not None else None

    def stop_metrics_server(self):
        http, self._http = self._http, None
        if http is not None:
            http.stop()

    def begin_drain(self):
        """Refuse new requests, keep dispatching until the queue is
        empty, then stop.  Non-blocking (signal-handler safe)."""
        with self._cond:
            if self._state in (DRAINING, STOPPED):
                return
            started = self._thread is not None
            self._set_state(DRAINING)
            self._cond.notify_all()
        if not started:
            self._finish_stop()

    def wait_drained(self, timeout=None):
        return self._stopped.wait(timeout)

    def drain(self, timeout=None):
        """begin_drain + wait; returns True when fully stopped."""
        self.begin_drain()
        ok = self.wait_drained(self._cfg.drain_timeout_s
                               if timeout is None else timeout)
        if self._thread is not None:
            self._thread.join(timeout=1.0)
        return ok

    def stop(self, timeout=None):
        """Drain, then force the dispatch loop down if the drain budget
        expires — leftover queued requests still get terminal (shed)
        replies."""
        self.begin_drain()
        budget = self._cfg.drain_timeout_s if timeout is None else timeout
        if not self.wait_drained(budget):
            with self._cond:
                self._stopping = True
                self._cond.notify_all()
            self.wait_drained(5.0)
        if self._thread is not None:
            self._thread.join(timeout=1.0)
        self.stop_metrics_server()
        return self._stopped.is_set()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def install_signal_handlers(self, signums=(_sigmod.SIGTERM,)):
        """SIGTERM → graceful drain: in-flight and queued requests
        finish, new ones are refused, then the previous handler (e.g.
        the Checkpointer's final flush) runs via the core/signals chain.
        With no previous handler the signal is NOT re-delivered — the
        process is expected to exit once ``wait_drained()`` returns.
        Idempotent and main-thread-guarded (worker threads warn once
        and skip)."""

        def make(signum, prev):
            def _handler(s, frame):
                _obs.metrics.counter('serving.signal_drains').inc()
                _flight.record('serving.signal_drain', signum=int(s))
                self.begin_drain()
                _flight.maybe_dump('sigterm')
                _signals.chain_previous(prev, s, frame, redeliver=False)
            return _handler

        return _signals.install(('serving', id(self)), signums,
                                make) is not None

    def uninstall_signal_handlers(self):
        _signals.uninstall(('serving', id(self)))

    # ----------------------------------------------------- admission
    def submit(self, feed, timeout_s=None):
        """Submit one request (dict name -> array with a leading batch
        dim).  Always returns a :class:`ServeFuture`; refusals come back
        as an already-terminal ``rejected`` result with a named reason,
        never an exception and never silence."""
        t_submit = self._clock()
        obs_on = _obs.enabled()
        trace = _tc.TraceContext.new() if obs_on else None
        t_pc = time.perf_counter() if obs_on else None
        _obs.metrics.counter('serving.submitted').inc()
        try:
            arrays = {k: np.asarray(v) for k, v in dict(feed).items()}
        except Exception as e:
            return self._rejected(t_submit, 'bad_request',
                                  'unfeedable request: %r' % (e,),
                                  trace, t_pc)
        if not arrays:
            return self._rejected(t_submit, 'bad_request',
                                  'empty feed dict', trace, t_pc)
        dims = {a.shape[0] for a in arrays.values() if a.ndim >= 1}
        if len(dims) != 1 or any(a.ndim == 0 for a in arrays.values()):
            return self._rejected(
                t_submit, 'bad_request',
                'request feeds need one shared leading batch dim; got '
                'shapes %s' % {k: a.shape for k, a in arrays.items()},
                trace, t_pc)
        rows = dims.pop()
        if rows == 0:
            return self._rejected(
                t_submit, 'empty_batch',
                'batch=0 request rejected: a serving request must carry '
                'at least one row (got leading dim 0)', trace, t_pc)
        if rows > self._row_limit:
            return self._rejected(
                t_submit, 'too_large',
                'request batch %d exceeds the serving limit %d (largest '
                'bucket boundary / max_batch_rows); split the request — '
                'nothing is silently truncated' % (rows, self._row_limit),
                trace, t_pc)
        if timeout_s is None:
            timeout_s = self._cfg.default_timeout_s
        deadline = None
        if timeout_s is not None:
            if timeout_s <= 0:
                return self._rejected(
                    t_submit, 'deadline',
                    'deadline already expired at admission '
                    '(timeout_s=%r)' % timeout_s, trace, t_pc)
            deadline = t_submit + float(timeout_s)
        if self._rate is not None and not self._rate.try_acquire():
            return self._rejected(t_submit, 'rate',
                                  'token-bucket rate limit exceeded '
                                  '(rate_qps=%r)' % self._cfg.rate_qps,
                                  trace, t_pc)
        signature = tuple(sorted((k, str(a.dtype), a.shape[1:])
                                 for k, a in arrays.items()))
        req = _Request(arrays, int(rows), signature, deadline, t_submit,
                       trace=trace, t_pc=t_pc)
        fut = self._admit(req, t_submit)
        if trace is not None:
            # the caller-thread slice the Perfetto flow arrow starts
            # from; the matching 'f' binds to the batch slice
            t_now = time.perf_counter()
            _obs.tracing.recorder().add_complete(
                'serving.submit', t_pc, t_now, cat='serving',
                args=trace.span_args(rows=int(rows)))
            _obs.tracing.add_flow(trace.trace_id[:16], 's', t_pc,
                                  name='serving.link', cat='serving')
        return fut

    def _admit(self, req, t_submit):
        cfg = self._cfg
        with self._cond:
            if self._state != READY:
                reason = ('not_ready' if self._state == STARTING
                          else 'draining')
                return self._rejected_locked(
                    req, reason, 'engine is %s; request refused'
                    % self._state)
            overflow = len(self._queue) >= cfg.max_queue
            if not overflow and _faults.any_active() \
                    and _faults.fire('queue_overflow'):
                overflow = True
            if overflow and cfg.overflow_policy == 'block':
                limit = t_submit + cfg.block_timeout_s
                while len(self._queue) >= cfg.max_queue \
                        and self._state == READY:
                    left = limit - self._clock()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                if self._state != READY:
                    return self._rejected_locked(
                        req, 'draining', 'engine began draining while '
                        'blocked on a full queue')
                overflow = len(self._queue) >= cfg.max_queue
            shed_req = None
            if overflow:
                if cfg.overflow_policy == 'shed_oldest' and self._queue:
                    shed_req = self._queue.popleft()
                elif cfg.overflow_policy != 'shed_oldest':
                    return self._rejected_locked(
                        req, 'full', 'request queue full '
                        '(max_queue=%d, policy=%s)'
                        % (cfg.max_queue, cfg.overflow_policy))
            self._queue.append(req)
            with self._out_lock:
                self._outstanding.add(req)
            _obs.metrics.counter('serving.admitted').inc()
            _obs.metrics.gauge('serving.queue_depth').set(len(self._queue))
            self._cond.notify_all()
        if shed_req is not None:
            self._resolve(shed_req, SHED, reason='overflow',
                          error='shed: newest request displaced the '
                                'oldest queued one (shed_oldest policy)')
        return req.future

    def _emit_root_span(self, trace, t_pc, status, reason=None, rows=None):
        """The request's single root span, `serving.request` — emitted
        exactly once, at terminal resolution, so its status IS the
        terminal reply's status."""
        if trace is None or t_pc is None:
            return
        args = trace.span_args(status=status)
        if reason:
            args['reason'] = reason
        if rows is not None:
            args['rows'] = int(rows)
        _obs.tracing.recorder().add_complete(
            'serving.request', t_pc, time.perf_counter(), cat='serving',
            args=args)

    def _rejected(self, t_submit, reason, message, trace=None, t_pc=None):
        fut = ServeFuture()
        if trace is not None:
            fut.traceparent = trace.to_traceparent()
        fut._resolve(ServeResult(REJECTED, error=message, reason=reason,
                                 latency_s=self._clock() - t_submit))
        _obs.metrics.counter('serving.rejected').inc()
        _obs.metrics.counter('serving.rejected.%s' % reason).inc()
        self._emit_root_span(trace, t_pc, REJECTED, reason=reason)
        return fut

    def _rejected_locked(self, req, reason, message):
        # admission refusals for an already-built request (still not in
        # the queue/outstanding set, so plain reject accounting applies)
        fut = req.future
        fut._resolve(ServeResult(REJECTED, error=message, reason=reason,
                                 latency_s=self._clock() - req.t_submit))
        _obs.metrics.counter('serving.rejected').inc()
        _obs.metrics.counter('serving.rejected.%s' % reason).inc()
        self._emit_root_span(req.trace, req.t_pc, REJECTED, reason=reason,
                             rows=req.rows)
        return fut

    def infer(self, feed, timeout_s=None, wait_timeout=None):
        """Blocking convenience: ``submit().result()``."""
        return self.submit(feed, timeout_s=timeout_s).result(wait_timeout)

    # ----------------------------------------------------- dispatch
    def _loop(self):
        try:
            while True:
                expired, batch, mode = self._next_batch()
                for r in expired:
                    self._resolve(
                        r, DEADLINE_EXCEEDED, reason='queue_wait',
                        error='deadline expired while queued; dropped '
                              'pre-dispatch (no compute was spent)')
                if batch is None:
                    return
                if batch:
                    self._run_batch(batch, mode)
        finally:
            self._finish_stop()

    def _next_batch(self):
        """Returns (expired_requests, batch|None, mode); batch None means
        the loop should exit (drained or force-stopped)."""
        cfg = self._cfg
        with self._cond:
            while True:
                if self._stopping:
                    return [], None, None
                if self._queue:
                    break
                if self._state == DRAINING:
                    return [], None, None
                self._cond.wait(0.05)
            if cfg.batch_linger_s > 0 and self._state == READY \
                    and sum(r.rows for r in self._queue) \
                    < cfg.max_batch_rows:
                self._cond.wait(cfg.batch_linger_s)
            now = self._clock()
            expired = [r for r in self._queue
                       if r.deadline is not None and r.deadline <= now]
            if expired:
                gone = set(map(id, expired))
                self._queue = collections.deque(
                    r for r in self._queue if id(r) not in gone)
            mode = self.breaker.mode()
            batch, taken_rows = [], 0
            if self._queue:
                if mode == 'slow':
                    batch.append(self._queue.popleft())
                else:
                    sig = self._queue[0].signature
                    keep = collections.deque()
                    for r in self._queue:
                        if r.signature == sig \
                                and taken_rows + r.rows \
                                <= cfg.max_batch_rows:
                            batch.append(r)
                            taken_rows += r.rows
                        else:
                            keep.append(r)
                    self._queue = keep
            _obs.metrics.gauge('serving.queue_depth').set(len(self._queue))
            self._cond.notify_all()   # wake blocked submitters
        return expired, batch, mode

    def _compile_marks(self):
        if not _obs.enabled():
            return 0
        c = _obs.metrics.counters()
        return sum(int(c.get(k) or 0)
                   for k in ('executor.compiles', 'executor.retraces',
                             'compile_cache.disk_misses'))

    def _emit_batch_span(self, batch, batch_ctx, t0, t_end, mode,
                         total_rows, pad_rows, cold, status):
        """The `serving.batch` span: one per dispatch, *linking* every
        coalesced request's trace (args.links + a flow 'f' per request),
        so a Perfetto export walks request root -> batch -> executor."""
        rec = _obs.tracing.recorder()
        args = batch_ctx.span_args(
            links=[r.trace.trace_id for r in batch if r.trace is not None],
            requests=len(batch), rows=int(total_rows),
            pad_rows=int(pad_rows), mode=mode or 'normal',
            cold=bool(cold), status=status)
        rec.add_complete('serving.batch', t0, t_end, cat='serving',
                         args=args)
        for r in batch:
            if r.trace is not None:
                rec.add_flow(r.trace.trace_id[:16], 'f', t0,
                             name='serving.link', cat='serving')

    def _run_batch(self, batch, mode):
        t0 = time.perf_counter()
        now = self._clock()
        obs_on = _obs.enabled()
        batch_ctx = _tc.TraceContext.new() if obs_on else None
        for r in batch:
            _obs.metrics.histogram('serving.queue_wait_ms').observe(
                max(0.0, (now - r.t_submit) * 1e3))
            if batch_ctx is not None and r.trace is not None:
                # queue-wait child: submit -> dispatch pick
                _obs.tracing.recorder().add_complete(
                    'serving.queue_wait', r.t_pc, t0, cat='serving',
                    args={'trace_id': r.trace.trace_id,
                          'parent_span_id': r.trace.span_id,
                          'batch_span_id': batch_ctx.span_id})
        total_rows = sum(r.rows for r in batch)
        cold = False
        if _faults.any_active():
            _faults.maybe_sleep('serve_slow_batch')
            if _faults.maybe_sleep('compile_storm'):
                cold = True
        marks = self._compile_marks()
        if len(batch) == 1:
            feed = batch[0].feed
        else:
            feed = {k: np.concatenate([r.feed[k] for r in batch])
                    for k in batch[0].feed}
        if self._bucketer is not None:
            feed, _true = self._bucketer.bucket_feed(feed)
        pad_rows = 0
        for a in feed.values():
            if getattr(a, 'ndim', 0) >= 1:
                pad_rows = max(0, int(a.shape[0]) - total_rows)
                break
        t_dev0 = time.perf_counter()
        if batch_ctx is not None:
            for r in batch:
                if r.trace is not None:
                    # dispatch child: coalesce + pad onto the bucket
                    _obs.tracing.recorder().add_complete(
                        'serving.dispatch', t0, t_dev0, cat='serving',
                        args={'trace_id': r.trace.trace_id,
                              'parent_span_id': r.trace.span_id,
                              'batch_span_id': batch_ctx.span_id,
                              'pad_rows': int(pad_rows)})
        try:
            if _faults.any_active():
                _faults.maybe_fail('serve_dispatch')
            with contextlib.ExitStack() as ctxs:
                if batch_ctx is not None:
                    # executor/predictor spans under this dispatch join
                    # the batch trace via the ambient context
                    ctxs.enter_context(_tc.use(batch_ctx))
                if mode in ('slow', 'probe'):
                    # degraded-mode dispatches are intentionally slow —
                    # their launch gaps are not pipeline stalls
                    ctxs.enter_context(
                        _obs.stall.suppress('breaker_%s' % mode))
                outs = self._backend(feed)
        except BaseException as e:  # noqa: BLE001 - replied per request
            self.breaker.record_failure()
            _obs.metrics.counter('serving.batch_failures').inc()
            t_fail = time.perf_counter()
            if batch_ctx is not None:
                self._emit_device_spans(batch, batch_ctx, t_dev0, t_fail)
                self._emit_batch_span(batch, batch_ctx, t0, t_fail, mode,
                                      total_rows, pad_rows, cold, ERROR)
            _flight.record('serving.batch_failure', error=repr(e)[:300],
                           rows=int(total_rows), requests=len(batch),
                           mode=mode or 'normal')
            for r in batch:
                self._resolve(r, ERROR, error=e, reason='dispatch')
            _flight.maybe_dump('serving_batch_failure')
            return
        t_dev1 = time.perf_counter()
        if self._compile_marks() > marks:
            cold = True
        if cold:
            _obs.metrics.counter('serving.cold_compiles').inc()
            self.breaker.record_cold()
        self.breaker.record_success(cold=cold)
        outs = [np.asarray(o) for o in outs]
        if batch_ctx is not None:
            self._emit_device_spans(batch, batch_ctx, t_dev0, t_dev1)
        # scatter: per-row outputs slice back to their request; outputs
        # without the batch leading dim (batch-aggregate fetches) are
        # handed to every request whole
        off = 0
        for r in batch:
            slices = []
            for o in outs:
                if o.ndim >= 1 and o.shape[0] >= total_rows:
                    slices.append(o[off:off + r.rows])
                else:
                    slices.append(o)
            off += r.rows
            self._resolve(r, OK, outputs=slices)
        _obs.metrics.counter('serving.batches').inc()
        if mode == 'slow':
            _obs.metrics.counter('serving.slow_path_batches').inc()
        _obs.metrics.histogram('serving.batch_rows').observe(total_rows)
        t_end = time.perf_counter()
        if batch_ctx is not None:
            self._emit_batch_span(batch, batch_ctx, t0, t_end, mode,
                                  total_rows, pad_rows, cold, OK)
        _obs.metrics.histogram('serving.batch_ms').observe(
            (t_end - t0) * 1e3)

    def _emit_device_spans(self, batch, batch_ctx, t_dev0, t_dev1):
        """Per-request `serving.device` child: the backend-call window
        (compile miss + device time) the request rode in."""
        rec = _obs.tracing.recorder()
        for r in batch:
            if r.trace is not None:
                rec.add_complete(
                    'serving.device', t_dev0, t_dev1, cat='serving',
                    args={'trace_id': r.trace.trace_id,
                          'parent_span_id': r.trace.span_id,
                          'batch_span_id': batch_ctx.span_id})

    # ----------------------------------------------------- resolution
    def _resolve(self, req, status, outputs=None, error=None, reason=None):
        res = ServeResult(status, outputs=outputs, error=error,
                          reason=reason,
                          latency_s=self._clock() - req.t_submit)
        if not req.future._resolve(res):
            return
        with self._out_lock:
            self._outstanding.discard(req)
        # exactly one root span per request, status = the terminal reply
        self._emit_root_span(req.trace, req.t_pc, status, reason=reason,
                             rows=req.rows)
        if status == OK:
            _obs.metrics.counter('serving.completed').inc()
            _obs.metrics.histogram('serving.latency_ms').observe(
                res.latency_s * 1e3)
        elif status == SHED:
            _obs.metrics.counter('serving.shed').inc()
        elif status == DEADLINE_EXCEEDED:
            _obs.metrics.counter('serving.deadline_exceeded').inc()
        elif status == ERROR:
            _obs.metrics.counter('serving.errors').inc()
        elif status == REJECTED:
            _obs.metrics.counter('serving.rejected').inc()
            if reason:
                _obs.metrics.counter('serving.rejected.%s' % reason).inc()

    def _finish_stop(self):
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self._set_state(STOPPED)
            self._cond.notify_all()
        for r in leftovers:
            self._resolve(r, SHED, reason='shutdown',
                          error='engine stopped before dispatch; request '
                                'shed during shutdown')
        # the deadlock audit: every admitted request was either batched
        # (resolved by _run_batch), expired (resolved by the loop), or a
        # leftover (just shed).  Anything still outstanding fell through
        # a crack — give it a terminal reply and make the bug loud.
        with self._out_lock:
            stragglers = list(self._outstanding)
            self._outstanding.clear()
        for r in stragglers:
            _obs.metrics.counter('serving.deadlocks').inc()
            self._resolve(r, ERROR, reason='deadlock',
                          error='engine stopped with this request '
                                'unresolved — serving bug (counted in '
                                'serving.deadlocks)')
        self._stopped.set()
