"""Admission control primitives for the serving engine.

Admission is the cheap front door: every check here runs in the
submitting client's thread, before a request costs the dispatch thread
anything.  The engine composes three gates —

  * request validation (shape sanity: batch >= 1, within the largest
    bucket boundary),
  * a :class:`TokenBucket` rate limiter (``rate_qps`` / ``rate_burst``),
  * bounded-queue overflow policy (``reject`` / ``block`` /
    ``shed_oldest``)

— and every refusal is a TERMINAL reply with a named reason, never a
silent drop (``serving.rejected.<reason>`` counters).
"""
import threading
import time

__all__ = ['TokenBucket', 'OVERFLOW_POLICIES']

OVERFLOW_POLICIES = ('reject', 'block', 'shed_oldest')


class TokenBucket(object):
    """Classic token bucket: ``qps`` tokens/second refill up to a
    ``burst`` ceiling; an admission costs one token.  The clock is
    injectable so tests (and deterministic soaks) can drive it."""

    def __init__(self, qps, burst=None, clock=time.monotonic):
        qps = float(qps)
        if qps <= 0:
            raise ValueError('rate_qps must be > 0, got %r' % qps)
        self.qps = qps
        self.burst = float(burst if burst is not None else max(1.0, qps))
        self._tokens = self.burst
        self._clock = clock
        self._last = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n=1.0):
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.qps)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def tokens(self):
        with self._lock:
            now = self._clock()
            return min(self.burst,
                       self._tokens + (now - self._last) * self.qps)
