"""Circuit breaker for the serving dispatch path.

The engine keeps superbatching while the backend is healthy.  Two
distinct failure smells trip the breaker:

  * **Consecutive batch failures** — the backend raising on every
    dispatch (a wedged device, a poisoned executable).  Re-batching
    into the same wall just multiplies blast radius.
  * **Compile-cache-miss storms** — a run of batches that each needed a
    cold compile (a client population suddenly sending never-seen
    shapes).  A 60-second compile at superbatch size stalls EVERY
    queued request behind it; serving cold traffic one request at a
    time bounds the damage to the cold requests themselves.

States follow the classic three-state machine:

  ``closed``     normal superbatching
  ``open``       tripped; the engine serves the SLOW PATH (one request
                 per dispatch) until ``cooldown_s`` elapses
  ``half_open``  cooldown elapsed; the next dispatch is a normal-sized
                 probe batch — success closes the breaker
                 (``serving.breaker_recoveries``), failure re-opens it

All transitions are counted (``serving.breaker_trips`` /
``serving.breaker_recoveries``) and the current state is exported as the
``serving.breaker_state`` gauge (0=closed, 1=half_open, 2=open).
"""
import threading
import time

from .. import observability as _obs
from ..observability import flight as _flight

__all__ = ['CircuitBreaker', 'CLOSED', 'OPEN', 'HALF_OPEN']

CLOSED, HALF_OPEN, OPEN = 'closed', 'half_open', 'open'
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker(object):
    def __init__(self, failure_threshold=3, storm_threshold=3,
                 cooldown_s=0.25, clock=time.monotonic):
        self.failure_threshold = max(1, int(failure_threshold))
        self.storm_threshold = max(1, int(storm_threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consec_failures = 0
        self._consec_cold = 0
        self._opened_at = None
        self.trips = 0
        self.recoveries = 0

    @property
    def state(self):
        with self._lock:
            return self._state

    def _set_state(self, state):
        self._state = state
        _obs.metrics.gauge('serving.breaker_state').set(_STATE_GAUGE[state])

    def _trip(self, reason):
        tripped = False
        if self._state != OPEN:
            tripped = True
            self.trips += 1
            _obs.metrics.counter('serving.breaker_trips').inc()
            _obs.tracing.instant('serving.breaker_trip', cat='serving',
                                 args={'reason': reason})
        self._set_state(OPEN)
        self._opened_at = self._clock()
        self._consec_failures = 0
        self._consec_cold = 0
        return tripped

    def record_failure(self):
        """A dispatched batch raised."""
        with self._lock:
            if self._state == HALF_OPEN:
                tripped, reason = self._trip('probe_failed'), 'probe_failed'
            else:
                self._consec_failures += 1
                tripped, reason = False, 'consecutive_failures'
                if self._consec_failures >= self.failure_threshold:
                    tripped = self._trip(reason)
        if tripped:
            # a trip is a postmortem-worthy transition; dump outside the
            # lock so the artifact write never blocks state reads
            _flight.maybe_dump('breaker_trip', extra={'reason': reason})

    def record_cold(self):
        """A dispatched batch needed a cold compile."""
        tripped = False
        with self._lock:
            if self._state == OPEN:
                return
            self._consec_cold += 1
            if self._consec_cold >= self.storm_threshold:
                tripped = self._trip('compile_storm')
        if tripped:
            _flight.maybe_dump('breaker_trip',
                               extra={'reason': 'compile_storm'})

    def record_success(self, cold=False):
        """A dispatched batch completed (``cold``: it also compiled —
        a success for its requests, still a storm signal)."""
        with self._lock:
            self._consec_failures = 0
            if not cold:
                self._consec_cold = 0
            if self._state == HALF_OPEN:
                self._set_state(CLOSED)
                self.recoveries += 1
                _obs.metrics.counter('serving.breaker_recoveries').inc()
                _obs.tracing.instant('serving.breaker_recovered',
                                     cat='serving')

    def mode(self):
        """Dispatch decision, one call per batch: ``'normal'`` (closed),
        ``'slow'`` (open, serve one request per dispatch), ``'probe'``
        (half-open: normal-sized batch whose outcome settles the
        state)."""
        with self._lock:
            if self._state == CLOSED:
                return 'normal'
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._set_state(HALF_OPEN)
                    return 'probe'
                return 'slow'
            return 'probe'
