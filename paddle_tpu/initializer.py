"""Parameter initializers — emit init ops into the startup program.

Parity: reference python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArray).  Random inits
lower to jax.random ops keyed off the startup program's seed.
"""
import numpy as np

from .core.framework import default_startup_program
from .core.dtypes import dtype_str  # noqa: F401 - legacy re-export

__all__ = [
    'Constant', 'Uniform', 'Normal', 'TruncatedNormal', 'Xavier', 'Bilinear',
    'MSRA', 'ConstantInitializer', 'UniformInitializer', 'NormalInitializer',
    'TruncatedNormalInitializer', 'XavierInitializer', 'BilinearInitializer',
    'MSRAInitializer', 'NumpyArrayInitializer', 'force_init_on_cpu',
    'init_on_cpu',
]


def force_init_on_cpu():
    return False


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    yield


class Initializer(object):
    def __call__(self, var, block=None):
        raise NotImplementedError

    def _startup_var(self, var):
        """Mirror the param var into the startup program and return the
        startup block to append the init op to."""
        sblock = default_startup_program().global_block()
        if var.name not in sblock.vars:
            sblock.create_var(name=var.name, shape=var.shape,
                              dtype=var.dtype, persistable=True)
        return sblock


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block=None):
        sb = self._startup_var(var)
        sb.append_op(type='fill_constant', inputs={},
                     outputs={'Out': sb.vars[var.name]},
                     attrs={'shape': list(var.shape), 'value': self.value,
                            'dtype': var.dtype})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block=None):
        sb = self._startup_var(var)
        sb.append_op(type='uniform_random', inputs={},
                     outputs={'Out': sb.vars[var.name]},
                     attrs={'shape': list(var.shape), 'min': self.low,
                            'max': self.high, 'seed': self.seed,
                            'dtype': var.dtype})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block=None):
        sb = self._startup_var(var)
        sb.append_op(type='gaussian_random', inputs={},
                     outputs={'Out': sb.vars[var.name]},
                     attrs={'shape': list(var.shape), 'mean': self.loc,
                            'std': self.scale, 'seed': self.seed,
                            'dtype': var.dtype})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block=None):
        sb = self._startup_var(var)
        sb.append_op(type='truncated_gaussian_random', inputs={},
                     outputs={'Out': sb.vars[var.name]},
                     attrs={'shape': list(var.shape), 'mean': self.loc,
                            'std': self.scale, 'seed': self.seed,
                            'dtype': var.dtype})


def _fans(var):
    shape = var.shape
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) >= 3:
        recep = int(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * recep, shape[0] * recep
    else:
        fan_in = fan_out = int(np.prod(shape))
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block=None):
        fan_in, fan_out = _fans(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        fan_out = self.fan_out if self.fan_out is not None else fan_out
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / (fan_in + fan_out)))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block=None):
        fan_in, _ = _fans(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        if self.uniform:
            limit = float(np.sqrt(6.0 / fan_in))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / fan_in))
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose (ref
    initializer.py BilinearInitializer)."""

    def __call__(self, var, block=None):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError('Bilinear init needs a 4-D conv weight')
        c_out, c_in, kh, kw = shape
        f = np.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, dtype='float32')
        og = np.ogrid[:kh, :kw]
        filt = (1 - abs(og[0] / f - c)) * (1 - abs(og[1] / f - c))
        w[range(c_out), range(c_in) if c_in == c_out else 0] = filt
        NumpyArrayInitializer(w)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block=None):
        sb = self._startup_var(var)
        sb.append_op(type='assign_value', inputs={},
                     outputs={'Out': sb.vars[var.name]},
                     attrs={'shape': list(self.value.shape),
                            'values': self.value.reshape(-1).tolist(),
                            'dtype': var.dtype})


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
