"""Imperative layer classes (parity: reference imperative/nn.py — Conv2D,
Pool2D, FC, BatchNorm; Embedding added as the natural fifth).

Each instance pins its parameter names, so repeated forward calls reuse the
same (already-initialized) Parameters — the eager analogue of the reference's
`_build_once` parameter caching.
"""
import copy

from ..param_attr import ParamAttr
from . import layers as imp_layers

__all__ = ['Conv2D', 'Pool2D', 'FC', 'BatchNorm', 'Embedding']


def _pin(attr, name):
    """Give an (optional) ParamAttr a stable name so the parameter is reused
    across forward calls."""
    if attr is False:
        return False
    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return False
    attr = copy.deepcopy(attr)
    if attr.name is None:
        attr.name = name
    return attr


class _FluidLayer(imp_layers.Layer):
    """Base for imperative layers implemented by calling paddle_tpu.layers.*
    in forward (ops execute eagerly under imperative.guard)."""

    def _track_params(self):
        # parameters land in the program's root block under pinned names
        from ..core.framework import Parameter, default_main_program
        root = default_main_program().global_block()
        prefix = self._full_name + '.'
        for name, v in root.vars.items():
            if isinstance(v, Parameter) and name.startswith(prefix):
                self._parameters.setdefault(name, v)


class Conv2D(_FluidLayer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, use_cudnn=True,
                 act=None, param_attr=None, bias_attr=None, name=None,
                 dtype='float32'):
        super(Conv2D, self).__init__(name_scope=name or 'conv2d', dtype=dtype)
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._act = act
        self._param_attr = _pin(param_attr, self._full_name + '.w_0')
        self._bias_attr = _pin(bias_attr, self._full_name + '.b_0')

    def forward(self, input):
        from .. import layers
        out = layers.conv2d(
            input, self._num_filters, self._filter_size, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            groups=self._groups, param_attr=self._param_attr,
            bias_attr=self._bias_attr, act=self._act)
        self._track_params()
        return out


class Pool2D(imp_layers.Layer):
    def __init__(self, pool_size=-1, pool_type='max', pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, name=None,
                 dtype='float32'):
        super(Pool2D, self).__init__(name_scope=name or 'pool2d', dtype=dtype)
        self._pool_size = pool_size
        self._pool_type = pool_type
        self._pool_stride = pool_stride
        self._pool_padding = pool_padding
        self._global_pooling = global_pooling
        self._ceil_mode = ceil_mode
        self._exclusive = exclusive

    def forward(self, input):
        from .. import layers
        return layers.pool2d(
            input, pool_size=self._pool_size, pool_type=self._pool_type,
            pool_stride=self._pool_stride, pool_padding=self._pool_padding,
            global_pooling=self._global_pooling, ceil_mode=self._ceil_mode,
            exclusive=self._exclusive)


class FC(_FluidLayer):
    def __init__(self, size, param_attr=None, bias_attr=None,
                 num_flatten_dims=1, dtype='float32', act=None, name=None):
        super(FC, self).__init__(name_scope=name or 'fc', dtype=dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._act = act
        self._param_attr = _pin(param_attr, self._full_name + '.w_0')
        self._bias_attr = _pin(bias_attr, self._full_name + '.b_0')

    def forward(self, input):
        from .. import layers
        out = layers.fc(input, self._size,
                        num_flatten_dims=self._num_flatten_dims,
                        param_attr=self._param_attr,
                        bias_attr=self._bias_attr, act=self._act)
        self._track_params()
        return out


class BatchNorm(_FluidLayer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype='float32', data_layout='NCHW', in_place=False,
                 name=None, moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=False, fuse_with_relu=False,
                 use_global_stats=False):
        super(BatchNorm, self).__init__(name_scope=name or 'batch_norm',
                                        dtype=dtype)
        self._act = act
        self._is_test = is_test
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_layout = data_layout
        self._use_global_stats = use_global_stats
        self._param_attr = _pin(param_attr, self._full_name + '.w_0')
        self._bias_attr = _pin(bias_attr, self._full_name + '.b_0')
        self._moving_mean_name = (moving_mean_name or
                                  self._full_name + '.mean')
        self._moving_variance_name = (moving_variance_name or
                                      self._full_name + '.var')

    def forward(self, input):
        from .. import layers
        out = layers.batch_norm(
            input, act=self._act, is_test=self._is_test,
            momentum=self._momentum, epsilon=self._epsilon,
            param_attr=self._param_attr, bias_attr=self._bias_attr,
            data_layout=self._data_layout,
            moving_mean_name=self._moving_mean_name,
            moving_variance_name=self._moving_variance_name,
            use_global_stats=self._use_global_stats)
        self._track_params()
        return out


class Embedding(_FluidLayer):
    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype='float32',
                 name=None):
        super(Embedding, self).__init__(name_scope=name or 'embedding',
                                        dtype=dtype)
        self._size = size
        self._is_sparse = is_sparse
        self._padding_idx = padding_idx
        self._param_attr = _pin(param_attr, self._full_name + '.w_0')
        self._dtype = dtype

    def forward(self, input):
        from .. import layers
        out = layers.embedding(
            input, self._size, is_sparse=self._is_sparse,
            padding_idx=self._padding_idx, param_attr=self._param_attr,
            dtype=self._dtype)
        self._track_params()
        return out
