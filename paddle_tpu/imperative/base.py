"""Imperative (dygraph) mode — eager op-by-op execution with tape autograd.

Parity: reference python/paddle/fluid/imperative/base.py (enabled, guard,
to_variable) + the C++ imperative tracer (paddle/fluid/imperative/tracer.cc).

TPU-native design: instead of a C++ tracer that records per-op grad-op nodes,
eager mode executes each appended op's JAX impl immediately (JAX dispatches
eagerly outside jit) and records the op on a flat tape.  `var.backward()`
replays the tape as a pure function of the leaf variables (Parameters and
`to_variable` inputs) under `jax.vjp`, so gradients come from XLA-native AD —
the exact same impls used by the graph executor, no hand-written grad kernels.
"""
import contextlib

import numpy as np

from ..core import framework
from ..core import registry
from ..core import unique_name
from ..core.framework import Parameter, Variable

__all__ = ['enabled', 'guard', 'to_variable', 'no_record']

_CONTROL_FLOW = {'while', 'conditional_block'}


class _OpEntry(object):
    """One executed op on the tape: (op, stable rng index)."""

    __slots__ = ('op', 'idx')

    def __init__(self, op, idx):
        self.op = op
        self.idx = idx

    @property
    def in_names(self):
        return self.op.input_names()

    @property
    def out_names(self):
        return self.op.output_names()

    def lookup(self, name):
        return self.op.block._find_var_recursive(name)

    def run(self, env, ctx_factory):
        import jax.lax as lax
        import jax.numpy as jnp
        op = self.op
        impl = registry.get_op(op.type).impl
        ins = {}
        for slot, names in op.inputs.items():
            vals = [env[n] for n in names]
            ins[slot] = vals if op.input_is_list[slot] else vals[0]
        outs = impl(ctx_factory(self.idx, op), ins, op.attrs) or {}
        for slot, names in op.outputs.items():
            if slot not in outs:
                continue
            vals = outs[slot]
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for name, val in zip(names, vals):
                if val is None:
                    continue
                var = self.lookup(name)
                if var is not None and var.stop_gradient and hasattr(
                        val, 'dtype') and jnp.issubdtype(
                            val.dtype, jnp.floating):
                    val = lax.stop_gradient(val)
                env[name] = val


class _PyLayerEntry(object):
    """A PyLayer call on the tape: host-side numpy forward/backward, lowered
    with jax.pure_callback + jax.custom_vjp at replay time."""

    __slots__ = ('cls', 'in_names', 'out_names', 'out_specs', 'block')

    def __init__(self, cls, in_names, out_names, out_specs, block):
        self.cls = cls
        self.in_names = in_names
        self.out_names = out_names
        self.out_specs = out_specs  # list of ShapeDtypeStruct
        self.block = block

    def lookup(self, name):
        return self.block._find_var_recursive(name)

    def run(self, env, ctx_factory):
        import jax
        cls = self.cls
        specs = self.out_specs

        @jax.custom_vjp
        def f(*xs):
            return jax.pure_callback(
                lambda *a: _as_tuple(cls.forward([np.asarray(x) for x in a]),
                                     len(specs)),
                tuple(specs), *xs)

        def fwd(*xs):
            ys = f(*xs)
            return ys, (xs, ys)

        def bwd(res, cts):
            xs, ys = res
            if len(xs) != 1 or len(specs) != 1:
                raise NotImplementedError(
                    'PyLayer backward supports one input/one output '
                    '(parity with the reference v1.3 PyLayer)')
            in_spec = jax.ShapeDtypeStruct(np.shape(xs[0]), xs[0].dtype)
            gx = jax.pure_callback(
                lambda x, y, ct: np.asarray(
                    cls.backward([np.asarray(x), np.asarray(y),
                                  np.asarray(ct)]),
                    dtype=in_spec.dtype).reshape(in_spec.shape),
                in_spec, xs[0], ys[0], cts[0])
            return (gx,)

        f.defvjp(fwd, bwd)
        ys = f(*[env[n] for n in self.in_names])
        for name, val in zip(self.out_names, ys):
            env[name] = val


def _as_tuple(x, n):
    if isinstance(x, (list, tuple)):
        return tuple(np.asarray(v) for v in x)
    assert n == 1
    return (np.asarray(x),)


class _ImperativeState(object):
    def __init__(self, main_prog, startup_prog, seed):
        import jax
        self.main_prog = main_prog
        self.startup_prog = startup_prog
        self.base_key = jax.random.key(seed)
        self.tape = []
        self.op_counter = 0
        self.no_record_depth = 0

    # ---- rng context for one eager/replayed op (mirrors registry.OpCtx)
    def ctx(self, idx, op):
        return _EagerOpCtx(self, idx, op)

    def next_index(self):
        i = self.op_counter
        self.op_counter += 1
        return i


class _EagerOpCtx(object):
    is_infer = False

    def __init__(self, state, op_index, op):
        self._state = state
        self.op_index = op_index
        self.op = op

    def rng(self, n=0):
        import jax
        return jax.random.fold_in(self._state.base_key,
                                  self.op_index * 1009 + n)


def _state():
    return framework._imperative[0]


def enabled():
    return _state() is not None


@contextlib.contextmanager
def guard(place=None, seed=0):
    """Enable imperative mode (parity: reference imperative/base.py guard).
    Fresh main/startup programs scope the eagerly-built graph."""
    prog = framework.Program()
    startup = framework.Program()
    with framework.program_guard(prog, startup):
        with unique_name.guard():
            st = _ImperativeState(prog, startup, seed)
            framework._imperative[0] = st
            try:
                yield
            finally:
                framework._imperative[0] = None


@contextlib.contextmanager
def no_record():
    """Execute eagerly but keep ops off the tape (used for optimizer updates,
    which must not be differentiated through on the next backward)."""
    st = _state()
    if st is None:
        yield
        return
    st.no_record_depth += 1
    try:
        yield
    finally:
        st.no_record_depth -= 1


def to_variable(value, block=None):
    """Wrap a numpy array as an eager Variable (autograd leaf)."""
    import jax.numpy as jnp
    st = _state()
    if st is None:
        raise RuntimeError('to_variable must be called under '
                           'imperative.guard()')
    if isinstance(value, Variable):
        return value
    arr = jnp.asarray(value)
    if block is None:
        block = st.main_prog.global_block()
    var = block.create_var(
        name=unique_name.generate('tmp_ivar'),
        shape=tuple(arr.shape), dtype=str(arr.dtype))
    var._ivalue = arr
    var._eager_leaf = True
    var.stop_gradient = not jnp.issubdtype(arr.dtype, jnp.floating)
    return var


# ------------------------------------------------------------------ exec


def eager_run_op(op):
    """Execute a just-appended op immediately; called from Block.append_op."""
    st = _state()
    if op.type in _CONTROL_FLOW:
        raise NotImplementedError(
            'op %s: graph control flow is not supported in imperative mode; '
            'use Python control flow directly' % op.type)
    impl = registry.get_op(op.type).impl
    ins = {}
    env = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            v = op.block._find_var_recursive(n)
            if v is None or getattr(v, '_ivalue', None) is None:
                raise ValueError(
                    'imperative: input var %s of op %s has no value '
                    '(was it fed via to_variable or produced eagerly?)'
                    % (n, op.type))
            env[n] = v._ivalue
            vals.append(v._ivalue)
        ins[slot] = vals if op.input_is_list[slot] else vals[0]
    idx = st.next_index()
    try:
        outs = impl(st.ctx(idx, op), ins, op.attrs) or {}
    except Exception:
        _drop_op(op)
        raise
    for slot, names in op.outputs.items():
        if slot not in outs:
            continue
        vals = outs[slot]
        vals = vals if isinstance(vals, (list, tuple)) else [vals]
        for name, val in zip(names, vals):
            if val is None:
                continue
            var = op.block._find_var_recursive(name)
            if var is None:
                continue
            var._ivalue = val
            var.shape = tuple(int(d) for d in val.shape)
            # mirror param init values onto the real Parameter (initializers
            # write to a same-named mirror var in the startup program)
            if op.block.program is st.startup_prog:
                real = st.main_prog.global_block()._find_var_recursive(name)
                if real is not None:
                    real._ivalue = val
                    real.shape = tuple(int(d) for d in val.shape)
    if _should_record(st, op):
        st.tape.append(_OpEntry(op, idx))
    elif op.block.program is st.main_prog:
        # unrecorded main-program ops (optimizer updates under no_record,
        # persistable-only writers) would otherwise pile up one per step
        _drop_op(op)


def _drop_op(op):
    ops = op.block.ops
    if ops and ops[-1] is op:
        ops.pop()
    else:  # pragma: no cover - defensive; append_op always puts it last
        try:
            ops.remove(op)
        except ValueError:
            pass


def _should_record(st, op):
    if st.no_record_depth > 0:
        return False
    if op.block.program is not st.main_prog:
        return False  # startup init ops are not part of the autograd graph
    if op.attrs.get('op_role') == framework.OpRole.Optimize:
        return False
    outs = [op.block._find_var_recursive(n) for n in op.output_names()]
    outs = [v for v in outs if v is not None]
    if outs and all(v.persistable for v in outs):
        return False  # writes only persistable state (lr vars, counters)
    return True


def record_pylayer(cls, in_vars, out_vars):
    import jax
    st = _state()
    if st is None or st.no_record_depth > 0:
        return
    specs = [jax.ShapeDtypeStruct(tuple(v._ivalue.shape), v._ivalue.dtype)
             for v in out_vars]
    st.tape.append(_PyLayerEntry(
        cls, [v.name for v in in_vars], [v.name for v in out_vars], specs,
        st.main_prog.global_block()))


# -------------------------------------------------------------- backward


def _is_leaf(v):
    if v.stop_gradient:
        return False
    if isinstance(v, Parameter):
        return v.trainable
    return getattr(v, '_eager_leaf', False)


def eager_backward(target):
    """Compute d(target)/d(leaves) by replaying the tape under jax.vjp.
    Gradients are stored on each leaf's `_grad_value` (fresh, not
    accumulated — v1.3 semantics).  Clears the tape afterwards."""
    import jax
    import jax.numpy as jnp

    st = _state()
    if st is None:
        raise RuntimeError('backward() outside imperative.guard()')
    entries = st.tape
    # classify inputs in tape order: a name read before any tape op wrote it
    # is an external input (leaf or constant) — even if a later/same op also
    # writes it (in-place persistable state like batch_norm moving stats)
    leaves, consts, leaf_vars = {}, {}, {}
    produced = set()
    for e in entries:
        for n in e.in_names:
            if n in produced or n in leaves or n in consts:
                continue
            v = e.lookup(n)
            val = None if v is None else getattr(v, '_ivalue', None)
            if val is None:
                raise ValueError('imperative backward: missing value for %s'
                                 % n)
            if _is_leaf(v):
                leaves[n] = val
                leaf_vars[n] = v
            else:
                consts[n] = val
        produced.update(e.out_names)

    tname = target.name
    if tname not in produced:
        if _is_leaf(target):  # d target / d target == 1
            target._grad_value = jnp.ones_like(target._ivalue)
        _clear_tape(st, leaves, consts)
        return {target.name: target} if _is_leaf(target) else {}

    def fw(leaf_vals):
        env = dict(consts)
        env.update(leaf_vals)
        for e in entries:
            e.run(env, st.ctx)
        return env[tname]

    out, pullback = jax.vjp(fw, leaves)
    grads, = pullback(jnp.ones_like(out))
    written = {}
    for n, g in grads.items():
        leaf_vars[n]._grad_value = g
        written[n] = leaf_vars[n]
    _clear_tape(st, leaves, consts)
    return written


def _clear_tape(st, ext_leaves=(), ext_consts=()):
    """Drop the tape and prune its temporaries from the block — including
    consumed `to_variable` leaves (each pins a batch-sized device array) —
    so memory stays bounded across training iterations."""
    dead_ops = set()
    dead_vars = set()
    for e in st.tape:
        if isinstance(e, _OpEntry):
            dead_ops.add(id(e.op))
        for n in e.out_names:
            v = e.lookup(n)
            if v is not None and not v.persistable and \
                    not isinstance(v, Parameter):
                dead_vars.add(n)
    blk = st.main_prog.global_block()
    for n in list(ext_leaves) + list(ext_consts):
        v = blk.vars.get(n)
        if v is not None and getattr(v, '_eager_leaf', False):
            dead_vars.add(n)
    if dead_ops:
        blk.ops = [op for op in blk.ops if id(op) not in dead_ops]
    for n in dead_vars:
        blk.vars.pop(n, None)
    st.tape = []


def eager_params_grads(loss, parameter_list=None, no_grad_set=None):
    """Optimizer.backward() in imperative mode: run tape backward, then
    materialize `<param>@GRAD` vars holding the grad values so the optimizer
    update ops can consume them eagerly.  Only gradients computed by THIS
    backward are used — a parameter absent from the current loss keeps its
    old _grad_value for inspection but is not re-updated with it."""
    st = _state()
    fresh = eager_backward(loss)
    root = st.main_prog.global_block()
    no_grad = set(no_grad_set or ())
    if parameter_list:
        params = [root.var(p) if isinstance(p, str) else p
                  for p in parameter_list]
    else:
        params = [v for v in root.vars.values() if isinstance(v, Parameter)]
    out = []
    for p in sorted(params, key=lambda v: v.name):
        if p.name in no_grad or not p.trainable or p.name not in fresh:
            continue
        g = p._grad_value
        if g is None:
            continue
        gvar = root.create_var(name=p.name + '@GRAD', shape=tuple(p.shape),
                               dtype=p.dtype, stop_gradient=True)
        gvar._ivalue = g
        out.append((p, gvar))
    return out
