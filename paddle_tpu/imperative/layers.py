"""Layer / PyLayer base classes for imperative mode.

Parity: reference python/paddle/fluid/imperative/layers.py (Layer with
_build_once lazy build, PyLayer with numpy forward/backward).
"""
import collections

import numpy as np

from ..core import unique_name
from ..core.framework import Parameter, Variable
from . import base

__all__ = ['Layer', 'PyLayer']


class Layer(object):
    """Composable eager module.  Subclasses implement `forward`; parameters
    created through sub-layers are discovered via attribute registration."""

    def __init__(self, name_scope=None, dtype='float32'):
        self._full_name = unique_name.generate(
            name_scope if name_scope else
            self.__class__.__name__.lower())
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._once_built = False

    def full_name(self):
        return self._full_name

    # ------------------------------------------------------------ params
    def parameters(self, include_sublayers=True):
        ret = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                ret.extend(l.parameters(include_sublayers=True))
        return ret

    def sublayers(self, include_sublayers=True):
        ret = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                ret.extend(l.sublayers(include_sublayers=True))
        return ret

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def state_dict(self, include_sublayers=True):
        d = collections.OrderedDict()
        for p in self.parameters(include_sublayers):
            d[p.name] = p.numpy()
        return d

    def set_dict(self, state, include_sublayers=True):
        import jax.numpy as jnp
        for p in self.parameters(include_sublayers):
            if p.name in state:
                p._ivalue = jnp.asarray(state[p.name])

    def train(self):
        self._is_test = False
        for l in self._sub_layers.values():
            l.train()

    def eval(self):
        self._is_test = True
        for l in self._sub_layers.values():
            l.eval()

    # ------------------------------------------------------------- call
    def _build_once(self, *args, **kwargs):
        pass

    def __call__(self, *inputs, **kwargs):
        if not self._once_built:
            self._build_once(*inputs, **kwargs)
            self._once_built = True
        return self.forward(*inputs, **kwargs)

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __setattr__(self, name, value):
        params = self.__dict__.get('_parameters')
        layers = self.__dict__.get('_sub_layers')
        if isinstance(value, Parameter) and params is not None:
            params[name] = value
        elif isinstance(value, Layer) and layers is not None:
            layers[name] = value
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only hit for names missing from __dict__
        params = self.__dict__.get('_parameters')
        if params and name in params:
            return params[name]
        layers = self.__dict__.get('_sub_layers')
        if layers and name in layers:
            return layers[name]
        raise AttributeError(name)


class PyLayer(object):
    """Custom host-side op: numpy `forward(inputs)` and
    `backward([inp, out, d_out])` static methods (parity: reference
    imperative/layers.py PyLayer).  Lowered through jax.pure_callback with a
    custom VJP on tape replay."""

    def __init__(self):
        pass

    @staticmethod
    def forward(inputs):
        raise NotImplementedError

    @staticmethod
    def backward(douts):
        raise NotImplementedError

    @classmethod
    def __call__(cls, *args, **kwargs):
        raise RuntimeError('call a PyLayer instance, not the class')

    def __call__(self, *inputs):
        import jax.numpy as jnp
        st = base._state()
        if st is None:
            raise RuntimeError('PyLayer must run under imperative.guard()')
        in_vars = [base.to_variable(v) if not isinstance(v, Variable) else v
                   for v in inputs]
        ins_np = [np.asarray(v._ivalue) for v in in_vars]
        outs = type(self).forward(ins_np)
        outs = outs if isinstance(outs, (list, tuple)) else (outs,)
        block = st.main_prog.global_block()
        out_vars = []
        for o in outs:
            arr = jnp.asarray(o)
            var = block.create_var(
                name=unique_name.generate('pylayer_out'),
                shape=tuple(arr.shape), dtype=str(arr.dtype))
            var._ivalue = arr
            out_vars.append(var)
        base.record_pylayer(type(self), in_vars, out_vars)
        return out_vars
