"""paddle_tpu.imperative — dygraph/eager mode.

Parity: reference python/paddle/fluid/imperative/__init__.py.
"""
from . import base
from .base import enabled, guard, to_variable, no_record  # noqa: F401
from . import layers
from .layers import Layer, PyLayer  # noqa: F401
from . import nn
from .nn import Conv2D, Pool2D, FC, BatchNorm, Embedding  # noqa: F401

__all__ = []
__all__ += base.__all__
__all__ += layers.__all__
__all__ += nn.__all__
