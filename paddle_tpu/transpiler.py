"""Transpilers (parity: python/paddle/fluid/transpiler/).

DistributeTranspiler keeps the reference API (transpile with trainer_id /
pservers / trainers, modes) but lowers to the TPU-collective world: trainers
are SPMD processes over a jax mesh (jax.distributed), gradients all-reduce
over ICI/DCN via GSPMD — there are no parameter servers.  `pserver` mode is
accepted and mapped to collective mode with a warning (the legacy go/
pserver in the reference is obsolete on TPU).
"""
import warnings

from .core.framework import default_main_program

__all__ = ['DistributeTranspiler', 'DistributeTranspilerConfig',
           'memory_optimize', 'release_memory', 'InferenceTranspiler',
           'HashName', 'RoundRobin']


class DistributeTranspilerConfig(object):
    slice_var_up = True
    split_method = None
    min_block_size = 8192
    sync_mode = True
    mode = 'tpu_collective'


class HashName(object):
    def __init__(self, pserver_endpoints):
        self._eps = pserver_endpoints


RoundRobin = HashName


class DistributeTranspiler(object):
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_id = 0
        self._trainers = 1
        self._program = None

    def transpile(self, trainer_id, program=None, pservers='', trainers=1,
                  sync_mode=True, startup_program=None,
                  current_endpoint=''):
        """Annotate `program` for SPMD data-parallel execution.

        trainers may be an int (process count) or a comma/`\\n`-separated
        endpoint list (NCCL2-mode convention in the reference)."""
        program = program or default_main_program()
        if isinstance(trainers, str):
            eps = trainers.replace('\n', ',').split(',')
            trainers = len([e for e in eps if e])
        self._trainer_id = trainer_id
        self._trainers = trainers
        self._program = program
        if pservers:
            warnings.warn(
                'pserver mode is obsolete on TPU; mapping to tpu_collective '
                '(SPMD + GSPMD all-reduce over ICI).')
        # mark every data var as batch-sharded over the 'data' mesh axis
        from jax.sharding import PartitionSpec as P
        for v in program.global_block().vars.values():
            if v.is_data and v.name not in program._sharding:
                program.set_sharding(v.name, P('data'))
        program._dist_info = {'trainer_id': trainer_id,
                              'num_trainers': trainers,
                              'mode': self.config.mode}
        return program

    def get_trainer_program(self, wait_port=True):
        return self._program

    def get_pserver_program(self, endpoint):
        raise RuntimeError(
            'no parameter servers on TPU: all trainers are SPMD peers. '
            'Launch the same trainer program on every host '
            '(jax.distributed.initialize).')

    get_pserver_programs = get_pserver_program

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        from .core.framework import default_startup_program
        return startup_program or default_startup_program()


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """No-op: XLA's buffer assignment already performs liveness-based reuse
    (the reference rewrites var names to share buffers; see
    memory_optimization_transpiler.py).  Use paddle_tpu.recompute for
    activation rematerialization."""
    return None


def release_memory(input_program, skip_opt_set=None):
    return None


class InferenceTranspiler(object):
    """No-op shim: BN folding / conv+bias fusion are XLA fusions."""

    def transpile(self, program, place, scope=None):
        return program
