"""Transpilers (parity: python/paddle/fluid/transpiler/).

DistributeTranspiler keeps the reference API (transpile with trainer_id /
pservers / trainers, modes) but lowers to the TPU-collective world: trainers
are SPMD processes over a jax mesh (jax.distributed), gradients all-reduce
over ICI/DCN via GSPMD — there are no parameter servers.  `pserver` mode is
accepted and mapped to collective mode with a warning (the legacy go/
pserver in the reference is obsolete on TPU).
"""
import warnings

from .core.framework import default_main_program

__all__ = ['DistributeTranspiler', 'DistributeTranspilerConfig',
           'memory_optimize', 'release_memory', 'InferenceTranspiler',
           'HashName', 'RoundRobin']


class DistributeTranspilerConfig(object):
    slice_var_up = True
    split_method = None
    min_block_size = 8192
    sync_mode = True
    mode = 'tpu_collective'


class HashName(object):
    def __init__(self, pserver_endpoints):
        self._eps = pserver_endpoints


RoundRobin = HashName


class DistributeTranspiler(object):
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_id = 0
        self._trainers = 1
        self._program = None

    def transpile(self, trainer_id, program=None, pservers='', trainers=1,
                  sync_mode=True, startup_program=None,
                  current_endpoint=''):
        """Annotate `program` for SPMD data-parallel execution.

        trainers may be an int (process count) or a comma/`\\n`-separated
        endpoint list (NCCL2-mode convention in the reference)."""
        program = program or default_main_program()
        if isinstance(trainers, str):
            eps = trainers.replace('\n', ',').split(',')
            trainers = len([e for e in eps if e])
        self._trainer_id = trainer_id
        self._trainers = trainers
        self._program = program
        if pservers:
            warnings.warn(
                'pserver mode is obsolete on TPU; mapping to tpu_collective '
                '(SPMD + GSPMD all-reduce over ICI).')
        # mark every data var as batch-sharded over the 'data' mesh axis
        from jax.sharding import PartitionSpec as P
        for v in program.global_block().vars.values():
            if v.is_data and v.name not in program._sharding:
                program.set_sharding(v.name, P('data'))
        program._dist_info = {'trainer_id': trainer_id,
                              'num_trainers': trainers,
                              'mode': self.config.mode}
        return program

    def get_trainer_program(self, wait_port=True):
        return self._program

    def get_pserver_program(self, endpoint):
        raise RuntimeError(
            'no parameter servers on TPU: all trainers are SPMD peers. '
            'Launch the same trainer program on every host '
            '(jax.distributed.initialize).')

    get_pserver_programs = get_pserver_program

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        from .core.framework import default_startup_program
        return startup_program or default_startup_program()


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """No-op: XLA's buffer assignment already performs liveness-based reuse
    (the reference rewrites var names to share buffers; see
    memory_optimization_transpiler.py).  Use paddle_tpu.recompute for
    activation rematerialization."""
    return None


def release_memory(input_program, skip_opt_set=None):
    return None


class InferenceTranspiler(object):
    """Inference graph optimization.

    Parity: reference transpiler/inference_transpiler.py — its main pass
    folds inference-mode batch_norm into the preceding conv2d's weights
    (`_fuse_batch_norm`).  XLA would fuse the BN *arithmetic* anyway, but
    folding at transpile time deletes the BN ops and their 4 per-channel
    state tensors from the program entirely: fewer buffers, a smaller
    executable, and exact train-time numerics (w' = w·s/√(v+ε),
    b' = (b−μ)·s/√(v+ε) + β)."""

    def transpile(self, program, place=None, scope=None):
        from .core.executor import global_scope
        scope = scope if scope is not None else global_scope()
        # consumer counts are PROGRAM-wide (sub-blocks included): a
        # shared filter, a sub-block reader, or a second branch off the
        # conv output must all veto the fold
        consumers = {}
        for blk in program.blocks:
            for op in blk.ops:
                for names in op.inputs.values():
                    for n in names:
                        consumers[n] = consumers.get(n, 0) + 1
        for block in program.blocks:
            producer = {}
            for op in block.ops:
                for names in op.outputs.values():
                    for n in names:
                        producer[n] = op
            kept = []
            for op in block.ops:
                if op.type == 'batch_norm' and \
                        op.attrs.get('is_test', False):
                    src = op.inputs['X'][0]
                    # match conv2d -> bn, or conv2d -> +bias -> bn (the
                    # fc/conv layers emit the bias as elementwise_add)
                    conv = None
                    tail = None          # op whose output feeds the bn
                    bias_name = None
                    p = producer.get(src)
                    if p is not None and p.type == 'elementwise_add' \
                            and consumers.get(src, 0) == 1:
                        q = producer.get(p.inputs['X'][0])
                        if q is not None and q.type == 'conv2d' and \
                                consumers.get(p.inputs['X'][0], 0) == 1:
                            conv, tail = q, p
                            bias_name = p.inputs['Y'][0]
                    elif p is not None and p.type == 'conv2d' and \
                            consumers.get(src, 0) == 1:
                        conv = tail = p
                        bias_name = conv.inputs.get('Bias', [None])[0]
                    if conv is not None and self._fold(
                            conv, op, scope, bias_name, consumers):
                        # like the reference pass: the fused chain's last
                        # op now WRITES the bn output's name, so fetches
                        # and sub-block readers of it keep working
                        y = op.outputs['Y'][0]
                        out_slot = ('Output' if tail.type == 'conv2d'
                                    else 'Out')
                        tail.outputs[out_slot] = [y]
                        yv = block._find_var_recursive(y)
                        if yv is not None:
                            yv.op = tail
                        continue
                kept.append(op)
            block.ops = kept
        program._bump()
        return program

    @staticmethod
    def _fold(conv, bn, scope, bias_name, consumers):
        import numpy as np
        names = {k: bn.inputs[k][0]
                 for k in ('Scale', 'Bias', 'Mean', 'Variance')}
        wname = conv.inputs['Filter'][0]
        if wname not in scope or any(n not in scope
                                     for n in names.values()):
            return False
        if bias_name is not None and bias_name not in scope:
            return False
        # weight-shared (siamese) convs: folding would scale the shared
        # tensor once per BN — refuse
        if consumers.get(wname, 0) > 1:
            return False
        if bias_name is not None and consumers.get(bias_name, 0) > 1:
            return False
        eps = bn.attrs.get('epsilon', 1e-5)
        s = np.asarray(scope.vars[names['Scale']], np.float64)
        b = np.asarray(scope.vars[names['Bias']], np.float64)
        m = np.asarray(scope.vars[names['Mean']], np.float64)
        v = np.asarray(scope.vars[names['Variance']], np.float64)
        w = np.asarray(scope.vars[wname])
        k = s / np.sqrt(v + eps)                      # [C_out]
        w2 = (w.astype(np.float64) * k[:, None, None, None]).astype(
            w.dtype)
        scope.vars[wname] = scope.vars[wname] * 0 + w2
        if bias_name is not None:
            old = np.asarray(scope.vars[bias_name], np.float64)
            new_b = ((old.reshape(-1) - m) * k + b).astype(w.dtype)
            new_b = new_b.reshape(np.asarray(scope.vars[bias_name]).shape)
            scope.vars[bias_name] = scope.vars[bias_name] * 0 + new_b
        else:
            # conv had no bias: materialize one holding the folded shift
            import jax.numpy as jnp
            blk = conv.block
            bias_name = wname + '.bnfold_bias'
            new_b = ((0.0 - m) * k + b).astype(w.dtype)
            scope.vars[bias_name] = jnp.asarray(new_b)
            blk.create_var(name=bias_name, shape=new_b.shape,
                           dtype=str(new_b.dtype), persistable=True)
            conv.inputs['Bias'] = [bias_name]
            # a slot added post-construction needs its arity recorded
            # (the executor indexes input_is_list at lowering)
            conv.input_is_list['Bias'] = False
        return True
