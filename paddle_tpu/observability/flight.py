"""Black-box flight recorder: a bounded, lock-cheap ring of recent
telemetry, dumped as one JSON artifact when something dies.

The ring is fed two ways: a tap on the trace recorder mirrors every
span/instant (fault injections, breaker transitions, stalls, retraces
and lint events all already flow through tracing), and subsystems can
`record()` explicit structured events (dump triggers, health
transitions).  Appends are bare `deque.append` calls — no lock on the
hot path, bounded by `PT_FLIGHT_EVENTS` (default 4096).

A dump (`dump()` / `maybe_dump()`) writes the ring plus a full metrics
snapshot, retrace reports, and the PT_* environment to
`$PT_FLIGHT_DIR/flight_<pid>_<seq>_<reason>.json` (atomic tmp+rename).
`maybe_dump` is the trigger every crash path calls — it no-ops unless
`PT_FLIGHT_DIR` is set and telemetry is enabled, so unit tests and
library users never get surprise files.  Trigger sites: serving batch
failure, circuit-breaker trip, recovery give-up re-raise, SIGTERM
drain, bench watchdog fire, and the `install()` excepthook for
uncaught crashes in soak tools.
"""
import json
import os
import sys
import threading
import time
from collections import deque

from . import metrics
from . import retrace
from . import tracing

__all__ = ['FlightRecorder', 'flight', 'record', 'dump', 'maybe_dump',
           'flight_dir', 'install', 'install_tap']

_MAX_EVENTS = int(os.environ.get('PT_FLIGHT_EVENTS', '4096'))
_MAX_DUMPS = int(os.environ.get('PT_FLIGHT_MAX_DUMPS', '20'))


def flight_dir():
    """Dump destination, or None (auto-dumps disabled)."""
    return os.environ.get('PT_FLIGHT_DIR') or None


class FlightRecorder(object):
    def __init__(self, max_events=_MAX_EVENTS):
        self._ring = deque(maxlen=max_events)
        self._lock = threading.Lock()   # dump bookkeeping only
        self._dump_seq = 0
        self.last_dump_path = None

    # -- feed --------------------------------------------------------
    def tap(self, event):
        """Trace-recorder tap: mirror an already-built event dict."""
        self._ring.append(event)

    def record(self, kind, **data):
        """Explicit structured event (no-op when telemetry disabled)."""
        if not metrics.enabled():
            return
        ev = {'kind': kind, 't': time.time()}
        if data:
            ev.update(data)
        self._ring.append(ev)

    def events(self):
        return list(self._ring)

    def reset(self):
        self._ring.clear()

    # -- dump --------------------------------------------------------
    def dump(self, reason, path=None, extra=None):
        """Write the postmortem artifact; returns the path (or None if
        the per-process dump budget is exhausted)."""
        with self._lock:
            if self._dump_seq >= _MAX_DUMPS:
                return None
            self._dump_seq += 1
            seq = self._dump_seq
        if path is None:
            d = flight_dir() or '.'
            safe = ''.join(c if c.isalnum() or c in '-_' else '_'
                           for c in str(reason))
            path = os.path.join(d, 'flight_%d_%03d_%s.json'
                                % (os.getpid(), seq, safe))
        artifact = {
            'reason': reason,
            'time_unix': time.time(),
            'pid': os.getpid(),
            'events': self.events(),
            'metrics': metrics.metrics_snapshot(),
            'retrace_reports': list(retrace.explainer().reports),
            'env': {k: v for k, v in os.environ.items()
                    if k.startswith('PT_') or k == 'JAX_PLATFORMS'},
        }
        if extra:
            artifact['extra'] = extra
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump(artifact, f, default=str)
        os.replace(tmp, path)
        self.last_dump_path = path
        metrics.counter('flight.dumps').inc()
        return path

    def maybe_dump(self, reason, extra=None):
        """Auto-dump trigger: only fires when PT_FLIGHT_DIR is set and
        telemetry is on.  Never raises — a postmortem writer that takes
        the process down is worse than no postmortem."""
        if not metrics.enabled() or flight_dir() is None:
            return None
        try:
            return self.dump(reason, extra=extra)
        except Exception:
            return None


_FLIGHT = FlightRecorder()


def flight():
    return _FLIGHT


def record(kind, **data):
    _FLIGHT.record(kind, **data)


def dump(reason, path=None, extra=None):
    return _FLIGHT.dump(reason, path=path, extra=extra)


def maybe_dump(reason, extra=None):
    return _FLIGHT.maybe_dump(reason, extra=extra)


def install_tap():
    """Mirror every trace event into the flight ring (idempotent)."""
    tracing.set_tap(_FLIGHT.tap)


_HOOKED = [False]


def install():
    """Wrap sys.excepthook so an uncaught crash in a tool/soak process
    leaves a flight dump (idempotent; the original hook still runs)."""
    if _HOOKED[0]:
        return
    _HOOKED[0] = True
    prev = sys.excepthook

    def _hook(exc_type, exc, tb):
        _FLIGHT.record('uncaught_exception', exc_type=exc_type.__name__,
                       message=str(exc)[:500])
        _FLIGHT.maybe_dump('crash')
        prev(exc_type, exc, tb)

    sys.excepthook = _hook
