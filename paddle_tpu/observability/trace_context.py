"""Request-level trace context: W3C-traceparent-compatible identifiers
plus an ambient (contextvars) current-context slot.

A `TraceContext` is minted once per logical request (`ServingEngine.
submit`) or per trainer step, carried on the request object through
admission -> queue -> dispatch coalescing -> executor launch -> reply,
and stamped into every span recorded on its behalf (`span_args()`).
Spans recorded by layers that never see the request object (the
executor hot path, the compile pipeline) still join the trace through
the ambient context: `use(ctx)` installs it for the dynamic extent of a
dispatch and `tracing.add_complete` attaches the ids automatically.

Wire format is the W3C trace-context `traceparent` header
(`00-<32 hex trace-id>-<16 hex span-id>-<2 hex flags>`), so an edge
proxy's header can be threaded straight through `from_traceparent`.
"""
import contextlib
import contextvars
import os
import re

__all__ = ['TraceContext', 'current', 'use', 'root_span']

_TRACEPARENT_RE = re.compile(
    r'^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$')

_CURRENT = contextvars.ContextVar('pt_trace_context', default=None)


class TraceContext(object):
    __slots__ = ('trace_id', 'span_id', 'parent_span_id')

    def __init__(self, trace_id, span_id, parent_span_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    @classmethod
    def new(cls):
        return cls(os.urandom(16).hex(), os.urandom(8).hex())

    def child(self):
        """Fresh span id under the same trace, parented to this span."""
        return TraceContext(self.trace_id, os.urandom(8).hex(), self.span_id)

    def to_traceparent(self):
        return '00-%s-%s-01' % (self.trace_id, self.span_id)

    @classmethod
    def from_traceparent(cls, header):
        """Parse a W3C traceparent header; returns None on malformed
        input (callers fall back to minting a fresh context)."""
        if not header:
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m is None or m.group(2) == '0' * 32 or m.group(3) == '0' * 16:
            return None
        return cls(m.group(2), m.group(3))

    def span_args(self, **extra):
        """Dict to merge into a span's `args`."""
        d = {'trace_id': self.trace_id, 'span_id': self.span_id}
        if self.parent_span_id:
            d['parent_span_id'] = self.parent_span_id
        if extra:
            d.update(extra)
        return d

    def __repr__(self):
        return 'TraceContext(%s)' % self.to_traceparent()


def current():
    """The ambient TraceContext for this thread/task, or None."""
    return _CURRENT.get()


@contextlib.contextmanager
def use(ctx):
    """Install `ctx` as the ambient context for the with-block."""
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


@contextlib.contextmanager
def root_span(name, cat='trace', args=None):
    """Mint a fresh trace, install it, and record `name` as its root
    span around the with-block.  No-op (no ids, no span) when telemetry
    is disabled."""
    from . import metrics, tracing
    import time
    if not metrics.enabled():
        yield None
        return
    ctx = TraceContext.new()
    t0 = time.perf_counter()
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)
        tracing.recorder().add_complete(
            name, t0, time.perf_counter(), cat, ctx.span_args(**(args or {})))
