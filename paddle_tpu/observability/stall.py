"""Pipeline-stall detection: launch-gap histogram + drain events.

In steady-state fused training the host's only job between device
launches is handing the next superbatch to `run_steps`; any sizable
host-side gap between the end of one launch call and the start of the
next means the async pipeline drained (slow reader, synchronous fetch,
accidental host round-trip).  The executor reports both edges here; the
gap lands in the `executor.launch_gap_ms` histogram and, above the
threshold (PT_OBS_STALL_MS, default 100 ms), increments
`executor.stall_count` and drops a `pipeline.stall` instant event on the
timeline so the drain is a recorded fact with a timestamp.

Gaps are only meaningful when the runtime is *trying* to go fast.
During a breaker slow-path dispatch (one request at a time, on purpose)
or a recovery rollback/replay window, host-side gaps are the degraded
mode working as designed — `suppress(reason)` marks those windows so
they count `executor.stall_suppressed` instead of polluting the stall
SLO, and `clear_window(executor)` forgets the previous launch-end mark
entirely after a rollback (the replay's first launch has no meaningful
predecessor).
"""
import contextlib
import os
import threading

from . import metrics
from . import tracing

__all__ = ['on_launch_start', 'on_launch_end', 'stall_threshold_ms',
           'set_stall_threshold_ms', 'suppress', 'suppressed',
           'clear_window']

_STALL_MS = [float(os.environ.get('PT_OBS_STALL_MS', '100'))]

# Suppression is a process-global depth counter: the serving dispatch
# thread enters it around degraded-mode dispatches, and the launch-gap
# check (which runs on the same thread, inside the backend call) reads
# it.  A lock keeps enter/exit races from under/overflowing the depth.
_SUPPRESS_LOCK = threading.Lock()
_SUPPRESS = [0]
_SUPPRESS_REASON = [None]


@contextlib.contextmanager
def suppress(reason):
    """Mark the with-block as an intentional slow window: launch gaps
    inside it never count as pipeline stalls."""
    with _SUPPRESS_LOCK:
        _SUPPRESS[0] += 1
        _SUPPRESS_REASON[0] = reason
    try:
        yield
    finally:
        with _SUPPRESS_LOCK:
            _SUPPRESS[0] -= 1
            if _SUPPRESS[0] == 0:
                _SUPPRESS_REASON[0] = None


def suppressed():
    return _SUPPRESS[0] > 0


def clear_window(owner):
    """Forget `owner`'s previous launch-end mark (recovery rollback: the
    replayed first launch must not be measured against the pre-rollback
    timeline)."""
    if getattr(owner, '_obs_prev_launch_end', None) is not None:
        owner._obs_prev_launch_end = None
        metrics.counter('executor.stall_windows_cleared').inc()


def stall_threshold_ms():
    return _STALL_MS[0]


def set_stall_threshold_ms(ms):
    _STALL_MS[0] = float(ms)


def on_launch_start(owner, t_start):
    """Called at the top of a launch with time.perf_counter(); `owner`
    (an Executor) carries the previous launch-end mark."""
    prev_end = getattr(owner, '_obs_prev_launch_end', None)
    if prev_end is None:
        return
    gap_ms = (t_start - prev_end) * 1000.0
    metrics.histogram('executor.launch_gap_ms').observe(gap_ms)
    if gap_ms > _STALL_MS[0]:
        if _SUPPRESS[0]:
            metrics.counter('executor.stall_suppressed').inc()
            tracing.instant('pipeline.stall_suppressed', cat='stall',
                            args={'gap_ms': round(gap_ms, 3),
                                  'reason': _SUPPRESS_REASON[0]})
            return
        metrics.counter('executor.stall_count').inc()
        metrics.counter('executor.stall_s').inc(gap_ms / 1000.0)
        tracing.instant('pipeline.stall', cat='stall',
                        args={'gap_ms': round(gap_ms, 3),
                              'threshold_ms': _STALL_MS[0]})


def on_launch_end(owner, t_end):
    owner._obs_prev_launch_end = t_end
