"""Pipeline-stall detection: launch-gap histogram + drain events.

In steady-state fused training the host's only job between device
launches is handing the next superbatch to `run_steps`; any sizable
host-side gap between the end of one launch call and the start of the
next means the async pipeline drained (slow reader, synchronous fetch,
accidental host round-trip).  The executor reports both edges here; the
gap lands in the `executor.launch_gap_ms` histogram and, above the
threshold (PT_OBS_STALL_MS, default 100 ms), increments
`executor.stall_count` and drops a `pipeline.stall` instant event on the
timeline so the drain is a recorded fact with a timestamp.
"""
import os

from . import metrics
from . import tracing

__all__ = ['on_launch_start', 'on_launch_end', 'stall_threshold_ms',
           'set_stall_threshold_ms']

_STALL_MS = [float(os.environ.get('PT_OBS_STALL_MS', '100'))]


def stall_threshold_ms():
    return _STALL_MS[0]


def set_stall_threshold_ms(ms):
    _STALL_MS[0] = float(ms)


def on_launch_start(owner, t_start):
    """Called at the top of a launch with time.perf_counter(); `owner`
    (an Executor) carries the previous launch-end mark."""
    prev_end = getattr(owner, '_obs_prev_launch_end', None)
    if prev_end is None:
        return
    gap_ms = (t_start - prev_end) * 1000.0
    metrics.histogram('executor.launch_gap_ms').observe(gap_ms)
    if gap_ms > _STALL_MS[0]:
        metrics.counter('executor.stall_count').inc()
        metrics.counter('executor.stall_s').inc(gap_ms / 1000.0)
        tracing.instant('pipeline.stall', cat='stall',
                        args={'gap_ms': round(gap_ms, 3),
                              'threshold_ms': _STALL_MS[0]})


def on_launch_end(owner, t_end):
    owner._obs_prev_launch_end = t_end
