"""Performance-lab record plumbing: schema-validated scenario records,
the mandatory provenance block, the append-only ledger, and the
baseline comparison math.

The lab exists because perf numbers without provenance are unreliable
evidence: 2 of the first 5 bench rounds (BENCH_r02, r05) silently
recorded CPU-fallback numbers after a PJRT-init hang, and nothing in
the JSON made them distinguishable from real TPU rounds.  Every record
written through this module carries the backend it ACTUALLY ran on,
the device kind, jax/jaxlib versions, the git sha, and the fallback
reason (or null) — and ``compare_records`` refuses to diff a
cpu-fallback candidate against a TPU baseline instead of passing it.

Metric classes (declared per scenario in ``export.SCHEMA`` under the
``perflab.<scenario>`` sections — see that table for the spec
vocabulary):

  * deterministic counters — exact integers, zero tolerance: any move
    in the worse direction is a regression.  CI-enforceable on CPU.
  * timing metrics — best-of-K floats with the raw samples recorded in
    the ``spread`` block; compared only on a matching device kind,
    within a relative threshold widened by the observed spread.
  * info — descriptive context, never compared.

Consumers: ``tools/perflab.py`` (the scenario matrix CLI), and the
``maybe_ledger`` writer that bench.py / serve_soak.py / pod_soak.py
call so their telemetry lands in the same ``PERF_HISTORY.jsonl``.
"""
import json
import os
import subprocess
import sys
import time

from .export import SCHEMA

__all__ = ['RECORD_SCHEMA', 'BASELINE_SCHEMA', 'PROVENANCE_KEYS',
           'DEFAULT_TIMING_TOLERANCE', 'scenario_names', 'metric_specs',
           'git_sha', 'provenance', 'build_record', 'error_record',
           'validate_record', 'append_record', 'read_ledger',
           'latest_per_scenario', 'maybe_ledger', 'compare_records',
           'compare_ledger', 'bless']

RECORD_SCHEMA = 'perflab/1'
BASELINE_SCHEMA = 'perflab-baseline/1'
# timing thresholds are deliberately loose by default: smoke-geometry
# CPU timings in CI containers are noisy, and the zero-tolerance gate
# is the counters'.  Baselines carry per-metric overrides for the
# metrics a PR is actually expected to hold (TPU tokens/s, MFU).
DEFAULT_TIMING_TOLERANCE = 0.5

PROVENANCE_KEYS = ('backend', 'device_kind', 'platform', 'jax', 'jaxlib',
                   'git_sha', 'python', 'fallback')


def scenario_names():
    """Every scenario with a declared record section."""
    return sorted(k[len('perflab.'):] for k in SCHEMA
                  if k.startswith('perflab.'))


def metric_specs(scenario):
    """{metric: spec} for one scenario's record section."""
    key = 'perflab.%s' % scenario
    if key not in SCHEMA:
        raise KeyError('perflab: no SCHEMA section %r (known scenarios: %s)'
                       % (key, ', '.join(scenario_names())))
    return dict(SCHEMA[key])


def git_sha():
    """HEAD sha of the repo this module lives in; PT_GIT_SHA overrides
    (detached CI checkouts), 'unknown' when neither resolves."""
    env = os.environ.get('PT_GIT_SHA')
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        r = subprocess.run(['git', 'rev-parse', 'HEAD'], cwd=root,
                           capture_output=True, text=True, timeout=10)
        sha = r.stdout.strip()
        if r.returncode == 0 and sha:
            return sha
    except Exception:
        pass
    return 'unknown'


def provenance(fallback=None):
    """The mandatory provenance block: the backend the calling process
    ACTUALLY initialized (not what a probe subprocess saw), jax/jaxlib
    versions, git sha, and the fallback reason (or None when the
    backend is the one that was asked for)."""
    import jax
    dev0 = jax.devices()[0]
    try:
        import jaxlib
        jaxlib_ver = getattr(jaxlib, '__version__', 'unknown')
    except Exception:
        jaxlib_ver = 'unknown'
    return {
        'backend': 'cpu-fallback' if fallback else dev0.platform,
        'platform': dev0.platform,
        'device_kind': str(dev0.device_kind),
        'jax': jax.__version__,
        'jaxlib': jaxlib_ver,
        'git_sha': git_sha(),
        'python': '%d.%d.%d' % sys.version_info[:3],
        'fallback': fallback,
    }


def build_record(scenario, metrics, spread=None, config=None,
                 prov=None, fallback=None, ts=None):
    """Assemble + validate one ledger record.  ``spread`` maps timing
    metrics to their raw best-of-K samples; ``config`` is the geometry
    the scenario ran at (compared records must match it exactly)."""
    rec = {
        'schema': RECORD_SCHEMA,
        'scenario': scenario,
        'ts': round(time.time() if ts is None else ts, 3),
        'provenance': prov if prov is not None else provenance(fallback),
        'config': dict(config or {}),
        'metrics': dict(metrics),
        'spread': {k: list(v) for k, v in (spread or {}).items()},
    }
    validate_record(rec)
    return rec


def error_record(scenario, kind, stage=None, detail=None, prov=None,
                 ts=None):
    """A structured failure record: the scenario died (timeout, crash,
    schema violation) but the round keeps its ledger row."""
    return {
        'schema': RECORD_SCHEMA,
        'scenario': scenario,
        'ts': round(time.time() if ts is None else ts, 3),
        'provenance': prov,
        'error': kind,
        'stage': stage,
        'detail': str(detail)[:2000] if detail is not None else None,
    }


def _fail(scenario, msg):
    raise ValueError('perflab record (%s): %s' % (scenario, msg))


def validate_record(rec):
    """Validate one record against its scenario's SCHEMA section and the
    provenance contract.  Raises ValueError; returns the record."""
    if not isinstance(rec, dict):
        raise ValueError('perflab record: not a dict: %r' % type(rec))
    scenario = rec.get('scenario')
    if not scenario:
        raise ValueError('perflab record: missing "scenario"')
    if rec.get('schema') != RECORD_SCHEMA:
        _fail(scenario, 'schema %r != %r' % (rec.get('schema'),
                                             RECORD_SCHEMA))
    if not isinstance(rec.get('ts'), (int, float)):
        _fail(scenario, 'missing/non-numeric "ts"')
    if 'error' in rec:
        # failure records skip metric validation but keep the shape:
        # the {"error", "stage"} contract from tools/_harness.py
        if not rec['error']:
            _fail(scenario, 'empty "error" kind')
        return rec
    prov = rec.get('provenance')
    if not isinstance(prov, dict):
        _fail(scenario, 'missing provenance block')
    for k in PROVENANCE_KEYS:
        if k not in prov:
            _fail(scenario, 'provenance missing %r' % k)
        if k != 'fallback' and prov[k] in (None, ''):
            _fail(scenario, 'provenance[%r] is null' % k)
    specs = metric_specs(scenario)
    metrics = rec.get('metrics')
    if not isinstance(metrics, dict):
        _fail(scenario, 'missing metrics block')
    unknown = set(metrics) - set(specs)
    if unknown:
        _fail(scenario, 'unknown metric keys %s' % sorted(unknown))
    missing = set(specs) - set(metrics)
    if missing:
        _fail(scenario, 'missing metric keys %s' % sorted(missing))
    for key, spec in specs.items():
        v = metrics[key]
        if spec[0] == 'counter':
            if not isinstance(v, int) or isinstance(v, bool):
                _fail(scenario, 'counter %r must be an int, got %r'
                      % (key, v))
        elif spec[0] == 'timing':
            if v is not None and not isinstance(v, (int, float)):
                _fail(scenario, 'timing %r must be a number or null, '
                      'got %r' % (key, v))
    spread = rec.get('spread', {})
    timing_keys = {k for k, s in specs.items() if s[0] == 'timing'}
    bad = set(spread) - timing_keys
    if bad:
        _fail(scenario, 'spread recorded for non-timing keys %s'
              % sorted(bad))
    return rec


# ------------------------------------------------------------- ledger
def append_record(path, rec):
    """Append one validated record to the JSONL ledger (append-only:
    history is never rewritten, a new baseline is a new bless)."""
    validate_record(rec)
    line = json.dumps(rec, sort_keys=True)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, 'a') as f:
        f.write(line + '\n')
        f.flush()
        os.fsync(f.fileno())
    return rec


def read_ledger(path):
    """All parseable records, in append order.  A torn final line (a
    killed writer) is skipped, not fatal — the ledger must always be
    readable."""
    records = []
    if not os.path.exists(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get('scenario'):
                records.append(rec)
    return records


def latest_per_scenario(records):
    """Newest record per scenario (append order breaks ts ties)."""
    latest = {}
    for rec in records:
        latest[rec['scenario']] = rec
    return latest


def maybe_ledger(scenario, metrics, spread=None, config=None,
                 fallback=None, ledger=None):
    """The shared scenario-record writer for the bench/soak tools: if a
    ledger path is given (or PT_PERF_LEDGER is set), build a provenanced
    record and append it.  Never raises — a broken ledger must not kill
    the bench that was asked to feed it."""
    path = ledger or os.environ.get('PT_PERF_LEDGER')
    if not path:
        return None
    try:
        rec = build_record(scenario, metrics, spread=spread,
                           config=config, fallback=fallback)
        return append_record(path, rec)
    except Exception as e:  # noqa: BLE001 - telemetry is best-effort here
        print('perflab: ledger append failed for %r: %s' % (scenario, e),
              file=sys.stderr)
        return None


# ------------------------------------------------------------ compare
def _rel_spread(samples):
    vals = [float(v) for v in (samples or []) if v is not None]
    if len(vals) < 2:
        return 0.0
    lo, hi = min(vals), max(vals)
    denom = max(abs(lo), abs(hi))
    return (hi - lo) / denom if denom else 0.0


def compare_records(base, cand, thresholds=None,
                    default_timing_tolerance=DEFAULT_TIMING_TOLERANCE):
    """Diff one candidate record against its baseline record.

    Returns {'scenario', 'status': 'ok'|'regression'|'refused',
    'regressions': [...], 'improvements': [...], 'skipped': [...],
    'reason': ...}.  Refusals are structural: a comparison that would
    be meaningless (cpu-fallback vs TPU, different platform, different
    geometry) is REFUSED with a reason, never silently passed — the
    BENCH_r02/r05 failure mode is unrepresentable."""
    scenario = cand.get('scenario') or base.get('scenario')
    out = {'scenario': scenario, 'status': 'ok', 'reason': None,
           'regressions': [], 'improvements': [], 'skipped': []}

    def refuse(reason):
        out['status'] = 'refused'
        out['reason'] = reason
        return out

    if 'error' in cand:
        out['status'] = 'regression'
        out['reason'] = 'candidate is a failure record: %s (stage=%s)' % (
            cand.get('error'), cand.get('stage'))
        out['regressions'].append({'metric': '(record)', 'kind': 'error',
                                   'detail': out['reason']})
        return out
    if 'error' in base:
        return refuse('baseline is a failure record: %s'
                      % base.get('error'))
    bp, cp = base.get('provenance') or {}, cand.get('provenance') or {}
    if cp.get('fallback') and bp.get('platform') == 'tpu':
        return refuse(
            'cpu-fallback candidate vs TPU baseline: candidate fell back '
            '(%s) — re-run on TPU or bless a CPU baseline explicitly'
            % cp.get('fallback'))
    if bp.get('platform') != cp.get('platform'):
        return refuse('backend mismatch: baseline platform %r vs '
                      'candidate %r — timings and counters are not '
                      'comparable across backends'
                      % (bp.get('platform'), cp.get('platform')))
    if (base.get('config') or {}) != (cand.get('config') or {}):
        return refuse('config mismatch: baseline %r vs candidate %r — '
                      'different geometry, not a regression signal'
                      % (base.get('config'), cand.get('config')))

    specs = metric_specs(scenario)
    thresholds = thresholds or {}
    same_device = bp.get('device_kind') == cp.get('device_kind')
    for key, spec in sorted(specs.items()):
        kind = spec[0]
        bv = (base.get('metrics') or {}).get(key)
        cv = (cand.get('metrics') or {}).get(key)
        if kind == 'info':
            continue
        if kind == 'counter':
            better = spec[1]
            delta = int(cv) - int(bv)
            worse = delta > 0 if better == 'lower' else delta < 0
            if worse:
                out['regressions'].append({
                    'metric': key, 'kind': 'counter', 'baseline': bv,
                    'candidate': cv,
                    'detail': '%s moved %+d (%s is better, zero '
                              'tolerance)' % (key, delta, better)})
            elif delta:
                out['improvements'].append({
                    'metric': key, 'kind': 'counter', 'baseline': bv,
                    'candidate': cv, 'detail': '%s moved %+d — consider '
                    're-blessing the baseline' % (key, delta)})
            continue
        # timing
        if not same_device:
            out['skipped'].append({'metric': key, 'detail':
                                   'device kind differs (%s vs %s)'
                                   % (bp.get('device_kind'),
                                      cp.get('device_kind'))})
            continue
        if bv is None or cv is None:
            out['skipped'].append({'metric': key, 'detail':
                                   'null on %s side' % (
                                       'both' if bv is None and cv is None
                                       else ('baseline' if bv is None
                                             else 'candidate'))})
            continue
        better = spec[1]
        tol = float(thresholds.get(key, default_timing_tolerance))
        tol_eff = max(tol,
                      _rel_spread((base.get('spread') or {}).get(key)),
                      _rel_spread((cand.get('spread') or {}).get(key)))
        bv, cv = float(bv), float(cv)
        if better == 'higher':
            bad = cv < bv * (1.0 - tol_eff)
            good = cv > bv * (1.0 + tol_eff)
        else:
            bad = cv > bv * (1.0 + tol_eff)
            good = cv < bv * (1.0 - tol_eff)
        entry = {'metric': key, 'kind': 'timing', 'baseline': bv,
                 'candidate': cv, 'tolerance': round(tol_eff, 4),
                 'detail': '%s %.4g -> %.4g (%s is better, tol %.0f%%)'
                           % (key, bv, cv, better, 100 * tol_eff)}
        if bad:
            out['regressions'].append(entry)
        elif good:
            out['improvements'].append(entry)
    if out['regressions']:
        out['status'] = 'regression'
    return out


def compare_ledger(baseline_doc, records, fail_on='regression',
                   scenarios=None):
    """Diff the newest ledger record per scenario against the baseline.

    Returns (rc, reports): rc 0 = clean, 1 = regression (or a scenario
    missing from the ledger), 2 = structured refusal.  ``fail_on=None``
    always returns rc 0 (report-only mode)."""
    if baseline_doc.get('schema') != BASELINE_SCHEMA:
        raise ValueError('perflab baseline: schema %r != %r'
                         % (baseline_doc.get('schema'), BASELINE_SCHEMA))
    latest = latest_per_scenario(records)
    wanted = scenarios or sorted(baseline_doc.get('scenarios', {}))
    default_tol = float(baseline_doc.get(
        'default_timing_tolerance', DEFAULT_TIMING_TOLERANCE))
    all_thresholds = baseline_doc.get('thresholds', {})
    reports = []
    for name in wanted:
        base = baseline_doc['scenarios'].get(name)
        if base is None:
            reports.append({'scenario': name, 'status': 'refused',
                            'reason': 'no baseline record', 'regressions': [],
                            'improvements': [], 'skipped': []})
            continue
        cand = latest.get(name)
        if cand is None:
            reports.append({'scenario': name, 'status': 'missing',
                            'reason': 'no ledger record for scenario',
                            'regressions': [], 'improvements': [],
                            'skipped': []})
            continue
        reports.append(compare_records(
            base, cand, thresholds=all_thresholds.get(name, {}),
            default_timing_tolerance=default_tol))
    rc = 0
    if fail_on:
        if any(r['status'] == 'refused' for r in reports):
            rc = 2
        elif any(r['status'] in ('regression', 'missing')
                 for r in reports):
            rc = 1
    return rc, reports


def bless(records, default_timing_tolerance=DEFAULT_TIMING_TOLERANCE,
          thresholds=None):
    """Build a baseline doc from the newest non-error record per
    scenario (how a new baseline is committed — see docs/perflab.md)."""
    latest = latest_per_scenario(
        [r for r in records if 'error' not in r])
    if not latest:
        raise ValueError('perflab bless: no non-error records to bless')
    for rec in latest.values():
        validate_record(rec)
    return {
        'schema': BASELINE_SCHEMA,
        'blessed_ts': round(time.time(), 3),
        'blessed_git_sha': git_sha(),
        'default_timing_tolerance': default_timing_tolerance,
        'thresholds': dict(thresholds or {}),
        'scenarios': latest,
    }
