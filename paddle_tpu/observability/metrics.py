"""Metrics registry: counters / gauges / histograms, thread-safe,
snapshot-to-dict, with a near-zero-overhead no-op mode.

The registry is process-global (one training process = one telemetry
stream, matching the one-executable-per-step execution model).  Hot
paths guard with `enabled()` ONCE per launch and skip every telemetry
call when off, so disabled mode costs a single branch — individual
metric mutators also check the flag as a second line of defense for
call sites that don't batch their guard.

Histogram buckets are power-of-two (frexp exponent): cheap to compute,
wide dynamic range, good enough to tell a 2 ms launch gap from a 200 ms
pipeline drain.
"""
import math
import os
import threading

__all__ = ['enabled', 'enable', 'disable', 'Counter', 'Gauge', 'Histogram',
           'MetricsRegistry', 'registry', 'counter', 'gauge', 'histogram',
           'metrics_snapshot', 'counters', 'reset']

_ENABLED = [os.environ.get('PT_OBS', '1') not in ('0', 'false', 'False')]


def enabled():
    return _ENABLED[0]


def enable():
    _ENABLED[0] = True


def disable():
    _ENABLED[0] = False


class Counter(object):
    """Monotonic accumulator (float, so it also serves as a seconds sink)."""
    __slots__ = ('name', 'value', '_lock')

    def __init__(self, name):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        if not _ENABLED[0]:
            return
        with self._lock:
            self.value += amount

    def snapshot(self):
        return self.value


class Gauge(object):
    """Last-value metric (queue depth, overlap fraction)."""
    __slots__ = ('name', 'value', 'updates', '_lock')

    def __init__(self, name):
        self.name = name
        self.value = None
        self.updates = 0
        self._lock = threading.Lock()

    def set(self, value):
        if not _ENABLED[0]:
            return
        with self._lock:
            self.value = value
            self.updates += 1

    def snapshot(self):
        return self.value


class Histogram(object):
    """count/sum/min/max plus power-of-two buckets keyed by the frexp
    exponent e (bucket e holds values in (2^(e-1), 2^e])."""
    __slots__ = ('name', 'count', 'total', 'min', 'max', 'buckets', '_lock')

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.buckets = {}
        self._lock = threading.Lock()

    def observe(self, value):
        if not _ENABLED[0]:
            return
        value = float(value)
        e = math.frexp(value)[1] if value > 0.0 else 0
        with self._lock:
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            self.buckets[e] = self.buckets.get(e, 0) + 1

    def snapshot(self):
        with self._lock:
            if not self.count:
                return {'count': 0}
            return {'count': self.count, 'sum': self.total,
                    'min': self.min, 'max': self.max,
                    'mean': self.total / self.count,
                    'buckets': {'le_2^%d' % e: n
                                for e, n in sorted(self.buckets.items())}}


class MetricsRegistry(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get(self, name, cls):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError('metric %r already registered as %s'
                            % (name, type(m).__name__))
        return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def histogram(self, name):
        return self._get(name, Histogram)

    def snapshot(self):
        """Full structured dump: {'counters': {...}, 'gauges': {...},
        'histograms': {...}}."""
        with self._lock:
            items = list(self._metrics.items())
        out = {'counters': {}, 'gauges': {}, 'histograms': {}}
        for name, m in items:
            kind = ('counters' if isinstance(m, Counter) else
                    'gauges' if isinstance(m, Gauge) else 'histograms')
            out[kind][name] = m.snapshot()
        return out

    def counters(self):
        """Flat {name: value} over counters AND gauges (the shape bench.py
        and tests diff against)."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items
                if isinstance(m, (Counter, Gauge))}

    def reset(self):
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def registry():
    return _REGISTRY


def counter(name):
    return _REGISTRY.counter(name)


def gauge(name):
    return _REGISTRY.gauge(name)


def histogram(name):
    return _REGISTRY.histogram(name)


def metrics_snapshot():
    return _REGISTRY.snapshot()


def counters():
    return _REGISTRY.counters()


def reset():
    _REGISTRY.reset()
