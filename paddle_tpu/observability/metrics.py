"""Metrics registry: counters / gauges / histograms, thread-safe,
snapshot-to-dict, with a near-zero-overhead no-op mode.

The registry is process-global (one training process = one telemetry
stream, matching the one-executable-per-step execution model).  Hot
paths guard with `enabled()` ONCE per launch and skip every telemetry
call when off, so disabled mode costs a single branch — individual
metric mutators also check the flag as a second line of defense for
call sites that don't batch their guard.

Histogram buckets are log-spaced (frexp exponent refined by a fixed
linear subdivision of the mantissa): cheap to compute, wide dynamic
range, and *bounded* — the backing store is a dict keyed by bucket
index, so a week-long soak recording millions of observations holds a
few dozen buckets, never a sample list.  Quantiles (`quantile(q)`,
p50/p99 in `snapshot()`) interpolate within the target bucket; with 4
sub-buckets per octave the worst-case relative error is ~12%, plenty to
steer an SLO gate.
"""
import math
import os
import threading

__all__ = ['enabled', 'enable', 'disable', 'Counter', 'Gauge', 'Histogram',
           'MetricsRegistry', 'registry', 'counter', 'gauge', 'histogram',
           'metrics_snapshot', 'counters', 'reset']

_ENABLED = [os.environ.get('PT_OBS', '1') not in ('0', 'false', 'False')]


def enabled():
    return _ENABLED[0]


def enable():
    _ENABLED[0] = True


def disable():
    _ENABLED[0] = False


class Counter(object):
    """Monotonic accumulator (float, so it also serves as a seconds sink)."""
    __slots__ = ('name', 'value', '_lock')

    def __init__(self, name):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        if not _ENABLED[0]:
            return
        with self._lock:
            self.value += amount

    def snapshot(self):
        return self.value


class Gauge(object):
    """Last-value metric (queue depth, overlap fraction)."""
    __slots__ = ('name', 'value', 'updates', '_lock')

    def __init__(self, name):
        self.name = name
        self.value = None
        self.updates = 0
        self._lock = threading.Lock()

    def set(self, value):
        if not _ENABLED[0]:
            return
        with self._lock:
            self.value = value
            self.updates += 1

    def snapshot(self):
        return self.value


_SUBBUCKETS = 4  # linear mantissa subdivisions per power-of-two octave


def _bucket_index(value):
    """Bucket index for a positive value: frexp exponent refined by a
    linear split of the mantissa into _SUBBUCKETS ranges."""
    m, e = math.frexp(value)          # value = m * 2^e, m in [0.5, 1)
    sub = int((m * 2.0 - 1.0) * _SUBBUCKETS)
    if sub >= _SUBBUCKETS:
        sub = _SUBBUCKETS - 1
    return e * _SUBBUCKETS + sub


def _bucket_bounds(idx):
    """(low, high] value range covered by bucket `idx`."""
    e, sub = divmod(idx, _SUBBUCKETS)
    lo = math.ldexp(1.0 + sub / float(_SUBBUCKETS), e - 1)
    hi = math.ldexp(1.0 + (sub + 1) / float(_SUBBUCKETS), e - 1)
    return lo, hi


class Histogram(object):
    """count/sum/min/max plus bounded log-spaced buckets (see module
    docstring).  Non-positive observations land in a dedicated slot so
    they can't alias a real bucket."""
    __slots__ = ('name', 'count', 'total', 'min', 'max', 'buckets',
                 'nonpos', '_lock')

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.buckets = {}
        self.nonpos = 0
        self._lock = threading.Lock()

    def observe(self, value):
        if not _ENABLED[0]:
            return
        value = float(value)
        idx = _bucket_index(value) if value > 0.0 else None
        with self._lock:
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            if idx is None:
                self.nonpos += 1
            else:
                self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def _quantile_locked(self, q):
        if not self.count:
            return None
        target = q * self.count
        run = float(self.nonpos)
        if self.nonpos and run >= target:
            return min(self.min, 0.0)
        for idx in sorted(self.buckets):
            n = self.buckets[idx]
            if run + n >= target:
                lo, hi = _bucket_bounds(idx)
                lo = max(lo, self.min)
                hi = min(hi, self.max)
                frac = (target - run) / n
                return lo + (hi - lo) * frac
            run += n
        return self.max

    def quantile(self, q):
        """Interpolated quantile estimate in [min, max]; None when empty."""
        with self._lock:
            return self._quantile_locked(q)

    def bucket_count(self):
        with self._lock:
            return len(self.buckets) + (1 if self.nonpos else 0)

    def snapshot(self):
        with self._lock:
            if not self.count:
                return {'count': 0}
            out = {'count': self.count, 'sum': self.total,
                   'min': self.min, 'max': self.max,
                   'mean': self.total / self.count,
                   'p50': self._quantile_locked(0.50),
                   'p99': self._quantile_locked(0.99),
                   'buckets': {'le_%g' % _bucket_bounds(idx)[1]: n
                               for idx, n in sorted(self.buckets.items())}}
            if self.nonpos:
                out['buckets']['le_0'] = self.nonpos
            return out

    def cumulative_buckets(self):
        """[(upper_bound, cumulative_count)] ascending — the Prometheus
        `le` rendering shape (observability/export.py)."""
        with self._lock:
            items = sorted(self.buckets.items())
            nonpos = self.nonpos
        out = []
        run = nonpos
        if nonpos:
            out.append((0.0, run))
        for idx, n in items:
            run += n
            out.append((_bucket_bounds(idx)[1], run))
        return out


class MetricsRegistry(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get(self, name, cls):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError('metric %r already registered as %s'
                            % (name, type(m).__name__))
        return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def histogram(self, name):
        return self._get(name, Histogram)

    def snapshot(self):
        """Full structured dump: {'counters': {...}, 'gauges': {...},
        'histograms': {...}}."""
        with self._lock:
            items = list(self._metrics.items())
        out = {'counters': {}, 'gauges': {}, 'histograms': {}}
        for name, m in items:
            kind = ('counters' if isinstance(m, Counter) else
                    'gauges' if isinstance(m, Gauge) else 'histograms')
            out[kind][name] = m.snapshot()
        return out

    def items(self):
        """Sorted [(name, metric_object)] — the export renderer walks
        live objects (cumulative buckets need more than snapshot())."""
        with self._lock:
            return sorted(self._metrics.items())

    def counters(self):
        """Flat {name: value} over counters AND gauges (the shape bench.py
        and tests diff against)."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items
                if isinstance(m, (Counter, Gauge))}

    def reset(self):
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def registry():
    return _REGISTRY


def counter(name):
    return _REGISTRY.counter(name)


def gauge(name):
    return _REGISTRY.gauge(name)


def histogram(name):
    return _REGISTRY.histogram(name)


def metrics_snapshot():
    return _REGISTRY.snapshot()


def counters():
    return _REGISTRY.counters()


def reset():
    _REGISTRY.reset()
