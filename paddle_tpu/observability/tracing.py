"""Span/event recorder emitting Chrome-trace / Perfetto-compatible JSON.

Reference parity: Fluid's profiler writes a chrome-tracing timeline
(`python/paddle/fluid/profiler.py` + tools/timeline.py); here the
recorder is in-process and always-on-cheap — spans are plain dicts in a
bounded deque, exported on demand as a `{"traceEvents": [...]}` file
that loads directly in Perfetto (ui.perfetto.dev) or chrome://tracing.

Timestamps are microseconds relative to a per-process perf_counter
epoch, so `ts` is monotonic and durations are wall-accurate; events are
sorted by `ts` at export time (completion order != start order for
nested spans).
"""
import contextlib
import json
import os
import threading
import time
from collections import deque

from .metrics import enabled
from . import trace_context as _tc

__all__ = ['TraceRecorder', 'recorder', 'span', 'instant', 'add_span',
           'add_flow', 'export_chrome_trace', 'span_summary', 'reset',
           'set_tap']

# Optional event tap (the flight recorder's feed).  One slot, called
# outside the recorder lock with the already-built event dict.
_TAP = [None]


def set_tap(fn):
    """Install `fn(event_dict)` to observe every recorded event; pass
    None to remove.  Returns the previous tap."""
    prev = _TAP[0]
    _TAP[0] = fn
    return prev


def _attach_ctx(args):
    """Merge the ambient TraceContext (if any) into span args so spans
    recorded deep in the stack (executor, compile pipeline) join the
    request trace that dispatched them."""
    ctx = _tc.current()
    if ctx is None:
        return args
    if args is None:
        return {'trace_id': ctx.trace_id, 'parent_span_id': ctx.span_id}
    if 'trace_id' in args:
        return args
    args = dict(args)
    args['trace_id'] = ctx.trace_id
    args['parent_span_id'] = ctx.span_id
    return args

_EPOCH = time.perf_counter()
_PID = os.getpid()
_MAX_EVENTS = int(os.environ.get('PT_OBS_MAX_EVENTS', '200000'))


def _us(pc_seconds):
    """perf_counter seconds -> microseconds since the recorder epoch."""
    return (pc_seconds - _EPOCH) * 1e6


class TraceRecorder(object):
    def __init__(self, max_events=_MAX_EVENTS):
        self._lock = threading.Lock()
        self._events = deque(maxlen=max_events)
        self._dropped = 0

    def add_complete(self, name, start_pc, end_pc, cat='runtime', args=None):
        """One 'X' (complete) event spanning [start_pc, end_pc] — raw
        time.perf_counter() values."""
        args = _attach_ctx(args)
        ev = {'name': name, 'ph': 'X', 'cat': cat,
              'ts': _us(start_pc), 'dur': max(0.0, (end_pc - start_pc) * 1e6),
              'pid': _PID, 'tid': threading.get_ident()}
        if args:
            ev['args'] = args
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)
        tap = _TAP[0]
        if tap is not None:
            tap(ev)

    def add_instant(self, name, cat='runtime', args=None):
        args = _attach_ctx(args)
        ev = {'name': name, 'ph': 'i', 's': 't', 'cat': cat,
              'ts': _us(time.perf_counter()),
              'pid': _PID, 'tid': threading.get_ident()}
        if args:
            ev['args'] = args
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)
        tap = _TAP[0]
        if tap is not None:
            tap(ev)

    def add_flow(self, flow_id, phase, ts_pc, name='link', cat='flow'):
        """Flow ('s' start / 'f' finish) event — the Perfetto arrow
        linking a request's submit-side slice to its batch slice."""
        ev = {'name': name, 'ph': 's' if phase == 's' else 'f',
              'id': flow_id, 'cat': cat, 'ts': _us(ts_pc),
              'pid': _PID, 'tid': threading.get_ident()}
        if ev['ph'] == 'f':
            ev['bp'] = 'e'
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)

    def events(self):
        with self._lock:
            evs = list(self._events)
        return sorted(evs, key=lambda e: e['ts'])

    def event_count(self):
        with self._lock:
            return len(self._events)

    def export(self, path):
        """Write Chrome-trace JSON (Perfetto-loadable).  Returns the path."""
        payload = {'traceEvents': self.events(), 'displayTimeUnit': 'ms'}
        if self._dropped:
            payload['otherData'] = {'dropped_events': self._dropped}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, 'w') as f:
            json.dump(payload, f)
        return path

    def summary(self):
        """Aggregate complete events by name:
        {name: {calls, total_us, min_us, max_us, ave_us}} — the table
        behind profiler.profiler(sorted_key=...)."""
        agg = {}
        for ev in self.events():
            if ev['ph'] != 'X':
                continue
            s = agg.setdefault(ev['name'], {'calls': 0, 'total_us': 0.0,
                                            'min_us': None, 'max_us': 0.0})
            d = ev['dur']
            s['calls'] += 1
            s['total_us'] += d
            s['min_us'] = d if s['min_us'] is None else min(s['min_us'], d)
            s['max_us'] = max(s['max_us'], d)
        for s in agg.values():
            s['ave_us'] = s['total_us'] / s['calls']
        return agg

    def reset(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0


_RECORDER = TraceRecorder()


def recorder():
    return _RECORDER


@contextlib.contextmanager
def span(name, cat='runtime', **args):
    """Record a complete event around the with-block (no-op when disabled)."""
    if not enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _RECORDER.add_complete(name, t0, time.perf_counter(), cat,
                               args or None)


def add_span(name, start_pc, end_pc, cat='runtime', args=None):
    if enabled():
        _RECORDER.add_complete(name, start_pc, end_pc, cat, args)


def instant(name, cat='runtime', args=None):
    if enabled():
        _RECORDER.add_instant(name, cat, args)


def add_flow(flow_id, phase, ts_pc, name='link', cat='flow'):
    if enabled():
        _RECORDER.add_flow(flow_id, phase, ts_pc, name, cat)


def export_chrome_trace(path):
    return _RECORDER.export(path)


def span_summary():
    return _RECORDER.summary()


def reset():
    _RECORDER.reset()
