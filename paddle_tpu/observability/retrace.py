"""Retrace explainer: names WHY an executable was (re)traced.

The Julia->TPU compile-the-loop model (arxiv 1810.09868) has one silent
failure mode: an unnoticed recompile.  Under whole-block lowering a
retrace can come from two layers — an executor-cache miss (new fetch
set, steps=K, program edit) or a jax.jit shape/dtype miss under an
existing cache entry — and both surface here the same way: the executor
detects a trace via `_TRACE_COUNT`, builds a `LaunchSignature` of every
cache-key component, and the explainer diffs it against the NEAREST
prior signature (fewest differing components) to record which component
changed: feed shapes, feed dtypes, fetch set, steps, program serial,
check_nan, scope.

A signature whose nearest prior differs in `program` is a new-program
compile (expected; counted in `executor.compiles`); anything else is a
retrace (`executor.retraces`) with its cause named in the report and an
instant event dropped on the timeline.  `executor.compile_s` accumulates
trace+compile wall time for both kinds.
"""
import threading
from collections import deque

from . import metrics
from . import tracing

__all__ = ['LaunchSignature', 'RetraceExplainer', 'explainer', 'reset']

_COMPONENTS = ('program', 'feed_shapes', 'feed_dtypes', 'fetch_set',
               'steps', 'check_nan', 'scope', 'opt', 'emit', 'kernelgen')


class LaunchSignature(object):
    """Structured cache key: one attribute per component the executor's
    lowering cache (and jax.jit underneath it) keys on.  `opt` is the
    program-rewriter config token (core/passes.config_token()): toggling
    PT_OPT / PT_OPT_SKIP mid-process changes what the tracer sees for the
    same raw program, and must be named, not a mystery retrace.  `emit`
    is the direct-emitter token (core/emit.config_token()) — flipping
    PT_EMIT is likewise a named signature change, as is `kernelgen`
    (ops/kernelgen.config_token()) for PT_KERNELGEN."""
    __slots__ = _COMPONENTS

    def __init__(self, program, feed_shapes, feed_dtypes, fetch_set,
                 steps, check_nan, scope, opt=None, emit=None,
                 kernelgen=None):
        self.program = program            # (serial, version)
        self.feed_shapes = dict(feed_shapes)   # name -> tuple
        self.feed_dtypes = dict(feed_dtypes)   # name -> str
        self.fetch_set = tuple(fetch_set)
        self.steps = steps
        self.check_nan = bool(check_nan)
        self.scope = scope
        self.opt = opt
        self.emit = emit
        self.kernelgen = kernelgen

    def changed_components(self, other):
        return [c for c in _COMPONENTS
                if getattr(self, c) != getattr(other, c)]

    def explain_against(self, other):
        """Human-readable per-component details of self vs other."""
        details = []
        if self.program != other.program:
            details.append('program: %r -> %r' % (other.program,
                                                  self.program))
        for label, new, old in (('feed_shape', self.feed_shapes,
                                 other.feed_shapes),
                                ('feed_dtype', self.feed_dtypes,
                                 other.feed_dtypes)):
            for n in sorted(set(new) | set(old)):
                if n not in old:
                    details.append('%s:%s added %r' % (label, n, new[n]))
                elif n not in new:
                    details.append('%s:%s removed (was %r)'
                                   % (label, n, old[n]))
                elif new[n] != old[n]:
                    details.append('%s:%s %r -> %r'
                                   % (label, n, old[n], new[n]))
        if self.fetch_set != other.fetch_set:
            added = [n for n in self.fetch_set if n not in other.fetch_set]
            removed = [n for n in other.fetch_set if n not in self.fetch_set]
            details.append('fetch_set: %s%s' % (
                ' '.join('+' + n for n in added),
                (' ' if added else '') + ' '.join('-' + n for n in removed)))
        if self.steps != other.steps:
            details.append('steps: %r -> %r' % (other.steps, self.steps))
        if self.check_nan != other.check_nan:
            details.append('check_nan: %r -> %r'
                           % (other.check_nan, self.check_nan))
        if self.scope != other.scope:
            details.append('scope: serial %r -> %r'
                           % (other.scope, self.scope))
        if self.opt != other.opt:
            details.append('opt: PT_OPT config %r -> %r (program rewriter '
                           'toggled/reconfigured)' % (other.opt, self.opt))
        if self.emit != other.emit:
            details.append('emit: PT_EMIT config %r -> %r (direct '
                           'emitter toggled or versioned)'
                           % (other.emit, self.emit))
        if self.kernelgen != other.kernelgen:
            details.append('kernelgen: PT_KERNELGEN config %r -> %r '
                           '(Pallas codegen tier toggled or versioned)'
                           % (other.kernelgen, self.kernelgen))
        return details


def _bucketable(sig, prior):
    """True when every differing feed shape differs only in its leading
    (batch) and/or second (sequence) dim — the exact raggedness
    FeedBucketer pads away."""
    names = set(sig.feed_shapes) | set(prior.feed_shapes)
    saw_diff = False
    for n in names:
        a = sig.feed_shapes.get(n)
        b = prior.feed_shapes.get(n)
        if a == b:
            continue
        if a is None or b is None or len(a) != len(b):
            return False
        if any(x != y for x, y in zip(a[2:], b[2:])):
            return False
        saw_diff = True
    return saw_diff


class RetraceExplainer(object):
    def __init__(self, max_reports=1000):
        self._lock = threading.Lock()
        self._seen = []
        self.reports = deque(maxlen=max_reports)

    def observe(self, sig, compile_s=0.0, label=None, cache=None,
                lowering=None):
        """Record one (re)trace; returns the report dict.  `cache` names
        the disk-cache verdict for this trace ('miss' / 'stablehlo_hit' /
        'disabled') so every retrace is annotated with whether the
        persistent tier could have prevented it.  `lowering` names HOW
        the program lowered: 'emit' (direct emitter), 'trace' (classic
        per-op tracing), or 'emit_fallback:<op>' (the emitter hit that
        op and this program degraded to tracing)."""
        with self._lock:
            if not self._seen:
                kind, changed, details = 'initial_compile', [], []
            else:
                nearest = min(self._seen,
                              key=lambda s: len(sig.changed_components(s)))
                changed = sig.changed_components(nearest)
                details = sig.explain_against(nearest)
                if 'program' in changed:
                    kind = 'new_program_compile'
                elif changed:
                    kind = 'retrace'
                else:
                    # identical signature traced again: the executor cache
                    # was bypassed or jit's own cache dropped the trace
                    kind = 'retrace'
                    details = ['identical signature retraced (cache '
                               'bypassed or jit cache evicted)']
            if kind == 'retrace' and changed and \
                    set(changed) <= {'feed_shapes'} and \
                    _bucketable(sig, nearest):
                details.append(
                    'bucketable: shapes differ only in batch/sequence '
                    'dims — a FeedBucketer (data_feeder.py) would map '
                    'this feed onto an existing bucket signature')
            self._seen.append(sig)
        report = {'kind': kind, 'changed': changed, 'details': details,
                  'compile_s': compile_s, 'label': label, 'cache': cache,
                  'lowering': lowering}
        self.reports.append(report)
        if kind == 'retrace':
            metrics.counter('executor.retraces').inc()
            tracing.instant('executor.retrace', cat='compile',
                            args={'cause': '; '.join(details) or 'unknown'})
        else:
            metrics.counter('executor.compiles').inc()
        metrics.counter('executor.compile_s').inc(compile_s)
        return report

    def observe_disk_load(self, sig, load_s=0.0):
        """Record a warm start: this signature's executable came from the
        persistent cache, so NO trace/compile happened — the signature
        still joins the nearest-prior pool so later real retraces diff
        against it."""
        with self._lock:
            self._seen.append(sig)
        report = {'kind': 'disk_load', 'changed': [], 'details': [],
                  'compile_s': 0.0, 'load_s': load_s, 'label': None,
                  'cache': 'hit'}
        self.reports.append(report)
        return report

    def last_report(self):
        return self.reports[-1] if self.reports else None

    def render_report(self, report=None):
        """One retrace-explainer report as text (docs/observability.md
        shows the shape)."""
        report = report or self.last_report()
        if report is None:
            return '<no traces recorded>'
        lines = ['[%s] compile_s=%.3f%s%s%s'
                 % (report['kind'], report['compile_s'],
                    ' cache=%s' % report['cache']
                    if report.get('cache') else '',
                    ' lowering=%s' % report['lowering']
                    if report.get('lowering') else '',
                    ' label=%s' % report['label'] if report['label']
                    else '')]
        for d in report['details']:
            lines.append('  changed: %s' % d)
        return '\n'.join(lines)

    def reset(self):
        with self._lock:
            self._seen = []
            self.reports.clear()


_EXPLAINER = RetraceExplainer()


def explainer():
    return _EXPLAINER


def reset():
    _EXPLAINER.reset()
