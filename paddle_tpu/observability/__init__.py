"""paddle_tpu.observability — in-process runtime telemetry.

Always-cheap instrumentation woven through the execution stack (see
docs/observability.md for the metric catalog and span taxonomy):

  * metrics       — counters/gauges/histograms (bounded log buckets with
                    p50/p99), thread-safe, snapshot-to-dict,
                    near-zero-overhead no-op mode (PT_OBS=0 / disable())
  * tracing       — span/event recorder exporting Chrome-trace/Perfetto
                    JSON, with flow-event linking and an ambient
                    trace-context stamp on every span
  * trace_context — W3C-traceparent TraceContext minted per serving
                    request / trainer step, propagated via contextvars
  * retrace       — the retrace explainer: every (re)trace diffs its
                    launch signature against the nearest prior one and
                    names which cache-key component changed
  * stall         — launch-gap histogram + pipeline-drain detection,
                    with suppression for intentional slow windows
                    (breaker slow path, recovery replay)
  * flight        — black-box flight recorder: bounded ring of recent
                    events, dumped as a JSON postmortem (PT_FLIGHT_DIR)
                    on crash/SIGTERM/breaker trip/recovery give-up
  * export        — Prometheus text rendering, the shared
                    telemetry_snapshot() JSON schema, and the
                    /metrics + /healthz + /varz HTTP endpoint
  * memory        — per-launch device-memory gauges (HBM where the
                    backend reports it, live-buffer counts everywhere)

Everything is process-global: one training process is one telemetry
stream.  `snapshot()` returns the whole state as one dict; `reset()`
clears it (profiler.reset_profiler routes here).
"""
from . import metrics  # noqa
from . import trace_context  # noqa
from . import tracing  # noqa
from . import retrace  # noqa
from . import stall  # noqa
from . import flight  # noqa
from . import export  # noqa
from . import memory  # noqa

from .metrics import (enabled, enable, disable, counter, gauge,  # noqa
                      histogram, metrics_snapshot, counters, registry)
from .tracing import (span, instant, add_span, add_flow,  # noqa
                      export_chrome_trace, span_summary, recorder)
from .trace_context import TraceContext  # noqa
from .retrace import LaunchSignature, explainer  # noqa
from .stall import (on_launch_start, on_launch_end,  # noqa
                    stall_threshold_ms, set_stall_threshold_ms)
from .export import render_prometheus, telemetry_snapshot  # noqa

__all__ = ['metrics', 'tracing', 'trace_context', 'retrace', 'stall',
           'flight', 'export', 'memory', 'enabled', 'enable', 'disable',
           'counter', 'gauge', 'histogram', 'metrics_snapshot', 'counters',
           'registry', 'span', 'instant', 'add_span', 'add_flow',
           'export_chrome_trace', 'span_summary', 'recorder',
           'TraceContext', 'LaunchSignature', 'explainer',
           'on_launch_start', 'on_launch_end', 'stall_threshold_ms',
           'set_stall_threshold_ms', 'render_prometheus',
           'telemetry_snapshot', 'snapshot', 'reset']

# every trace event mirrors into the flight ring (bounded; lock-free
# appends), so a postmortem dump always carries the recent timeline
flight.install_tap()


def snapshot():
    """Full telemetry dump: metrics + span summary + retrace reports."""
    snap = metrics.metrics_snapshot()
    snap['spans'] = tracing.span_summary()
    snap['retrace_reports'] = list(retrace.explainer().reports)
    return snap


def reset():
    """Clear every recorded metric, span, and retrace report."""
    metrics.reset()
    tracing.reset()
    retrace.reset()
    flight.flight().reset()
