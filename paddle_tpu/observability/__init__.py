"""paddle_tpu.observability — in-process runtime telemetry.

Always-cheap instrumentation woven through the execution stack (see
docs/observability.md for the metric catalog and span taxonomy):

  * metrics   — counters/gauges/histograms, thread-safe, snapshot-to-dict,
                near-zero-overhead no-op mode (PT_OBS=0 or disable())
  * tracing   — span/event recorder exporting Chrome-trace/Perfetto JSON
  * retrace   — the retrace explainer: every (re)trace diffs its launch
                signature against the nearest prior one and names which
                cache-key component changed
  * stall     — launch-gap histogram + pipeline-drain detection

Everything is process-global: one training process is one telemetry
stream.  `snapshot()` returns the whole state as one dict; `reset()`
clears it (profiler.reset_profiler routes here).
"""
from . import metrics  # noqa
from . import tracing  # noqa
from . import retrace  # noqa
from . import stall  # noqa

from .metrics import (enabled, enable, disable, counter, gauge,  # noqa
                      histogram, metrics_snapshot, counters, registry)
from .tracing import (span, instant, add_span, export_chrome_trace,  # noqa
                      span_summary, recorder)
from .retrace import LaunchSignature, explainer  # noqa
from .stall import (on_launch_start, on_launch_end,  # noqa
                    stall_threshold_ms, set_stall_threshold_ms)

__all__ = ['metrics', 'tracing', 'retrace', 'stall', 'enabled', 'enable',
           'disable', 'counter', 'gauge', 'histogram', 'metrics_snapshot',
           'counters', 'registry', 'span', 'instant', 'add_span',
           'export_chrome_trace', 'span_summary', 'recorder',
           'LaunchSignature', 'explainer', 'on_launch_start',
           'on_launch_end', 'stall_threshold_ms', 'set_stall_threshold_ms',
           'snapshot', 'reset']


def snapshot():
    """Full telemetry dump: metrics + span summary + retrace reports."""
    snap = metrics.metrics_snapshot()
    snap['spans'] = tracing.span_summary()
    snap['retrace_reports'] = list(retrace.explainer().reports)
    return snap


def reset():
    """Clear every recorded metric, span, and retrace report."""
    metrics.reset()
    tracing.reset()
    retrace.reset()
