"""Device-memory profiling hooks.

HBM is the binding constraint for the fusion work in ROADMAP item 2,
and until now the runtime never measured it.  `on_launch()` is called
by the executor after every device launch (only when telemetry is on)
and samples:

  * `exec.hbm_peak_bytes` / `exec.hbm_in_use_bytes` / `exec.hbm_limit_bytes`
    — from `device.memory_stats()` where the backend supports it (TPU,
    GPU).  CPU's `memory_stats()` returns None; the probe caches that
    verdict once and the hook degrades to a single cached-flag check —
    the graceful no-op the CPU CI path runs.
  * `exec.live_buffers` — `len(jax.live_arrays())`, which works on
    every backend and catches buffer leaks (a serving soak whose live
    count climbs monotonically is holding results somewhere).

`host_rss_bytes()` reports the process high-water RSS (checkpoint
snapshots are forced host copies; train/checkpoint.py accounts their
bytes in `ckpt.snapshot_host_bytes`).  `PT_OBS_MEM=0` switches the
whole module off independently of PT_OBS.
"""
import os

from . import metrics

__all__ = ['on_launch', 'device_memory_stats', 'live_buffer_count',
           'host_rss_bytes', 'mem_enabled']

_MEM_ON = [os.environ.get('PT_OBS_MEM', '1') not in ('0', 'false', 'False')]
# tri-state cache: None = not probed, False = backend has no stats,
# True = stats available
_STATS_SUPPORTED = [None]


def mem_enabled():
    return _MEM_ON[0] and metrics.enabled()


def _reset_probe():
    _STATS_SUPPORTED[0] = None


def device_memory_stats():
    """The first local device's memory stats dict, or None when the
    backend doesn't report them (CPU).  The negative verdict is cached —
    per-launch cost on CPU is one list lookup."""
    if _STATS_SUPPORTED[0] is False:
        return None
    try:
        import jax
        devs = jax.local_devices()
        stats = devs[0].memory_stats() if devs else None
    except Exception:
        stats = None
    if not stats:
        _STATS_SUPPORTED[0] = False
        return None
    _STATS_SUPPORTED[0] = True
    return stats


def live_buffer_count():
    try:
        import jax
        return len(jax.live_arrays())
    except Exception:
        return None


def on_launch():
    """Per-launch sampling hook (executor calls this with obs enabled)."""
    if not _MEM_ON[0] or not metrics.enabled():
        return
    stats = device_memory_stats()
    if stats:
        peak = stats.get('peak_bytes_in_use')
        in_use = stats.get('bytes_in_use')
        limit = stats.get('bytes_limit')
        if peak is not None:
            metrics.gauge('exec.hbm_peak_bytes').set(int(peak))
        if in_use is not None:
            metrics.gauge('exec.hbm_in_use_bytes').set(int(in_use))
        if limit is not None:
            metrics.gauge('exec.hbm_limit_bytes').set(int(limit))
    live = live_buffer_count()
    if live is not None:
        metrics.gauge('exec.live_buffers').set(live)


def host_rss_bytes():
    """Process peak RSS in bytes (ru_maxrss is KiB on Linux)."""
    try:
        import resource
        rss_kib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        import sys
        if sys.platform == 'darwin':   # macOS reports bytes already
            return int(rss_kib)
        return int(rss_kib) * 1024
    except Exception:
        return None
