"""Telemetry export surfaces: Prometheus text rendering, the one shared
telemetry-snapshot schema, and a stdlib-only HTTP endpoint.

Three consumers, one source of truth:

  * ``render_prometheus()`` walks the live metrics registry and emits
    Prometheus text exposition format (0.0.4).  Dotted names become
    underscored families; counters get the ``_total`` suffix, so
    ``serving.admitted`` scrapes as ``serving_admitted_total``.
    Histograms render as real cumulative-``le`` histograms straight
    from the bounded log buckets.
  * ``telemetry_snapshot(section, ...)`` is the ONE JSON emitter behind
    ``bench.py``'s telemetry block, ``tools/serve_soak.py`` and
    ``tools/fault_soak.py`` — each section's keys live in ``SCHEMA``,
    so a renamed counter breaks one declarative table (which ci_smoke
    validates once) instead of silently drifting three tools apart.
  * ``MetricsServer`` serves ``/metrics`` (Prometheus text),
    ``/healthz`` (ServingEngine health, 503 while not accepting) and
    ``/varz`` (full JSON debug dump) from a daemon thread.  The
    ServingEngine owns one when ``PT_METRICS_PORT`` (or
    ``ServingConfig.metrics_port``) is set — it starts at ``start()``
    and is torn down by ``stop()``.
"""
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import flight as _flight
from . import metrics
from . import retrace
from . import tracing

__all__ = ['render_prometheus', 'prom_name', 'telemetry_snapshot',
           'schema_keys', 'SCHEMA', 'MetricsServer', 'start_http_server',
           'resolve_metrics_port', 'PROM_CONTENT_TYPE']

PROM_CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'


# ------------------------------------------------------------------ prom
def prom_name(name, suffix=''):
    """`serving.admitted` -> `serving_admitted` (+ optional suffix)."""
    n = ''.join(ch if (ch.isalnum() or ch == '_') else '_' for ch in name)
    if n and n[0].isdigit():
        n = '_' + n
    return n + suffix


def _fmt(v):
    return '%.10g' % float(v)


def render_prometheus():
    """The whole registry in Prometheus text exposition format."""
    lines = []
    for name, m in metrics.registry().items():
        if isinstance(m, metrics.Counter):
            pn = prom_name(name, '_total')
            lines.append('# TYPE %s counter' % pn)
            lines.append('%s %s' % (pn, _fmt(m.snapshot())))
        elif isinstance(m, metrics.Gauge):
            v = m.snapshot()
            if v is None or not isinstance(v, (int, float)):
                continue
            pn = prom_name(name)
            lines.append('# TYPE %s gauge' % pn)
            lines.append('%s %s' % (pn, _fmt(v)))
        elif isinstance(m, metrics.Histogram):
            pn = prom_name(name)
            snap = m.snapshot()
            lines.append('# TYPE %s histogram' % pn)
            for le, cum in m.cumulative_buckets():
                lines.append('%s_bucket{le="%s"} %d' % (pn, _fmt(le), cum))
            lines.append('%s_bucket{le="+Inf"} %d' % (pn, snap['count']))
            lines.append('%s_sum %s' % (pn, _fmt(snap.get('sum', 0.0))))
            lines.append('%s_count %d' % (pn, snap['count']))
    return '\n'.join(lines) + '\n'


# ------------------------------------------------- shared JSON schema
# Spec kinds: ('int'|'sec', counter) read one counter (sec rounds to ms
# precision); ('delta_int', counter) subtracts the baseline snapshot;
# ('sum_int', names) / ('ratio', num, den) derive; ('quantile', hist, q)
# reads a bounded-histogram quantile; ('extra',) must be supplied by the
# caller (values the registry can't know — platform, program op counts);
# ('block_prefix', prefixes, names) / ('block_names', names) build the
# nested counters dict soak tools print.
SCHEMA = {
    'bench': (
        ('platform', ('extra',)),
        ('device_kind', ('extra',)),
        ('retraces', ('delta_int', 'executor.retraces')),
        ('retraces_total', ('int', 'executor.retraces')),
        ('compiles', ('int', 'executor.compiles')),
        ('compile_s', ('sec', 'executor.compile_s')),
        ('compile_s_cold', ('sec', 'executor.compile_s')),
        ('compile_s_warm', ('sec', 'compile_cache.load_s')),
        ('compile_cache_hits', ('int', 'compile_cache.disk_hits')),
        ('compile_cache_misses', ('int', 'compile_cache.disk_misses')),
        ('tail_splits', ('int', 'executor.tail_splits')),
        ('emit_s', ('sec', 'executor.emit_s')),
        ('trace_s', ('sec', 'executor.trace_s')),
        ('backend_compile_s', ('sec', 'executor.backend_compile_s')),
        ('program_op_count_raw', ('extra',)),
        ('program_op_count_opt', ('extra',)),
        ('opt_pass_ms', ('sec', 'opt.pass_ms')),
        ('opt_ops_fused', ('int', 'opt.ops_fused')),
        ('stall_count', ('delta_int', 'executor.stall_count')),
        ('prefetch_starvation_s', ('sec', 'prefetch.starvation_s')),
        ('fetch_sync_s', ('sec', 'executor.fetch_sync_s')),
        ('kernel_fallbacks', ('int', 'kernel.fallbacks')),
        ('emitter_fallbacks', ('int', 'emitter.fallbacks')),
        ('kernelgen_ops', ('int', 'kernelgen.ops')),
        ('kernelgen_fallbacks', ('int', 'kernelgen.fallbacks')),
        ('autotune_searches', ('int', 'kernelgen.autotune_searches')),
        ('autotune_cache_hits', ('int',
                                 'kernelgen.autotune_cache_hits')),
        ('fused_adam_ms', ('extra',)),
        ('host_blocked_s', ('sec', 'executor.host_blocked_s')),
        ('nan_poll_lag_steps', ('int', 'nan_poll.lag_steps')),
        ('prefetch_upload_overlap_s', ('sec', 'prefetch.upload_overlap_s')),
        ('forensics_replays', ('int', 'recovery.forensics_replay_steps')),
        ('quarantined_samples', ('int', 'feed.quarantined')),
    ),
    'serving': (
        ('admitted', ('int', 'serving.admitted')),
        ('terminal_replies', ('sum_int', ('serving.completed',
                                          'serving.errors',
                                          'serving.deadline_exceeded',
                                          'serving.shed'))),
        ('shed_rate', ('ratio', 'serving.shed', 'serving.admitted')),
        ('p50_ms', ('quantile', 'serving.latency_ms', 0.50)),
        ('p99_ms', ('quantile', 'serving.latency_ms', 0.99)),
        ('breaker_trips', ('int', 'serving.breaker_trips')),
        ('breaker_recoveries', ('int', 'serving.breaker_recoveries')),
        ('deadlocks', ('int', 'serving.deadlocks')),
        ('ttft_p50_ms', ('quantile', 'serving.ttft_ms', 0.50)),
        ('ttft_p99_ms', ('quantile', 'serving.ttft_ms', 0.99)),
        ('itl_p50_ms', ('quantile', 'serving.itl_ms', 0.50)),
        ('itl_p99_ms', ('quantile', 'serving.itl_ms', 0.99)),
        ('kv_slots_in_use', ('int', 'generation.kv_slots_in_use')),
        ('kv_pages_in_use', ('int', 'generation.kv_pages_in_use')),
        ('kv_bytes_reserved', ('int', 'generation.kv_bytes_reserved')),
        ('kv_bytes_live', ('int', 'generation.kv_bytes_live')),
        ('counters', ('block_prefix', ('serving.', 'faults.',
                                       'generation.'),
                      ('bucketer.bucket_count',))),
    ),
    'resilience': (
        ('counters', ('block_names', (
            'faults.injected', 'recovery.rollbacks', 'recovery.divergences',
            'recovery.skipped_steps', 'recovery.device_loss', 'ckpt.saves',
            'ckpt.write_failures', 'ckpt.torn_deleted', 'ckpt.restores',
            'ckpt.corrupt_skipped', 'ckpt.shard_writes',
            'ckpt.shard_manifests', 'ckpt.partial_swept', 'ckpt.reshards',
            'ckpt.desync_dropped', 'health.beats', 'health.trips',
            'health.lost_hosts', 'health.desyncs', 'retry.attempts',
            'executor.retraces', 'executor.stall_count',
            'prefetch.starvation_count', 'kernel.fallbacks',
            'nan_poll.polls', 'nan_poll.trips',
            'executor.host_blocked_s', 'recovery.forensics_runs',
            'recovery.forensics_replay_steps',
            'recovery.escalation.quarantine', 'recovery.escalation.skip',
            'feed.quarantined', 'retry.attempts.feed_read'))),
    ),
}

# ------------------------------------------- perf-lab record sections
# One ``perflab.<scenario>`` section per performance-lab scenario
# (observability/perflab.py validates every ledger record against its
# section).  These use a different spec vocabulary from the telemetry
# sections above — they describe RECORD metrics and how `perflab
# compare` treats them, not how to read the live registry:
#
#   ('counter', 'lower'|'higher')      deterministic integer.  Exact,
#       zero tolerance: any move in the worse direction (away from the
#       declared better direction) is a regression.  CI-enforceable on
#       CPU — op counts, fallbacks and retraces don't depend on clock
#       noise.
#   ('timing', 'lower'|'higher', unit) noise-bounded float (or null
#       when unmeasurable, e.g. MFU off-TPU).  Best-of-K with the raw
#       samples recorded in the record's ``spread`` block; compared
#       only when baseline and candidate share a backend, within a
#       per-metric relative threshold widened by the observed spread.
#   ('info', )                         descriptive context (shapes,
#       request counts).  Never compared.
SCHEMA.update({
    'perflab.train_transformer': (
        ('program_op_count_opt', ('counter', 'lower')),
        ('compiles_after_warmup', ('counter', 'lower')),
        ('retraces', ('counter', 'lower')),
        ('kernel_fallbacks', ('counter', 'lower')),
        ('kernelgen_fallbacks', ('counter', 'lower')),
        ('emitter_fallbacks', ('counter', 'lower')),
        ('tokens_per_s', ('timing', 'higher', 'tokens/s')),
        ('mfu', ('timing', 'higher', 'ratio')),
        ('host_blocked_s', ('timing', 'lower', 's')),
        ('params_m', ('info',)),
        ('batch', ('info',)),
        ('seq', ('info',)),
        ('steps_per_launch', ('info',)),
    ),
    'perflab.train_resnet': (
        ('compiles_after_warmup', ('counter', 'lower')),
        ('retraces', ('counter', 'lower')),
        ('kernel_fallbacks', ('counter', 'lower')),
        ('emitter_fallbacks', ('counter', 'lower')),
        ('images_per_s', ('timing', 'higher', 'img/s')),
        ('mfu', ('timing', 'higher', 'ratio')),
        ('batch', ('info',)),
        ('depth', ('info',)),
    ),
    'perflab.decode_stream': (
        ('compiles_after_warmup', ('counter', 'lower')),
        ('deadlocks', ('counter', 'lower')),
        ('kv_slots_leaked', ('counter', 'lower')),
        ('kv_pages_leaked', ('counter', 'lower')),
        ('streams_failed', ('counter', 'lower')),
        ('streams_at_slo', ('counter', 'higher')),
        ('density_x_vs_dense', ('counter', 'higher')),
        ('tokens_per_s_per_chip', ('timing', 'higher', 'tokens/s')),
        ('ttft_p99_ms', ('timing', 'lower', 'ms')),
        ('itl_p99_ms', ('timing', 'lower', 'ms')),
        ('requests', ('info',)),
        ('streams_ok', ('info',)),
    ),
    'perflab.pod_parallel': (
        ('workers_completed', ('counter', 'higher')),
        ('worker_failures', ('counter', 'lower')),
        ('allreduce_gbps', ('timing', 'higher', 'GB/s')),
        ('steps_per_s_1worker', ('timing', 'higher', 'steps/s')),
        ('scaling_2worker_x', ('timing', 'higher', 'x')),
        # shard-pass round: explicit-collective accounting + per-device
        # persistable HBM, replicated vs ZeRO-sharded in one record
        ('reshards_inserted', ('counter', 'lower')),
        ('collective_bytes', ('counter', 'lower')),
        ('hbm_sharded_ratio', ('timing', 'lower', 'x')),
        ('hbm_params_bytes_replicated', ('info',)),
        ('hbm_params_bytes_sharded', ('info',)),
        ('devices', ('info',)),
    ),
    'perflab.fused_adam_micro': (
        ('kernelgen_ops', ('counter', 'higher')),
        ('kernelgen_fallbacks', ('counter', 'lower')),
        ('retraces', ('counter', 'lower')),
        ('fused_adam_ms', ('timing', 'lower', 'ms')),
        ('params', ('info',)),
    ),
    # ledger bridges: bench.py / serve_soak.py / pod_soak.py emit their
    # existing telemetry through the shared scenario-record writer
    # (PT_PERF_LEDGER=<path>) so all three feed the same PERF_HISTORY
    'perflab.bench': (
        ('program_op_count_opt', ('counter', 'lower')),
        ('retraces', ('counter', 'lower')),
        ('kernel_fallbacks', ('counter', 'lower')),
        ('kernelgen_fallbacks', ('counter', 'lower')),
        ('emitter_fallbacks', ('counter', 'lower')),
        ('tokens_per_s', ('timing', 'higher', 'tokens/s')),
        ('mfu', ('timing', 'higher', 'ratio')),
        ('host_blocked_s', ('timing', 'lower', 's')),
        ('fused_adam_ms', ('timing', 'lower', 'ms')),
        ('resnet50_images_per_s', ('timing', 'higher', 'img/s')),
        ('batch', ('info',)),
        ('seq', ('info',)),
    ),
    'perflab.serve_soak': (
        ('deadlocks', ('counter', 'lower')),
        ('no_reply', ('counter', 'lower')),
        ('p99_ms', ('timing', 'lower', 'ms')),
        ('ttft_p99_ms', ('timing', 'lower', 'ms')),
        ('itl_p99_ms', ('timing', 'lower', 'ms')),
        ('scenario', ('info',)),
        ('admitted', ('info',)),
    ),
    'perflab.decode_capacity': (
        ('streams_at_slo', ('counter', 'higher')),
        ('kv_pages_leaked', ('counter', 'lower')),
        ('density_x_vs_dense', ('counter', 'higher')),
        ('capacity_floor', ('info',)),
        ('kv_budget_bytes', ('info',)),
        ('page_len', ('info',)),
        ('kv_quant', ('info',)),
    ),
    'perflab.pod_soak': (
        ('failures', ('counter', 'lower')),
        ('segments', ('info',)),
        ('rollbacks', ('info',)),
        ('manifests', ('info',)),
    ),
})


def schema_keys(section):
    return [k for k, _ in SCHEMA[section]]


def telemetry_snapshot(section, baseline=None, extra=None, snapshot=None):
    """Build the section's telemetry dict from the live registry.

    ``baseline`` is an earlier ``obs.counters()`` for delta keys;
    ``extra`` supplies exactly the keys declared ``('extra',)`` —
    missing or unknown extra keys raise, which is the anti-drift
    contract the three emitters share.
    """
    spec = SCHEMA[section]
    c = metrics.counters() if snapshot is None else snapshot
    baseline = baseline or {}
    extra = dict(extra or {})
    declared_extra = {k for k, s in spec if s[0] == 'extra'}
    unknown = set(extra) - declared_extra
    if unknown:
        raise ValueError('telemetry_snapshot(%r): unexpected extra keys %s'
                         % (section, sorted(unknown)))
    missing = declared_extra - set(extra)
    if missing:
        raise ValueError('telemetry_snapshot(%r): missing extra keys %s'
                         % (section, sorted(missing)))

    def val(name):
        return c.get(name) or 0

    out = {}
    for key, s in spec:
        kind = s[0]
        if kind == 'extra':
            out[key] = extra[key]
        elif kind == 'int':
            out[key] = int(val(s[1]))
        elif kind == 'sec':
            out[key] = round(float(val(s[1])), 3)
        elif kind == 'delta_int':
            out[key] = int(val(s[1])) - int(baseline.get(s[1]) or 0)
        elif kind == 'sum_int':
            out[key] = sum(int(val(n)) for n in s[1])
        elif kind == 'ratio':
            out[key] = round(float(val(s[1])) / float(max(1, val(s[2]))), 4)
        elif kind == 'quantile':
            q = metrics.histogram(s[1]).quantile(s[2])
            out[key] = None if q is None else float(q)
        elif kind == 'block_prefix':
            prefixes, names = s[1], s[2]
            out[key] = {k: c.get(k) for k in sorted(c)
                        if k.startswith(prefixes) or k in names}
        elif kind == 'block_names':
            out[key] = {k: c.get(k) or 0 for k in s[1]}
        else:
            raise ValueError('unknown telemetry spec kind %r' % (kind,))
    return out


# ------------------------------------------------------- HTTP endpoint
def _varz():
    snap = metrics.metrics_snapshot()
    snap['spans'] = tracing.span_summary()
    snap['retrace_reports'] = list(retrace.explainer().reports)
    snap['flight_events'] = len(_flight.flight().events())
    snap['env'] = {k: v for k, v in os.environ.items()
                   if k.startswith('PT_') or k == 'JAX_PLATFORMS'}
    return snap


class _Handler(BaseHTTPRequestHandler):
    server_version = 'paddle-tpu-obs/1'

    def log_message(self, fmt, *args):   # no stderr spam per scrape
        pass

    def do_GET(self):
        path = self.path.split('?', 1)[0]
        if path == '/metrics':
            body, ctype, code = render_prometheus().encode(), \
                PROM_CONTENT_TYPE, 200
        elif path == '/healthz':
            engine = getattr(self.server, 'pt_engine', None)
            if engine is not None:
                h = engine.health()
                code = 200 if h.get('accepting') else 503
            else:
                h, code = {'state': 'ok', 'accepting': True}, 200
            body, ctype = (json.dumps(h) + '\n').encode(), 'application/json'
        elif path == '/varz':
            body, ctype, code = \
                (json.dumps(_varz(), default=str) + '\n').encode(), \
                'application/json', 200
        else:
            body, ctype, code = b'not found\n', 'text/plain', 404
        self.send_response(code)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsServer(object):
    """Daemon-threaded HTTP server for /metrics, /healthz, /varz.
    ``port=0`` binds an ephemeral port (tests); ``.port`` is the bound
    one.  ``engine`` (optional) backs /healthz."""

    def __init__(self, port=0, host='127.0.0.1', engine=None):
        self._host = host
        self._want_port = int(port)
        self._engine = engine
        self._httpd = None
        self._thread = None

    def start(self):
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self._host, self._want_port), _Handler)
        httpd.daemon_threads = True
        httpd.pt_engine = self._engine
        self._httpd = httpd
        self._thread = threading.Thread(target=httpd.serve_forever,
                                        name='ObsMetricsHTTP', daemon=True)
        self._thread.start()
        metrics.gauge('obs.metrics_port').set(self.port)
        return self

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else None

    def url(self, path='/metrics'):
        return 'http://%s:%d%s' % (self._host, self.port, path)

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def start_http_server(port=0, host='127.0.0.1', engine=None):
    return MetricsServer(port=port, host=host, engine=engine).start()


def resolve_metrics_port(explicit=None):
    """Config beats env (`PT_METRICS_PORT`); None means no server."""
    if explicit is not None:
        return int(explicit)
    env = os.environ.get('PT_METRICS_PORT')
    if env in (None, ''):
        return None
    return int(env)
