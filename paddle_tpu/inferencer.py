"""Deprecated location (parity: reference fluid/inferencer.py) — use
paddle_tpu.contrib.Inferencer."""
from .contrib.inferencer import Inferencer  # noqa: F401

__all__ = []
