"""Training checkpoint / resume with failure recovery.

Parity: reference python/paddle/fluid/trainer.py CheckpointConfig +
_save_checkpoint/_load_checkpoint (epoch/step metadata, rotation) and the
contrib fault-tolerance hooks.  TPU-native: persistables are device arrays in
the Scope; serialization goes through io.save_persistables (numpy .npz under
the hood), and an atomic SUCCESS marker guards against torn checkpoints from
mid-write failures.
"""
import json
import os
import shutil
import tempfile

from .. import io as fluid_io

__all__ = ['CheckpointConfig', 'Checkpointer']

_SUCCESS = '_SUCCESS'
_META = 'META'


class CheckpointConfig(object):
    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or 'checkpoint'
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))


class Checkpointer(object):
    """Periodic checkpoint writer + newest-valid-checkpoint restorer."""

    def __init__(self, config, executor, main_program=None):
        if isinstance(config, str):
            config = CheckpointConfig(config)
        self.config = config
        self.executor = executor
        self.main_program = main_program
        self._serial = -1

    # --------------------------------------------------------------- save
    def _dir_of(self, serial):
        return os.path.join(self.config.checkpoint_dir,
                            'checkpoint_%d' % serial)

    def maybe_save(self, epoch_id, step_id, extra_meta=None):
        """Save if the step/epoch intervals say so; returns the checkpoint
        dir or None."""
        if step_id % self.config.step_interval != 0 or \
                epoch_id % self.config.epoch_interval != 0:
            return None
        return self.save(epoch_id, step_id, extra_meta)

    def save(self, epoch_id, step_id, extra_meta=None):
        cfg = self.config
        os.makedirs(cfg.checkpoint_dir, exist_ok=True)
        serial = self._serial + 1
        final_dir = self._dir_of(serial)
        # write to a temp dir then rename: a crash mid-write can never leave
        # a half-checkpoint that restore() would pick up
        tmp = tempfile.mkdtemp(dir=cfg.checkpoint_dir, prefix='.tmp_ckpt_')
        try:
            fluid_io.save_persistables(self.executor, tmp, self.main_program)
            meta = {'epoch_id': int(epoch_id), 'step_id': int(step_id)}
            if extra_meta:
                meta.update(extra_meta)
            with open(os.path.join(tmp, _META), 'w') as f:
                json.dump(meta, f)
            with open(os.path.join(tmp, _SUCCESS), 'w') as f:
                f.write('ok')
            if os.path.isdir(final_dir):
                shutil.rmtree(final_dir)
            os.rename(tmp, final_dir)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._serial = serial
        self._rotate()
        return final_dir

    def _serials(self):
        d = self.config.checkpoint_dir
        if not os.path.isdir(d):
            return []
        out = []
        for name in os.listdir(d):
            if not name.startswith('checkpoint_'):
                continue
            try:
                s = int(name.split('_')[1])
            except (IndexError, ValueError):
                continue
            if os.path.exists(os.path.join(d, name, _SUCCESS)):
                out.append(s)
        return sorted(out)

    def _rotate(self):
        keep = self.config.max_num_checkpoints
        serials = self._serials()
        for s in serials[:-keep] if keep > 0 else []:
            shutil.rmtree(self._dir_of(s), ignore_errors=True)

    # ------------------------------------------------------------ restore
    def restore(self):
        """Load the newest COMPLETE checkpoint (ones without the SUCCESS
        marker — torn by a failure — are skipped).  Returns its meta dict,
        or None if nothing to restore."""
        for s in reversed(self._serials()):
            ckpt = self._dir_of(s)
            try:
                fluid_io.load_persistables(self.executor, ckpt,
                                           self.main_program)
                with open(os.path.join(ckpt, _META)) as f:
                    meta = json.load(f)
                self._serial = s
                return meta
            except Exception:
                # corrupt beyond the marker: fall back to the previous one
                continue
        return None
