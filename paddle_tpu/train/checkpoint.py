"""Fault-tolerant training checkpoints: async preemption-safe writer,
newest-valid restorer, full bitwise-resume state.

Parity: reference python/paddle/fluid/trainer.py CheckpointConfig +
_save_checkpoint/_load_checkpoint (epoch/step metadata, rotation) and the
contrib fault-tolerance hooks — grown into a resilience primitive:

  * **Async writes.**  ``save()`` snapshots device state to host numpy
    (the only synchronous part — a device→host copy) and hands the write
    to a single background thread, so checkpointing never stalls the
    step loop on disk I/O.  ``wait()`` drains pending writes; a write
    failure is counted (``ckpt.write_failures``) and warned, not fatal —
    a lost checkpoint is survivable, a dead soak is not
    (``CheckpointConfig(strict_writes=True)`` restores raise-on-failure).
  * **Atomic + torn-proof.**  Each checkpoint is written into a temp dir
    and renamed into place with a ``_SUCCESS`` marker written last; a
    crash mid-write can never leave a half-checkpoint that ``restore()``
    would pick up.  ``restore()`` additionally DELETES torn directories
    (no marker) and stale temp dirs left by killed writers.
  * **Full resume state.**  META carries epoch/step, the executor's
    RNG/run-counter state (`Executor.rng_state`), caller ``extra_meta``
    (e.g. a FeedPrefetcher cursor), and a wall-clock stamp.  Restoring
    puts every persistable (params + optimizer accumulators + LR
    counters) back in the scope AND re-arms the run counters, so a
    resumed run continues **bitwise-identically** to an uninterrupted
    one — dropout masks and all (the counter fold-in RNG derivation
    makes the stream a pure function of saved state).
  * **Preemption flush.**  ``install_signal_handlers()`` arms SIGTERM/
    SIGINT to flush one final blocking checkpoint at the last recorded
    progress before the previous handler (or default death) runs.
  * **Sharded pod mode.**  With ``CheckpointConfig(host_count=H,
    host_id=h)`` each host snapshots and writes only ITS row-slice of
    every persistable (``arrays_<h>.npz`` — host RAM and disk I/O scale
    as 1/H) into a shared ``checkpoint_<serial>.parts`` staging dir.
    The last host to land its shard **finalizes** the serial under the
    ``ckpt.lock`` advisory lock: it verifies the roster is complete and
    step-consistent, writes ``MANIFEST.json`` (global shapes, shard
    index map, per-file SHA-256, mesh axes, writer roster, sharding
    specs), then marks and renames the dir — so a committed checkpoint
    is all-hosts-or-nothing, and a partial one is swept as a unit.
    Serials are derived from the global step (``step_id + 1``) so
    lockstep hosts converge on the same dir without communication.
  * **Elastic restore.**  ``restore()`` reassembles global arrays from
    any manifest (every shard file checksum-verified first) and loads
    them onto the CURRENT config — an H-host checkpoint resumes on 1
    host or vice versa; a mesh/roster change counts ``ckpt.reshards``.

Rotation keeps the newest ``max_num_checkpoints`` *valid* dirs, under
the same ``ckpt.lock`` so two writers sharing a dir cannot sweep each
other's newest-K.  Real disk writes go through ``retry_with_backoff``
(transient OSError absorbed — the ``ckpt_io`` fault site rehearses
this); the ``ckpt_write`` fault site still simulates a CRASH between
the tensor file and the marker/sidecar, which is how the torn-scan and
partial-sweep paths stay tested.
"""
import hashlib
import json
import os
import queue
import shutil
import signal as _signal
import tempfile
import threading
import time
import warnings

try:
    import fcntl
except ImportError:  # non-POSIX: advisory locking degrades to thread-only
    fcntl = None

import numpy as np

from .. import observability as _obs
from ..core import signals as _signals
from ..core.retry import retry_with_backoff
from ..observability import flight as _flight
from ..testing import faults as _faults

__all__ = ['CheckpointConfig', 'Checkpointer']

_SUCCESS = '_SUCCESS'
_META = 'META'
_ARRAYS = '__params__.npz'   # same file the io.save_persistables path used
_MANIFEST = 'MANIFEST.json'
_SHARD_META = 'shard_%d.json'
_SHARD_NPZ = 'arrays_%d.npz'
_PARTS = '.parts'            # staging suffix for multi-host serials
_LOCKFILE = 'ckpt.lock'
_FORMAT = 'ptckpt-sharded-1'
# step skew the host_desync fault injects into a sidecar/heartbeat (kept
# in sync with parallel/health.py): far past any desync tolerance
_DESYNC_SKEW = 10000


def _sha256_file(path):
    h = hashlib.sha256()
    n = 0
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(1 << 20), b''):
            h.update(chunk)
            n += len(chunk)
    return h.hexdigest(), n


def _write_json_atomic(path, obj):
    tmp = '%s.tmp%d' % (path, os.getpid())
    with open(tmp, 'w') as f:
        json.dump(obj, f)
    os.replace(tmp, path)


class _DirLock(object):
    """Advisory inter-process lock on a file inside the checkpoint dir,
    re-entrant within a thread.  flock() is per open-file-description,
    so the writer thread and a signal-flush in the main thread must not
    share one fd — a plain threading.RLock in front serializes them."""

    def __init__(self, path, timeout_s=30.0):
        self.path = path
        self.timeout_s = float(timeout_s)
        self._tlock = threading.RLock()
        self._depth = 0
        self._fd = None

    def __enter__(self):
        self._tlock.acquire()
        self._depth += 1
        if self._depth > 1 or fcntl is None:
            return self
        os.makedirs(os.path.dirname(self.path) or '.', exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        deadline = time.monotonic() + self.timeout_s
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    os.close(fd)
                    self._depth -= 1
                    self._tlock.release()
                    raise RuntimeError(
                        'timed out (%.1fs) waiting for checkpoint lock %s '
                        '— another process is holding it' %
                        (self.timeout_s, self.path))
                time.sleep(0.02)
        self._fd = fd
        return self

    def __exit__(self, *exc):
        if self._depth == 1 and self._fd is not None:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None
        self._depth -= 1
        self._tlock.release()
        return False


class CheckpointConfig(object):
    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10, async_write=True,
                 strict_writes=False, handle_signals=True, sharded=None,
                 host_id=None, host_count=None, lock_timeout_s=30.0,
                 stale_parts_s=900.0):
        self.checkpoint_dir = checkpoint_dir or 'checkpoint'
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        self.async_write = bool(async_write)
        self.strict_writes = bool(strict_writes)
        # honored by owners that manage a training loop (contrib.Trainer):
        # arm the SIGTERM/SIGINT final-flush handlers on construction
        self.handle_signals = bool(handle_signals)
        # pod roster: which slice of every persistable THIS process owns
        if host_id is None:
            host_id = int(os.environ.get('PT_HOST_ID', '0'))
        if host_count is None:
            host_count = int(os.environ.get('PT_HOST_COUNT', '1'))
        self.host_id = int(host_id)
        self.host_count = max(1, int(host_count))
        if not 0 <= self.host_id < self.host_count:
            raise ValueError('host_id %d not in roster of %d host(s)'
                             % (self.host_id, self.host_count))
        # sharded=None: manifest format whenever the roster has >1 host
        self.sharded = (self.host_count > 1) if sharded is None \
            else bool(sharded)
        if self.host_count > 1 and not self.sharded:
            raise ValueError('a multi-host roster requires sharded mode: '
                             'the legacy single-file format has no commit '
                             'protocol for %d writers' % self.host_count)
        self.lock_timeout_s = float(lock_timeout_s)
        # a .parts staging dir above the newest valid serial is normally
        # in flight; older than this, its writer is presumed dead
        self.stale_parts_s = None if stale_parts_s is None \
            else float(stale_parts_s)


class Checkpointer(object):
    """Periodic async checkpoint writer + newest-valid-checkpoint restorer."""

    def __init__(self, config, executor, main_program=None, scope=None,
                 quarantine=None):
        if isinstance(config, str):
            config = CheckpointConfig(config)
        self.config = config
        self.executor = executor
        self.main_program = main_program
        self.scope = scope
        # optional data_feeder.SampleQuarantine: its sample-index set
        # rides checkpoint META, so a resumed run never re-trips on a
        # sample forensics already condemned (RecoveryPolicy discovers
        # the quarantine through this attribute)
        self.quarantine = quarantine
        self._serial = -1
        self._q = queue.Queue()
        self._pending = 0
        self._cond = threading.Condition()
        self._thread = None
        self._write_error = None
        self._warned_write = False
        self._last_progress = None   # (epoch_id, step_id, extra_meta)
        self._prev_handlers = {}
        self._lockobj = None

    # --------------------------------------------------------------- save
    def _dir_of(self, serial):
        return os.path.join(self.config.checkpoint_dir,
                            'checkpoint_%d' % serial)

    def _scope(self):
        if self.scope is not None:
            return self.scope
        from ..core.executor import global_scope
        return global_scope()

    def dir_lock(self):
        """The ``ckpt.lock`` advisory lock serializing rotation, torn/
        partial sweeps, and manifest finalization across every process
        sharing this checkpoint dir."""
        if self._lockobj is None:
            self._lockobj = _DirLock(
                os.path.join(self.config.checkpoint_dir, _LOCKFILE),
                self.config.lock_timeout_s)
        return self._lockobj

    def _mesh_info(self):
        """Mesh layout riding the manifest: restore compares it against
        the CURRENT executor's mesh to detect (and count) a reshard."""
        mesh = getattr(self.executor, 'mesh', None)
        if mesh is None:
            return {'axes': [], 'shape': []}
        try:
            return {'axes': [str(a) for a in mesh.axis_names],
                    'shape': [int(s) for s in mesh.devices.shape]}
        except Exception:
            return {'axes': [], 'shape': []}

    def _sharding_info(self):
        """Per-var PartitionSpec annotations (Variable.sharding) in the
        canonical spec_to_jsonable form — placement metadata travels
        with the artifact, not in runtime state, so a differently-meshed
        restorer can adopt the specs verbatim (restore() writes them
        back onto the program, counted as ckpt.sharding_adopted)."""
        from ..core.sharding import normalize_spec, spec_to_jsonable
        prog = self.main_program
        sh = getattr(prog, '_sharding', None) if prog is not None else None
        if not sh:
            return {}
        out = {}
        for name, spec in sh.items():
            try:
                out[name] = spec_to_jsonable(normalize_spec(spec))
            except Exception:
                continue
        return out

    def note_progress(self, epoch_id, step_id, extra_meta=None):
        """Record where training is WITHOUT saving — the signal-flush
        handler checkpoints this position when a preemption lands between
        interval saves."""
        self._last_progress = (int(epoch_id), int(step_id), extra_meta)

    def maybe_save(self, epoch_id, step_id, extra_meta=None):
        """Save if the step/epoch intervals say so; returns the checkpoint
        dir or None.  Always records progress for the signal flush."""
        self.note_progress(epoch_id, step_id, extra_meta)
        if step_id % self.config.step_interval != 0 or \
                epoch_id % self.config.epoch_interval != 0:
            return None
        return self.save(epoch_id, step_id, extra_meta)

    def _snapshot(self):
        """Device → host copy of every persistable in scope.  The copy
        must be REAL (``np.array(copy=True)``), not ``np.asarray``: on
        the CPU backend a jax array exposes a ZERO-COPY numpy view of
        the XLA buffer, and the very next step DONATES that buffer —
        the background writer would serialize freed memory (observed as
        glibc heap corruption).  A forced copy makes the snapshot
        independent of donation, so the writer can run while training
        continues.

        Sharded mode copies only THIS host's row-slice (axis 0,
        ``[h*n//H, (h+1)*n//H)``; 0-d arrays belong to host 0), so the
        host-RAM pinned per queued snapshot scales as 1/H.  Returns
        ``(arrays, specs)`` — specs is None in legacy mode, else the
        global shape/dtype + slice bounds each shard was cut from.
        """
        scope = self._scope()
        if self.main_program is not None:
            names = [v.name for v in self.main_program.list_vars()
                     if v.persistable and v.name in scope]
        else:
            names = list(scope.keys())
        obs_on = _obs.enabled()
        t0 = time.perf_counter() if obs_on else None
        sharded = self.config.sharded
        h, H = self.config.host_id, self.config.host_count
        arrays, specs = {}, ({} if sharded else None)
        for n in names:
            src = scope.get(n)
            if not sharded:
                arrays[n] = np.array(src, copy=True)
                continue
            shape = tuple(int(x) for x in np.shape(src))
            if not shape:
                if h == 0:
                    arrays[n] = np.array(src, copy=True)
                    specs[n] = {'shape': [],
                                'dtype': str(arrays[n].dtype)}
                continue
            lo = shape[0] * h // H
            hi = shape[0] * (h + 1) // H
            if lo == hi:
                continue   # fewer rows than hosts: this host owns none
            arrays[n] = np.array(src[lo:hi], copy=True)
            specs[n] = {'shape': list(shape),
                        'dtype': str(arrays[n].dtype),
                        'start': lo, 'stop': hi}
        if obs_on:
            t1 = time.perf_counter()
            # host-memory accounting: each queued snapshot pins this many
            # bytes of host RAM until its background write drains
            nbytes = sum(a.nbytes for a in arrays.values())
            _obs.metrics.gauge('ckpt.snapshot_host_bytes').set(nbytes)
            _obs.metrics.counter('ckpt.snapshot_bytes_total').inc(nbytes)
            _obs.tracing.add_span('ckpt.snapshot', t0, t1,
                                  cat='ckpt', args={'arrays': len(arrays),
                                                    'bytes': nbytes})
            # the copies above are forced device->host reads (scope read):
            # they block on every in-flight launch that owns those
            # buffers — the one part of "async" checkpointing that can
            # still serialize the device, so it counts as host-blocked
            # time (core/async_runtime.host_block taxonomy)
            _obs.metrics.counter('executor.host_blocked_s').inc(t1 - t0)
            _obs.tracing.add_span('host_block', t0, t1, cat='launch',
                                  args={'reason': 'ckpt_snapshot',
                                        'arrays': len(arrays)})
        return arrays, specs

    def save(self, epoch_id, step_id, extra_meta=None, blocking=None):
        """Snapshot now, write in the background (unless ``blocking`` or
        the config says sync).  Returns the directory the checkpoint will
        land in; ``wait()`` guarantees it is on disk (in sharded mode:
        that THIS host's shard is on disk — the serial commits once the
        whole roster has landed)."""
        self.note_progress(epoch_id, step_id, extra_meta)
        self._raise_or_warn_write_error()
        cfg = self.config
        os.makedirs(cfg.checkpoint_dir, exist_ok=True)
        arrays, specs = self._snapshot()
        meta = {'epoch_id': int(epoch_id), 'step_id': int(step_id),
                'wall_time': time.time()}
        rng = getattr(self.executor, 'rng_state', None)
        if callable(rng):
            meta['rng_state'] = rng()
        if extra_meta:
            meta.update(extra_meta)
        if self.quarantine is not None:
            meta['quarantine'] = self.quarantine.state()
        if cfg.sharded:
            # step-derived serials: lockstep hosts converge on the same
            # dir with no communication, and stay monotonic across a
            # restore (the pre-training save(0, -1) lands at serial 0)
            serial = int(step_id) + 1
            self._serial = max(self._serial, serial)
        else:
            serial = self._serial + 1
            self._serial = serial
        final_dir = self._dir_of(serial)
        mesh_info = self._mesh_info() if cfg.sharded else None
        with self._cond:
            self._pending += 1
        self._q.put((serial, final_dir, arrays, meta, specs, mesh_info))
        if _obs.enabled():
            _obs.metrics.gauge('ckpt.async_queue_depth').set(self._q.qsize())
        self._ensure_thread()
        if blocking is None:
            blocking = not cfg.async_write
        if blocking:
            self.wait()
        return final_dir

    def _ensure_thread(self):
        # spawn and retire are both under _cond: a writer deciding to
        # retire and a save() that just enqueued can never miss each
        # other (retire re-checks the queue; spawn re-checks liveness)
        with self._cond:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._writer_loop, name='CheckpointWriter',
                    daemon=True)
                self._thread.start()

    def _writer_loop(self):
        while True:
            try:
                job = self._q.get(timeout=5.0)
            except queue.Empty:
                with self._cond:
                    if not self._q.empty():
                        continue   # a job slipped in: keep serving
                    self._thread = None   # retire; next save respawns
                    return
            try:
                self._write(*job)
            except Exception as e:  # noqa: BLE001 - surfaced via wait/save
                self._write_error = e
                _obs.metrics.counter('ckpt.write_failures').inc()
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()

    def _write(self, serial, final_dir, arrays, meta, specs=None,
               mesh_info=None):
        if specs is not None:
            return self._write_sharded(serial, final_dir, arrays, meta,
                                       specs, mesh_info)
        obs_on = _obs.enabled()
        t0 = time.perf_counter() if obs_on else None
        cfg = self.config
        # write to a temp dir then rename: a crash mid-write can never
        # leave a half-checkpoint that restore() would pick up
        tmp = tempfile.mkdtemp(dir=cfg.checkpoint_dir,
                               prefix='.tmp_ckpt_%d_' % os.getpid())
        try:
            def _tensors():
                _faults.maybe_fail('ckpt_io')
                np.savez(os.path.join(tmp, _ARRAYS), **arrays)
            # a transient disk blip must not cost a rotation slot: the
            # real writes retry with deterministic backoff (ckpt_io
            # rehearses exactly this); ckpt_write below stays OUTSIDE
            # the retry — it simulates a crash, not a blip
            retry_with_backoff(_tensors, name='ckpt.write')
            # torn-write rehearsal point: tensors on disk, marker not yet
            _faults.maybe_fail('ckpt_write')
            retry_with_backoff(
                lambda: _write_json_atomic(os.path.join(tmp, _META), meta),
                name='ckpt.meta')
            with open(os.path.join(tmp, _SUCCESS), 'w') as f:
                f.write('ok')
            with self.dir_lock():
                if os.path.isdir(final_dir):
                    shutil.rmtree(final_dir)
                os.rename(tmp, final_dir)
                self._rotate()
        except _faults.InjectedFault:
            # an injected fault simulates a CRASH mid-write: a crashed
            # process runs no cleanup, so the torn temp dir stays on disk
            # for the restore-time scan to collect — that scan path is
            # exactly what the fault exists to exercise
            raise
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if obs_on:
            t1 = time.perf_counter()
            _obs.metrics.counter('ckpt.saves').inc()
            _obs.metrics.counter('ckpt.save_s').inc(t1 - t0)
            _obs.metrics.counter('ckpt.bytes_written').inc(
                os.path.getsize(os.path.join(final_dir, _ARRAYS)))
            _obs.tracing.add_span('ckpt.write', t0, t1, cat='ckpt',
                                  args={'serial': serial,
                                        'step': meta.get('step_id')})

    # ---------------------------------------------------- sharded commit
    def _write_sharded(self, serial, final_dir, arrays, meta, specs,
                       mesh_info):
        """Land THIS host's shard in the serial's .parts staging dir,
        then try to finalize (the last roster member to land wins)."""
        obs_on = _obs.enabled()
        t0 = time.perf_counter() if obs_on else None
        cfg = self.config
        if os.path.exists(os.path.join(final_dir, _SUCCESS)):
            return   # already committed (a signal flush replayed a step)
        parts = final_dir + _PARTS
        os.makedirs(parts, exist_ok=True)
        h = cfg.host_id
        fname = _SHARD_NPZ % h
        fpath = os.path.join(parts, fname)

        def _tensors():
            _faults.maybe_fail('ckpt_io')
            tmpf = '%s.tmp%d' % (fpath, os.getpid())
            with open(tmpf, 'wb') as f:
                np.savez(f, **arrays)
            os.replace(tmpf, fpath)
        retry_with_backoff(_tensors, name='ckpt.shard_write')
        digest, nbytes = _sha256_file(fpath)
        # torn-write rehearsal: shard tensors on disk, sidecar not yet —
        # the serial can never finalize and must be swept as a unit
        _faults.maybe_fail('ckpt_write')
        if _faults.fire('host_desync', int(meta.get('step_id', 0))):
            # a drifted host: its sidecar claims a far-future step; the
            # finalize guard must refuse to commit the mixed serial
            meta = dict(meta,
                        step_id=int(meta.get('step_id', 0)) + _DESYNC_SKEW)
        sidecar = {'format': _FORMAT, 'serial': serial, 'host': h,
                   'host_count': cfg.host_count, 'file': fname,
                   'sha256': digest, 'bytes': nbytes, 'arrays': specs,
                   'meta': meta, 'mesh': mesh_info or {}}
        retry_with_backoff(
            lambda: _write_json_atomic(
                os.path.join(parts, _SHARD_META % h), sidecar),
            name='ckpt.shard_meta')
        if obs_on:
            _obs.metrics.counter('ckpt.shard_writes').inc()
            _obs.metrics.counter('ckpt.shard_bytes_written').inc(nbytes)
            _obs.tracing.add_span('ckpt.shard_write', t0,
                                  time.perf_counter(), cat='ckpt',
                                  args={'serial': serial, 'host': h,
                                        'bytes': nbytes})
        self._try_finalize(serial, final_dir, parts)

    def _try_finalize(self, serial, final_dir, parts):
        """Commit the serial if the whole roster has landed: verify every
        sidecar agrees on (roster, step), assemble MANIFEST.json, mark
        _SUCCESS, rename .parts into place — all under ckpt.lock, so
        concurrent finalizers and sweepers serialize.  Returns the final
        dir, or None while the roster is still incomplete."""
        cfg = self.config
        H = cfg.host_count
        with self.dir_lock():
            if os.path.exists(os.path.join(final_dir, _SUCCESS)):
                return final_dir   # a peer finalized first
            sidecars = []
            for hh in range(H):
                try:
                    with open(os.path.join(parts, _SHARD_META % hh)) as f:
                        m = json.load(f)
                except (OSError, ValueError):
                    return None   # roster incomplete: a peer still writing
                if m.get('format') != _FORMAT or \
                        int(m.get('host_count', -1)) != H:
                    return None   # sidecar from a different roster era
                sidecars.append(m)
            steps = sorted({int(m['meta'].get('step_id', -1))
                            for m in sidecars})
            if len(steps) > 1:
                # the roster disagrees on WHAT STEP this serial is —
                # committing would mix optimizer states across steps;
                # the torn serial is dropped as a unit
                _obs.metrics.counter('ckpt.desync_dropped').inc()
                _obs.metrics.counter('health.desyncs').inc()
                _flight.record('ckpt.desync', serial=serial, steps=steps)
                _flight.maybe_dump('ckpt_desync')
                shutil.rmtree(parts, ignore_errors=True)
                return None
            manifest = {
                'format': _FORMAT, 'serial': serial,
                'meta': sidecars[0]['meta'],
                'mesh': sidecars[0]['mesh'],
                'writers': list(range(H)),
                'sharding': self._sharding_info(),
                'files': {m['file']: {'host': m['host'],
                                      'sha256': m['sha256'],
                                      'bytes': m['bytes']}
                          for m in sidecars},
                'arrays': {},
            }
            for m in sidecars:
                for n, spec in m['arrays'].items():
                    g = manifest['arrays'].setdefault(
                        n, {'shape': spec['shape'], 'dtype': spec['dtype'],
                            'shards': []})
                    shard = {'host': m['host'], 'file': m['file']}
                    if 'start' in spec:
                        shard['start'] = spec['start']
                        shard['stop'] = spec['stop']
                    g['shards'].append(shard)
            # the committed dir is EXACTLY the manifest's contents: drop
            # strays (tmp files, shards from a dead larger roster)
            keep = set(manifest['files'])
            keep.update(_SHARD_META % hh for hh in range(H))
            for nm in os.listdir(parts):
                if nm in keep or nm in (_MANIFEST, _SUCCESS):
                    continue
                p = os.path.join(parts, nm)
                try:
                    shutil.rmtree(p) if os.path.isdir(p) else os.unlink(p)
                except OSError:
                    pass
            retry_with_backoff(
                lambda: _write_json_atomic(
                    os.path.join(parts, _MANIFEST), manifest),
                name='ckpt.manifest')
            with open(os.path.join(parts, _SUCCESS), 'w') as f:
                f.write('ok')
            if os.path.isdir(final_dir):
                shutil.rmtree(final_dir)
            os.rename(parts, final_dir)
            self._rotate()
        _obs.metrics.counter('ckpt.saves').inc()
        _obs.metrics.counter('ckpt.shard_manifests').inc()
        if _obs.enabled():
            _obs.metrics.counter('ckpt.bytes_written').inc(
                sum(rec['bytes'] for rec in manifest['files'].values()))
            _obs.tracing.instant(
                'ckpt.commit', cat='ckpt',
                args={'serial': serial, 'hosts': H,
                      'step': manifest['meta'].get('step_id')})
        return final_dir

    def wait(self, timeout=None):
        """Block until every queued write has hit disk (or failed)."""
        with self._cond:
            self._cond.wait_for(lambda: self._pending == 0, timeout=timeout)
        self._raise_or_warn_write_error()

    def _raise_or_warn_write_error(self):
        err, self._write_error = self._write_error, None
        if err is None:
            return
        if self.config.strict_writes:
            raise RuntimeError('checkpoint write failed') from err
        if not self._warned_write:
            self._warned_write = True
            warnings.warn('checkpoint write failed (%r); training continues '
                          'without it — the previous valid checkpoint is '
                          'still the restore point' % (err,))

    # ------------------------------------------------------------- scan
    def _serials(self, include_torn=False):
        d = self.config.checkpoint_dir
        if not os.path.isdir(d):
            return []
        out = []
        for name in os.listdir(d):
            if not name.startswith('checkpoint_') or name.endswith(_PARTS):
                continue
            try:
                s = int(name.split('_')[1])
            except (IndexError, ValueError):
                continue
            if include_torn or os.path.exists(os.path.join(d, name,
                                                           _SUCCESS)):
                out.append(s)
        return sorted(out)

    def _rotate(self):
        keep = self.config.max_num_checkpoints
        with self.dir_lock():
            serials = self._serials()
            for s in serials[:-keep] if keep > 0 else []:
                shutil.rmtree(self._dir_of(s), ignore_errors=True)

    def _sweep_torn(self):
        """Delete torn checkpoint dirs (no _SUCCESS), stale temp dirs,
        and dead .parts staging dirs.  Runs from restore() — after
        wait(), none of OUR writes are in flight, and a temp dir from a
        previous (killed) process is by definition dead.  A .parts dir
        is swept as a UNIT when its serial is already committed or
        superseded (<= the newest valid serial), or when it has gone
        ``stale_parts_s`` without progress — a live lockstep roster
        lands its shards within one step of each other."""
        d = self.config.checkpoint_dir
        if not os.path.isdir(d):
            return 0
        dropped = partial = 0
        with self.dir_lock():
            valid = set(self._serials())
            newest = max(valid) if valid else None
            for name in os.listdir(d):
                path = os.path.join(d, name)
                if name.startswith('.tmp_ckpt_'):
                    shutil.rmtree(path, ignore_errors=True)
                    dropped += 1
                elif name.startswith('checkpoint_') and \
                        name.endswith(_PARTS):
                    try:
                        s = int(name[len('checkpoint_'):-len(_PARTS)])
                    except ValueError:
                        continue
                    stale = newest is not None and s <= newest
                    if not stale and self.config.stale_parts_s is not None:
                        try:
                            age = time.time() - os.path.getmtime(path)
                        except OSError:
                            continue
                        stale = age > self.config.stale_parts_s
                    if stale:
                        shutil.rmtree(path, ignore_errors=True)
                        partial += 1
                elif name.startswith('checkpoint_'):
                    try:
                        s = int(name.split('_')[1])
                    except (IndexError, ValueError):
                        continue
                    if s not in valid:
                        shutil.rmtree(path, ignore_errors=True)
                        dropped += 1
        if dropped:
            _obs.metrics.counter('ckpt.torn_deleted').inc(dropped)
        if partial:
            _obs.metrics.counter('ckpt.partial_swept').inc(partial)
        return dropped + partial

    # ------------------------------------------------------------ restore
    def _load_legacy(self, ckpt, keep):
        with np.load(os.path.join(ckpt, _ARRAYS),
                     allow_pickle=False) as data:
            arrays = {n: data[n] for n in data.files
                      if keep is None or n in keep}
        with open(os.path.join(ckpt, _META)) as f:
            meta = json.load(f)
        return arrays, meta

    def _load_sharded(self, ckpt, keep):
        """Reassemble global arrays from a manifest checkpoint: verify
        every shard file against its SHA-256 FIRST (a flipped bit in any
        shard fails the whole serial), then fill each global array from
        its shards' recorded slice bounds.  The manifest's mesh/roster
        is compared with the CURRENT config — a mismatch is an elastic
        restore and counts ``ckpt.reshards``."""
        with open(os.path.join(ckpt, _MANIFEST)) as f:
            man = json.load(f)
        if man.get('format') != _FORMAT:
            raise ValueError('unknown manifest format %r'
                             % (man.get('format'),))
        for fname, rec in man['files'].items():
            digest, _ = _sha256_file(os.path.join(ckpt, fname))
            if digest != rec['sha256']:
                raise ValueError('checksum mismatch in %s' % fname)
        wanted = {n: rec for n, rec in man['arrays'].items()
                  if keep is None or n in keep}
        by_file = {}
        for n, rec in wanted.items():
            for sh in rec['shards']:
                by_file.setdefault(sh['file'], []).append((n, rec, sh))
        arrays = {}
        for fname, entries in by_file.items():
            with np.load(os.path.join(ckpt, fname),
                         allow_pickle=False) as data:
                for n, rec, sh in entries:
                    piece = data[n]
                    if n not in arrays:
                        # dtype from the data, not the manifest: extension
                        # dtypes (bfloat16) round-trip through npz but not
                        # through np.dtype(str)
                        arrays[n] = np.empty(tuple(rec['shape']),
                                             dtype=piece.dtype)
                    if arrays[n].ndim == 0:
                        arrays[n][()] = piece
                    else:
                        arrays[n][int(sh['start']):int(sh['stop'])] = piece
        meta = dict(man['meta'])
        # stash the manifest's placement table for restore() to adopt
        self._restored_sharding = man.get('sharding') or {}
        cur_mesh = self._mesh_info()
        cur_writers = list(range(self.config.host_count))
        if man.get('mesh') != cur_mesh or \
                man.get('writers') != cur_writers:
            _obs.metrics.counter('ckpt.reshards').inc()
            if _obs.enabled():
                _obs.tracing.instant(
                    'ckpt.reshard', cat='ckpt',
                    args={'from_mesh': man.get('mesh'),
                          'to_mesh': cur_mesh,
                          'from_hosts': len(man.get('writers') or []),
                          'to_hosts': self.config.host_count})
        return arrays, meta

    def _adopt_sharding(self):
        """Write the restored manifest's PartitionSpecs back onto the
        CURRENT program's vars (Variable.sharding, which syncs
        Program._sharding and re-arms the shard pass) — the placement an
        elastic restore resumes under is the one the artifact recorded,
        not whatever the fresh program happened to declare.  Returns the
        number of vars whose spec actually changed."""
        sh = getattr(self, '_restored_sharding', None)
        if not sh or self.main_program is None:
            return 0
        from ..core.sharding import normalize_spec, spec_from_jsonable
        block = self.main_program.global_block()
        adopted = 0
        for name, jsonable in sh.items():
            v = block._find_var_recursive(name)
            if v is None:
                continue
            try:
                spec = normalize_spec(spec_from_jsonable(jsonable))
            except Exception:
                continue
            if spec is None or v.sharding == spec:
                continue
            if v.shape is not None and len(spec) > len(v.shape):
                continue   # rank overflow: leave it to the D017 lint
            v.sharding = spec
            adopted += 1
        return adopted

    def restore(self):
        """Load the newest COMPLETE checkpoint (torn ones — no SUCCESS
        marker — are deleted, partial multi-host serials swept as a
        unit), put every array back in the scope, re-arm the executor's
        RNG/run counters, and return the meta dict (None if nothing to
        restore).  Both formats restore onto any config: a manifest
        checkpoint is reassembled and re-sliced for the current mesh/
        roster (elastic restore), a legacy one loads whole."""
        try:
            self.wait()
        except RuntimeError:
            pass   # strict-mode write error: restoring is still valid
        self._sweep_torn()
        scope = self._scope()
        keep = None
        if self.main_program is not None:
            keep = {v.name for v in self.main_program.list_vars()
                    if v.persistable}
        for s in reversed(self._serials()):
            ckpt = self._dir_of(s)
            self._restored_sharding = {}
            try:
                if os.path.exists(os.path.join(ckpt, _MANIFEST)):
                    arrays, meta = self._load_sharded(ckpt, keep)
                else:
                    arrays, meta = self._load_legacy(ckpt, keep)
            except Exception:
                # corrupt beyond the marker: fall back to the previous one
                _obs.metrics.counter('ckpt.corrupt_skipped').inc()
                continue
            for n, a in arrays.items():
                scope.set(n, a)
            adopted = self._adopt_sharding()
            if adopted:
                _obs.metrics.counter('ckpt.sharding_adopted').inc(adopted)
            rng = meta.get('rng_state')
            if rng and callable(getattr(self.executor, 'set_rng_state',
                                        None)):
                self.executor.set_rng_state(rng)
            q = meta.get('quarantine')
            if q and self.quarantine is not None:
                # union, never shrink: indices condemned after this
                # checkpoint was written stay condemned on rollback
                self.quarantine.restore(q)
            self._serial = s
            if _obs.enabled():
                _obs.metrics.counter('ckpt.restores').inc()
                _obs.tracing.instant('ckpt.restore', cat='ckpt',
                                     args={'serial': s,
                                           'step': meta.get('step_id')})
            return meta
        return None

    # ------------------------------------------------------------ signals
    def flush_final(self):
        """One blocking checkpoint at the last recorded progress (the
        signal handler's body; callable directly for tests)."""
        if self._last_progress is None:
            return None
        epoch_id, step_id, extra = self._last_progress
        return self.save(epoch_id, step_id, extra, blocking=True)

    def install_signal_handlers(self, signums=(_signal.SIGTERM,
                                               _signal.SIGINT)):
        """Arm a final-flush on SIGTERM/SIGINT, then chain to the previous
        handler (or re-deliver with the default handler, preserving the
        kill).  Installation goes through core/signals.py: idempotent —
        a second install (Trainer.train called again) never chains a
        handler to an older copy of itself — and main-thread-guarded
        (a serving worker thread calling this warns once and skips
        instead of crashing in ``signal.signal``).  The serving engine's
        drain handler composes by chaining: installed after this one, it
        drains first and then the checkpoint flush still runs."""

        def make(signum, prev):
            def _handler(s, frame):
                try:
                    self.flush_final()
                    _obs.metrics.counter('ckpt.signal_flushes').inc()
                finally:
                    _signals.chain_previous(prev, s, frame, redeliver=True)
            return _handler

        installed = _signals.install(('ckpt', id(self)), signums, make)
        if installed is None:
            return False
        self._prev_handlers.update(installed)
        return True

    def uninstall_signal_handlers(self):
        _signals.uninstall(('ckpt', id(self)))
        self._prev_handlers.clear()
