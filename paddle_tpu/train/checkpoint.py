"""Fault-tolerant training checkpoints: async preemption-safe writer,
newest-valid restorer, full bitwise-resume state.

Parity: reference python/paddle/fluid/trainer.py CheckpointConfig +
_save_checkpoint/_load_checkpoint (epoch/step metadata, rotation) and the
contrib fault-tolerance hooks — grown into a resilience primitive:

  * **Async writes.**  ``save()`` snapshots device state to host numpy
    (the only synchronous part — a device→host copy) and hands the write
    to a single background thread, so checkpointing never stalls the
    step loop on disk I/O.  ``wait()`` drains pending writes; a write
    failure is counted (``ckpt.write_failures``) and warned, not fatal —
    a lost checkpoint is survivable, a dead soak is not
    (``CheckpointConfig(strict_writes=True)`` restores raise-on-failure).
  * **Atomic + torn-proof.**  Each checkpoint is written into a temp dir
    and renamed into place with a ``_SUCCESS`` marker written last; a
    crash mid-write can never leave a half-checkpoint that ``restore()``
    would pick up.  ``restore()`` additionally DELETES torn directories
    (no marker) and stale temp dirs left by killed writers.
  * **Full resume state.**  META carries epoch/step, the executor's
    RNG/run-counter state (`Executor.rng_state`), caller ``extra_meta``
    (e.g. a FeedPrefetcher cursor), and a wall-clock stamp.  Restoring
    puts every persistable (params + optimizer accumulators + LR
    counters) back in the scope AND re-arms the run counters, so a
    resumed run continues **bitwise-identically** to an uninterrupted
    one — dropout masks and all (the counter fold-in RNG derivation
    makes the stream a pure function of saved state).
  * **Preemption flush.**  ``install_signal_handlers()`` arms SIGTERM/
    SIGINT to flush one final blocking checkpoint at the last recorded
    progress before the previous handler (or default death) runs.

Rotation keeps the newest ``max_num_checkpoints`` *valid* dirs.  The
``ckpt_write`` fault site (testing/faults.py) tears a write between the
tensor file and the marker, which is how the torn-scan path stays tested.
"""
import json
import os
import queue
import shutil
import signal as _signal
import tempfile
import threading
import time
import warnings

import numpy as np

from .. import observability as _obs
from ..core import signals as _signals
from ..testing import faults as _faults

__all__ = ['CheckpointConfig', 'Checkpointer']

_SUCCESS = '_SUCCESS'
_META = 'META'
_ARRAYS = '__params__.npz'   # same file the io.save_persistables path used


class CheckpointConfig(object):
    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10, async_write=True,
                 strict_writes=False, handle_signals=True):
        self.checkpoint_dir = checkpoint_dir or 'checkpoint'
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        self.async_write = bool(async_write)
        self.strict_writes = bool(strict_writes)
        # honored by owners that manage a training loop (contrib.Trainer):
        # arm the SIGTERM/SIGINT final-flush handlers on construction
        self.handle_signals = bool(handle_signals)


class Checkpointer(object):
    """Periodic async checkpoint writer + newest-valid-checkpoint restorer."""

    def __init__(self, config, executor, main_program=None, scope=None):
        if isinstance(config, str):
            config = CheckpointConfig(config)
        self.config = config
        self.executor = executor
        self.main_program = main_program
        self.scope = scope
        self._serial = -1
        self._q = queue.Queue()
        self._pending = 0
        self._cond = threading.Condition()
        self._thread = None
        self._write_error = None
        self._warned_write = False
        self._last_progress = None   # (epoch_id, step_id, extra_meta)
        self._prev_handlers = {}

    # --------------------------------------------------------------- save
    def _dir_of(self, serial):
        return os.path.join(self.config.checkpoint_dir,
                            'checkpoint_%d' % serial)

    def _scope(self):
        if self.scope is not None:
            return self.scope
        from ..core.executor import global_scope
        return global_scope()

    def note_progress(self, epoch_id, step_id, extra_meta=None):
        """Record where training is WITHOUT saving — the signal-flush
        handler checkpoints this position when a preemption lands between
        interval saves."""
        self._last_progress = (int(epoch_id), int(step_id), extra_meta)

    def maybe_save(self, epoch_id, step_id, extra_meta=None):
        """Save if the step/epoch intervals say so; returns the checkpoint
        dir or None.  Always records progress for the signal flush."""
        self.note_progress(epoch_id, step_id, extra_meta)
        if step_id % self.config.step_interval != 0 or \
                epoch_id % self.config.epoch_interval != 0:
            return None
        return self.save(epoch_id, step_id, extra_meta)

    def _snapshot(self):
        """Device → host copy of every persistable in scope.  The copy
        must be REAL (``np.array(copy=True)``), not ``np.asarray``: on
        the CPU backend a jax array exposes a ZERO-COPY numpy view of
        the XLA buffer, and the very next step DONATES that buffer —
        the background writer would serialize freed memory (observed as
        glibc heap corruption).  A forced copy makes the snapshot
        independent of donation, so the writer can run while training
        continues."""
        scope = self._scope()
        if self.main_program is not None:
            names = [v.name for v in self.main_program.list_vars()
                     if v.persistable and v.name in scope]
        else:
            names = list(scope.keys())
        obs_on = _obs.enabled()
        t0 = time.perf_counter() if obs_on else None
        arrays = {n: np.array(scope.get(n), copy=True) for n in names}
        if obs_on:
            # host-memory accounting: each queued snapshot pins this many
            # bytes of host RAM until its background write drains
            nbytes = sum(a.nbytes for a in arrays.values())
            _obs.metrics.gauge('ckpt.snapshot_host_bytes').set(nbytes)
            _obs.metrics.counter('ckpt.snapshot_bytes_total').inc(nbytes)
            _obs.tracing.add_span('ckpt.snapshot', t0, time.perf_counter(),
                                  cat='ckpt', args={'arrays': len(arrays),
                                                    'bytes': nbytes})
        return arrays

    def save(self, epoch_id, step_id, extra_meta=None, blocking=None):
        """Snapshot now, write in the background (unless ``blocking`` or
        the config says sync).  Returns the directory the checkpoint will
        land in; ``wait()`` guarantees it is on disk."""
        self.note_progress(epoch_id, step_id, extra_meta)
        self._raise_or_warn_write_error()
        cfg = self.config
        os.makedirs(cfg.checkpoint_dir, exist_ok=True)
        arrays = self._snapshot()
        meta = {'epoch_id': int(epoch_id), 'step_id': int(step_id),
                'wall_time': time.time()}
        rng = getattr(self.executor, 'rng_state', None)
        if callable(rng):
            meta['rng_state'] = rng()
        if extra_meta:
            meta.update(extra_meta)
        serial = self._serial + 1
        self._serial = serial
        final_dir = self._dir_of(serial)
        with self._cond:
            self._pending += 1
        self._q.put((serial, final_dir, arrays, meta))
        if _obs.enabled():
            _obs.metrics.gauge('ckpt.async_queue_depth').set(self._q.qsize())
        self._ensure_thread()
        if blocking is None:
            blocking = not cfg.async_write
        if blocking:
            self.wait()
        return final_dir

    def _ensure_thread(self):
        # spawn and retire are both under _cond: a writer deciding to
        # retire and a save() that just enqueued can never miss each
        # other (retire re-checks the queue; spawn re-checks liveness)
        with self._cond:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._writer_loop, name='CheckpointWriter',
                    daemon=True)
                self._thread.start()

    def _writer_loop(self):
        while True:
            try:
                job = self._q.get(timeout=5.0)
            except queue.Empty:
                with self._cond:
                    if not self._q.empty():
                        continue   # a job slipped in: keep serving
                    self._thread = None   # retire; next save respawns
                    return
            try:
                self._write(*job)
            except Exception as e:  # noqa: BLE001 - surfaced via wait/save
                self._write_error = e
                _obs.metrics.counter('ckpt.write_failures').inc()
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()

    def _write(self, serial, final_dir, arrays, meta):
        obs_on = _obs.enabled()
        t0 = time.perf_counter() if obs_on else None
        cfg = self.config
        # write to a temp dir then rename: a crash mid-write can never
        # leave a half-checkpoint that restore() would pick up
        tmp = tempfile.mkdtemp(dir=cfg.checkpoint_dir,
                               prefix='.tmp_ckpt_%d_' % os.getpid())
        try:
            np.savez(os.path.join(tmp, _ARRAYS), **arrays)
            # torn-write rehearsal point: tensors on disk, marker not yet
            _faults.maybe_fail('ckpt_write')
            with open(os.path.join(tmp, _META), 'w') as f:
                json.dump(meta, f)
            with open(os.path.join(tmp, _SUCCESS), 'w') as f:
                f.write('ok')
            if os.path.isdir(final_dir):
                shutil.rmtree(final_dir)
            os.rename(tmp, final_dir)
        except _faults.InjectedFault:
            # an injected fault simulates a CRASH mid-write: a crashed
            # process runs no cleanup, so the torn temp dir stays on disk
            # for the restore-time scan to collect — that scan path is
            # exactly what the fault exists to exercise
            raise
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._rotate()
        if obs_on:
            t1 = time.perf_counter()
            _obs.metrics.counter('ckpt.saves').inc()
            _obs.metrics.counter('ckpt.save_s').inc(t1 - t0)
            _obs.metrics.counter('ckpt.bytes_written').inc(
                os.path.getsize(os.path.join(final_dir, _ARRAYS)))
            _obs.tracing.add_span('ckpt.write', t0, t1, cat='ckpt',
                                  args={'serial': serial,
                                        'step': meta.get('step_id')})

    def wait(self, timeout=None):
        """Block until every queued write has hit disk (or failed)."""
        with self._cond:
            self._cond.wait_for(lambda: self._pending == 0, timeout=timeout)
        self._raise_or_warn_write_error()

    def _raise_or_warn_write_error(self):
        err, self._write_error = self._write_error, None
        if err is None:
            return
        if self.config.strict_writes:
            raise RuntimeError('checkpoint write failed') from err
        if not self._warned_write:
            self._warned_write = True
            warnings.warn('checkpoint write failed (%r); training continues '
                          'without it — the previous valid checkpoint is '
                          'still the restore point' % (err,))

    # ------------------------------------------------------------- scan
    def _serials(self, include_torn=False):
        d = self.config.checkpoint_dir
        if not os.path.isdir(d):
            return []
        out = []
        for name in os.listdir(d):
            if not name.startswith('checkpoint_'):
                continue
            try:
                s = int(name.split('_')[1])
            except (IndexError, ValueError):
                continue
            if include_torn or os.path.exists(os.path.join(d, name,
                                                           _SUCCESS)):
                out.append(s)
        return sorted(out)

    def _rotate(self):
        keep = self.config.max_num_checkpoints
        serials = self._serials()
        for s in serials[:-keep] if keep > 0 else []:
            shutil.rmtree(self._dir_of(s), ignore_errors=True)

    def _sweep_torn(self):
        """Delete torn checkpoint dirs (no _SUCCESS) and stale temp dirs.
        Runs from restore() — after wait(), none of OUR writes are in
        flight, and a temp dir from a previous (killed) process is by
        definition dead."""
        d = self.config.checkpoint_dir
        if not os.path.isdir(d):
            return 0
        dropped = 0
        valid = set(self._serials())
        for name in os.listdir(d):
            path = os.path.join(d, name)
            if name.startswith('.tmp_ckpt_'):
                shutil.rmtree(path, ignore_errors=True)
                dropped += 1
            elif name.startswith('checkpoint_'):
                try:
                    s = int(name.split('_')[1])
                except (IndexError, ValueError):
                    continue
                if s not in valid:
                    shutil.rmtree(path, ignore_errors=True)
                    dropped += 1
        if dropped:
            _obs.metrics.counter('ckpt.torn_deleted').inc(dropped)
        return dropped

    # ------------------------------------------------------------ restore
    def restore(self):
        """Load the newest COMPLETE checkpoint (torn ones — no SUCCESS
        marker — are deleted), put every array back in the scope, re-arm
        the executor's RNG/run counters, and return the meta dict (None
        if nothing to restore)."""
        try:
            self.wait()
        except RuntimeError:
            pass   # strict-mode write error: restoring is still valid
        self._sweep_torn()
        scope = self._scope()
        keep = None
        if self.main_program is not None:
            keep = {v.name for v in self.main_program.list_vars()
                    if v.persistable}
        for s in reversed(self._serials()):
            ckpt = self._dir_of(s)
            try:
                with np.load(os.path.join(ckpt, _ARRAYS),
                             allow_pickle=False) as data:
                    arrays = {n: data[n] for n in data.files
                              if keep is None or n in keep}
                with open(os.path.join(ckpt, _META)) as f:
                    meta = json.load(f)
            except Exception:
                # corrupt beyond the marker: fall back to the previous one
                _obs.metrics.counter('ckpt.corrupt_skipped').inc()
                continue
            for n, a in arrays.items():
                scope.set(n, a)
            rng = meta.get('rng_state')
            if rng and callable(getattr(self.executor, 'set_rng_state',
                                        None)):
                self.executor.set_rng_state(rng)
            self._serial = s
            if _obs.enabled():
                _obs.metrics.counter('ckpt.restores').inc()
                _obs.tracing.instant('ckpt.restore', cat='ckpt',
                                     args={'serial': s,
                                           'step': meta.get('step_id')})
            return meta
        return None

    # ------------------------------------------------------------ signals
    def flush_final(self):
        """One blocking checkpoint at the last recorded progress (the
        signal handler's body; callable directly for tests)."""
        if self._last_progress is None:
            return None
        epoch_id, step_id, extra = self._last_progress
        return self.save(epoch_id, step_id, extra, blocking=True)

    def install_signal_handlers(self, signums=(_signal.SIGTERM,
                                               _signal.SIGINT)):
        """Arm a final-flush on SIGTERM/SIGINT, then chain to the previous
        handler (or re-deliver with the default handler, preserving the
        kill).  Installation goes through core/signals.py: idempotent —
        a second install (Trainer.train called again) never chains a
        handler to an older copy of itself — and main-thread-guarded
        (a serving worker thread calling this warns once and skips
        instead of crashing in ``signal.signal``).  The serving engine's
        drain handler composes by chaining: installed after this one, it
        drains first and then the checkpoint flush still runs."""

        def make(signum, prev):
            def _handler(s, frame):
                try:
                    self.flush_final()
                    _obs.metrics.counter('ckpt.signal_flushes').inc()
                finally:
                    _signals.chain_previous(prev, s, frame, redeliver=True)
            return _handler

        installed = _signals.install(('ckpt', id(self)), signums, make)
        if installed is None:
            return False
        self._prev_handlers.update(installed)
        return True

    def uninstall_signal_handlers(self):
        _signals.uninstall(('ckpt', id(self)))
        self._prev_handlers.clear()
