"""NaN forensics: deterministic divergence bisection after a trip.

When RecoveryPolicy catches a ``check_nan`` trip — synchronous or a
deferred ``nan_window_steps`` window — it knows only that *some* step
since the last clean checkpoint went non-finite.  This module spends the
repo's bitwise rerun-determinism (counter-folded RNG streams, step-exact
checkpoints, deterministic fault injection) to turn that into a named
verdict, in three bisection phases:

  1. **steps** — replay the condemned window from the restored
     checkpoint one step at a time (the forensic runner is a
     single-step lowering, so every step gets a synchronous verdict —
     PT_NAN_POLL=1 semantics regardless of the production cadence);
  2. **ops** — the replay runner is lowered with a
     :class:`~paddle_tpu.core.executor.ForensicProbes` collector
     (``PT_FORENSIC`` probe variant): every op's inexact outputs carry a
     fused [all_finite, nonfinite_count, max_abs] probe, fetched as one
     stacked array per step.  The first false probe names the op, its
     output var and the D-style ``source_loc`` the analyzer stamped.
     The RAW program is lowered (no passes / emit / kernelgen), so
     fused groups are seen at sub-program granularity while the
     production path keeps its kernels — RNG parity is by construction,
     since optimized twins pin each op's raw position in
     ``rng_stream``;
  3. **batch rows** — the tripped step's (re-poisoned) feed is scanned
     on host for non-finite rows; when the poison is state-borne
     instead of data-borne, a bounded zero-substitution bisection over
     batch rows decides between "these rows did it" and "the state was
     already poisoned".

The verdict is a structured :class:`ForensicReport` attached to the
flight recorder (``forensics.report`` + a ``forensics`` dump trigger)
and the ``recovery.forensics_*`` metrics/spans.  RecoveryPolicy feeds
the named sample indices into the data plane's quarantine
(data_feeder.SampleQuarantine) — see docs/robustness.md.

Scope note: single-chip executors only (``exe.mesh is None``); a pod
trip aborts forensics (counted) and falls back to plain rollback.
"""
import os

import numpy as np

from .. import observability as _obs
from ..observability import flight as _flight
from ..observability import trace_context as _tc
from ..testing import faults as _faults

__all__ = ['LaunchRecord', 'ForensicReport', 'investigate', 'enabled']


def enabled():
    """PT_FORENSIC gate: on by default, ``PT_FORENSIC=0`` disables."""
    return os.environ.get('PT_FORENSIC', '1') not in ('0', 'false', 'False')


class LaunchRecord(object):
    """What RecoveryPolicy must remember about one launch to replay it:
    the program, the launch's feed (one per-step dict, a stacked
    superbatch dict, or a list of per-step dicts), the step count, the
    fetch list, and ``step0`` — the same step id the caller passes to
    ``checkpointer.save``/``maybe_save``, so the window can be aligned
    against the restored checkpoint's ``step_id``."""
    __slots__ = ('program', 'feed', 'steps', 'fetch_list', 'step0')

    def __init__(self, program, feed, steps, fetch_list, step0):
        self.program = program
        self.feed = feed
        self.steps = None if steps is None else int(steps)
        self.fetch_list = fetch_list
        self.step0 = int(step0)

    @property
    def nsteps(self):
        return 1 if self.steps is None else max(1, self.steps)


class ForensicReport(object):
    """Structured verdict of one forensic investigation."""

    def __init__(self):
        self.tripped = False         # did the replay reproduce the trip?
        self.step = None             # step id (caller convention) that tripped
        self.counter = None          # RNG/run counter of the tripped step
        self.window = []             # step ids replayed
        self.op_pos = None           # program position of the first bad op
        self.op_type = None
        self.var = None              # first non-finite output var
        self.source_loc = None       # D-style file:line from the analyzer
        self.nonfinite_count = None  # elements gone non-finite in that var
        self.max_abs_finite = None   # largest finite |x| in that var
        self.rows = None             # batch rows named (None: not data-borne)
        self.row_method = None       # 'feed_scan' | 'substitution' | 'state'
        self.sample_indices = None   # reader indices of the named rows
        self.batch_size = None
        self.replayed_steps = 0
        self.probe_launches = 0      # extra row-probe launches

    def to_dict(self):
        return {k: getattr(self, k) for k in (
            'tripped', 'step', 'counter', 'window', 'op_pos', 'op_type',
            'var', 'source_loc', 'nonfinite_count', 'max_abs_finite',
            'rows', 'row_method', 'sample_indices', 'batch_size',
            'replayed_steps', 'probe_launches')}

    def __repr__(self):
        if not self.tripped:
            return '<ForensicReport: trip not reproduced over window %s>' \
                % (self.window,)
        return ('<ForensicReport step=%s op=%s(%s) var=%s rows=%s '
                'samples=%s loc=%s>' % (self.step, self.op_type,
                                        self.op_pos, self.var, self.rows,
                                        self.sample_indices,
                                        self.source_loc))


def _per_step_feeds(exe, records):
    """Flatten window records into [(step_id, {name: np/dev array})] in
    launch order, unstacking superbatches and normalizing LoD feeds the
    way the original launches did."""
    out = []
    for rec in records:
        block = rec.program.global_block()
        if isinstance(rec.feed, (list, tuple)):
            per = [exe._normalize_feed(block, f) for f in rec.feed]
        elif rec.steps is None:
            per = [exe._normalize_feed(block, rec.feed)]
        else:
            stacked = {k: np.asarray(v) for k, v in rec.feed.items()}
            per = [{k: v[i] for k, v in stacked.items()}
                   for i in range(rec.nsteps)]
        for i, f in enumerate(per):
            out.append((rec.step0 + i, f))
    return out


def _batch_size(feed):
    """The consistent leading batch dim across this step's arrays, or
    None when the feed has no single batch axis to bisect over."""
    dims = {np.asarray(v).shape[0] for v in feed.values()
            if np.asarray(v).ndim >= 1}
    return dims.pop() if len(dims) == 1 else None


def _scan_feed_rows(feed, batch):
    """Host scan: batch rows carrying any non-finite float value."""
    bad = set()
    for v in feed.values():
        a = np.asarray(v)
        if a.ndim < 1 or a.shape[0] != batch or \
                not np.issubdtype(a.dtype, np.floating):
            continue
        flat = a.reshape(batch, -1)
        bad.update(int(r) for r in
                   np.nonzero(~np.isfinite(flat).all(axis=1))[0])
    return sorted(bad)


def _substitute_rows(feed, rows, batch):
    """Zero out the given batch rows of every float feed array — the
    substitution probe: if the step runs clean without these rows, the
    poison was data-borne and lived in them."""
    rows = list(rows)
    out = {}
    for k, v in feed.items():
        a = np.asarray(v)
        if np.issubdtype(a.dtype, np.floating) and a.ndim >= 1 and \
                a.shape[0] == batch:
            b = np.array(a, copy=True)
            b[rows] = 0
            out[k] = b
        else:
            out[k] = v
    return out


class _Runner(object):
    """One compiled forensic probe executable, reused for every replayed
    step and row probe of an investigation (same shapes -> one trace)."""

    def __init__(self, exe, program, feed_names, fetch_names):
        from ..core import executor as _ex
        self.exe = exe
        self.program = program
        self.collector = _ex.ForensicProbes()
        # RAW program, no emit engine, no donation: maximum probe
        # granularity (fused groups replay their sub-ops), per-op
        # source_loc intact, and a pure step we can re-run at will.
        # check_nan keeps the production trip criterion as output #3.
        self.fn, self.params_in, self.writeback = _ex._lower(
            program, tuple(feed_names), tuple(fetch_names),
            donate=False, mesh=None, check_nan=True, steps=None,
            forensic=self.collector)

    def step(self, scope, feed, counter):
        """Run one probed step.  Returns (ok, probes, updates)."""
        params = self.exe._gather_params(self.program, self.params_in,
                                         scope, None)
        fetches, updates, ok, probes = self.fn(
            params, feed, np.uint32(int(counter) & 0xffffffff))
        return bool(ok), np.asarray(probes), updates


def investigate(checkpointer, records, meta=None, sample_index_of=None,
                max_row_probes=24):
    """Replay the condemned window from the restored checkpoint and name
    the first non-finite op, step and (when data-borne) batch rows.

    Preconditions: the caller (RecoveryPolicy.rollback) has ALREADY
    restored the checkpoint ``meta`` describes — scope and RNG counters
    sit at the window's start.  On return the checkpoint is restored
    AGAIN, so the investigation's own state advances never leak into
    the resumed run.  Returns a ForensicReport, or None when forensics
    cannot run here (no executor, a pod mesh, a window that does not
    align with the restored step)."""
    exe = getattr(checkpointer, 'executor', None)
    if exe is None or not records:
        return None
    if getattr(exe, 'mesh', None) is not None:
        _obs.metrics.counter('recovery.forensics_aborted').inc()
        _flight.record('forensics.aborted', reason='mesh')
        return None
    if meta is None:
        _obs.metrics.counter('recovery.forensics_aborted').inc()
        _flight.record('forensics.aborted', reason='no_meta')
        return None
    ckpt_step = int(meta.get('step_id', -1))
    live = [r for r in records if r.step0 + r.nsteps - 1 > ckpt_step]
    if not live or live[0].step0 != ckpt_step + 1:
        # the buffered window has a gap against the restored checkpoint
        # (records rotated out, or a save landed mid-window without the
        # caller pruning) — replaying would mis-align RNG streams
        _obs.metrics.counter('recovery.forensics_aborted').inc()
        _flight.record('forensics.aborted', reason='window_gap',
                       ckpt_step=ckpt_step,
                       window=[r.step0 for r in records])
        return None

    scope = checkpointer._scope()
    program = live[0].program
    fetch_names = tuple(exe._resolve_fetch(live[0].fetch_list))
    steps = _per_step_feeds(exe, live)
    feed_names = tuple(sorted(steps[0][1]))
    # the restore re-armed the stream's counter at the window start: the
    # i-th replayed step consumes exactly the counter the original did
    ctr0 = exe.stream_counter(feed_names, fetch_names)

    report = ForensicReport()
    report.window = [s for s, _ in steps]
    _obs.metrics.counter('recovery.forensics_runs').inc()

    with _tc.root_span('recovery.forensics', cat='recovery',
                       args={'window_steps': len(steps),
                             'ckpt_step': ckpt_step}):
        try:
            runner = _Runner(exe, program, feed_names, fetch_names)
            with _faults.forensic_replay():
                _bisect(runner, scope, steps, ctr0, report,
                        sample_index_of, max_row_probes)
        finally:
            # leave no trace: the investigation advanced scope state up
            # to the poisoned step — put everything back as rollback left
            # it before the resumed run continues
            checkpointer.restore()
            if hasattr(exe, 'reset_nan_window'):
                exe.reset_nan_window()

    _obs.metrics.counter(
        'recovery.forensics_named' if report.tripped
        else 'recovery.forensics_unattributed').inc()
    _flight.record('forensics.report', **report.to_dict())
    _flight.maybe_dump('forensics')
    _obs.tracing.instant(
        'forensics.verdict', cat='recovery',
        args={'step': report.step, 'op': report.op_type,
              'var': report.var, 'rows': report.rows})
    return report


def _bisect(runner, scope, steps, ctr0, report, sample_index_of,
            max_row_probes):
    """Phases 1-3 against a prepared runner; fills ``report`` in place."""
    for i, (step_id, feed) in enumerate(steps):
        ctr = ctr0 + i
        # reproduce the original poison: the nan_step site replays its
        # armed window without consuming budget (forensic_replay ctx)
        pfeed = _faults.poison_nan(dict(feed), ctr, 1)
        ok, probes, updates = runner.step(scope, pfeed, ctr)
        report.replayed_steps += 1
        _obs.metrics.counter('recovery.forensics_replay_steps').inc()
        if ok:
            # clean step: commit its updates so the next replayed step
            # sees exactly the state the original run gave it
            for n, v in updates.items():
                scope.vars[n] = v
            continue
        # ---- phase 1 verdict: this is the step -----------------------
        report.tripped = True
        report.step = int(step_id)
        report.counter = int(ctr)
        # ---- phase 2: first false probe names the op -----------------
        meta = runner.collector.meta
        if probes.shape[0] == len(meta):
            for j in range(probes.shape[0]):
                if probes[j, 0] < 0.5:
                    m = meta[j]
                    report.op_pos = m['pos']
                    report.op_type = m['op_type']
                    report.var = m['var']
                    report.source_loc = m['source_loc']
                    report.nonfinite_count = int(probes[j, 1])
                    report.max_abs_finite = float(probes[j, 2])
                    break
        # ---- phase 3: batch rows -------------------------------------
        _bisect_rows(runner, scope, pfeed, ctr, report, step_id,
                     sample_index_of, max_row_probes)
        return
    # window replayed clean end to end: the trip did not reproduce
    # (non-deterministic hardware fault, or state the checkpoint already
    # cleaned) — report it as such rather than inventing a culprit
    report.tripped = False


def _bisect_rows(runner, scope, pfeed, ctr, report, step_id,
                 sample_index_of, max_row_probes):
    from ..data_feeder import default_sample_index
    index_of = sample_index_of or default_sample_index
    batch = _batch_size(pfeed)
    report.batch_size = batch
    if batch is None or batch < 1:
        report.row_method = 'no_batch_axis'
        return
    # fast path: the poison is visible in the (re-poisoned) feed itself
    rows = _scan_feed_rows(pfeed, batch)
    if rows:
        report.rows = rows
        report.row_method = 'feed_scan'
        report.sample_indices = [int(index_of(step_id, r, batch))
                                 for r in rows]
        return
    # substitution probes: does removing rows clean the step?
    budget = [int(max_row_probes)]

    def clean_without(rows_out):
        if budget[0] <= 0:
            raise _BudgetSpent()
        budget[0] -= 1
        report.probe_launches += 1
        _obs.metrics.counter('recovery.forensics_probes').inc()
        ok, _, _ = runner.step(
            scope, _substitute_rows(pfeed, rows_out, batch), ctr)
        return ok

    try:
        if not clean_without(range(batch)):
            # even a fully-neutralized batch trips: the poison is in the
            # carried state (params/optimizer), not in this batch's data
            report.rows = None
            report.row_method = 'state'
            return
        culprits = _delta_rows(list(range(batch)), [], clean_without)
    except _BudgetSpent:
        report.row_method = 'substitution_budget_spent'
        return
    report.rows = sorted(int(r) for r in culprits)
    report.row_method = 'substitution'
    report.sample_indices = [int(index_of(step_id, r, batch))
                             for r in report.rows]


class _BudgetSpent(Exception):
    pass


def _delta_rows(cand, fixed, clean_without):
    """Minimal culprit set by recursive halving.  Invariant: substituting
    ``cand + fixed`` runs clean.  Returns the rows of ``cand`` that must
    stay substituted (culprits may live in both halves)."""
    if len(cand) <= 1:
        return list(cand)
    mid = len(cand) // 2
    left, right = cand[:mid], cand[mid:]
    if clean_without(left + fixed):
        return _delta_rows(left, fixed, clean_without)
    if clean_without(right + fixed):
        return _delta_rows(right, fixed, clean_without)
    lf = _delta_rows(left, right + fixed, clean_without)
    rf = _delta_rows(right, lf + fixed, clean_without)
    return lf + rf
