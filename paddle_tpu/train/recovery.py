"""Divergence guard: detect a blown-up step, roll back, skip, retry.

A NaN/Inf step (the executor's fused ``check_nan`` verdict) or a loss
spike (this module's heuristic) used to simply raise and kill the run —
hours of soak lost to one bad superbatch.  `RecoveryPolicy` turns the
raise into a bounded recovery loop:

  1. **rollback** — restore the last good checkpoint (params + optimizer
     accumulators + RNG counters, via train/checkpoint.py), so the model
     never trains on top of poisoned state;
  2. **skip** — the offending superbatch is dropped (`run()` returns
     None and the caller moves to the next batch);
  3. **dampen** — optionally scale a named LR variable down;
  4. **give up** — after ``max_retries`` consecutive divergences the
     original exception re-raises: a systematically-diverging run should
     die loudly, not loop forever.

Every action is counted in observability (``recovery.*`` — see
docs/robustness.md) so a "healthy" run that silently rolled back 50
times is visible for what it is.
"""
import numpy as np

from .. import observability as _obs
from ..observability import flight as _flight
from ..observability import trace_context as _tc

__all__ = ['DivergenceError', 'RecoveryPolicy', 'is_divergence']


class DivergenceError(RuntimeError):
    """Training diverged per the loss-spike heuristic (check_nan failures
    arrive as the executor's own RuntimeError)."""


def _is_device_loss(exc):
    # lazy: train/ must not drag the parallel package (jax mesh imports)
    # in at module load just to classify an exception
    from ..parallel.health import DeviceLossError
    return isinstance(exc, DeviceLossError)


def is_divergence(exc):
    """Is this exception a numeric divergence the policy may absorb?
    Anything else (shape errors, OOM, bugs) must propagate untouched."""
    if isinstance(exc, (DivergenceError, FloatingPointError)):
        return True
    return isinstance(exc, RuntimeError) and \
        str(exc).startswith('check_nan')


class RecoveryPolicy(object):
    """Wrap each training launch: ``out = policy.run(lambda: exe.run(...))``.
    ``None`` means "this superbatch was rolled back and skipped — feed me
    the next one"."""

    def __init__(self, checkpointer, max_retries=3, lr_var=None,
                 lr_scale=None, spike_factor=None, window=32, min_history=5):
        if checkpointer is None:
            raise ValueError('RecoveryPolicy needs a Checkpointer to roll '
                             'back to')
        self.checkpointer = checkpointer
        self.max_retries = max(1, int(max_retries))
        self.lr_var = lr_var
        self.lr_scale = lr_scale
        self.spike_factor = float(spike_factor) if spike_factor else None
        self.window = max(2, int(window))
        self.min_history = max(2, int(min_history))
        self._history = []
        self._consecutive = 0

    # ------------------------------------------------------------ heuristic
    def check_loss(self, loss):
        """Loss-spike divergence heuristic: a finite-history median sets
        the scale; a loss beyond ``spike_factor`` times it (plus a small
        absolute floor, so near-zero-loss runs don't trip on noise)
        raises DivergenceError.  Non-finite losses always diverge."""
        v = float(np.max(np.asarray(loss, dtype=np.float64)))
        if not np.isfinite(v):
            raise DivergenceError(
                'loss is non-finite (%r) — training diverged' % v)
        if self.spike_factor and len(self._history) >= self.min_history:
            ref = float(np.median(self._history))
            limit = self.spike_factor * max(abs(ref), 1e-6)
            if v > limit:
                raise DivergenceError(
                    'loss spike: %.6g > %.3g x median(%.6g) over the last '
                    '%d steps' % (v, self.spike_factor, ref,
                                  len(self._history)))
        self._history.append(v)
        if len(self._history) > self.window:
            self._history.pop(0)

    # -------------------------------------------------------------- driver
    def run(self, fn, loss_index=0):
        """Run one launch.  Returns its fetches, or None when the launch
        diverged and was rolled back (the caller skips the superbatch).
        Re-raises after ``max_retries`` consecutive divergences, and
        re-raises immediately for non-divergence errors."""
        try:
            out = fn()
            if out and loss_index is not None and self.spike_factor:
                self.check_loss(out[loss_index])
            self._consecutive = 0
            return out
        except Exception as e:  # noqa: BLE001 - filtered right below
            if _is_device_loss(e):
                # a pod fault, not a divergence: the mesh this run was
                # compiled for no longer exists, so skipping-and-continuing
                # is meaningless.  Roll the scope back to the last good
                # manifest (so the NEXT incarnation restores clean state
                # even if this process's shards were mid-write) and
                # re-raise — the supervisor restarts on a smaller mesh
                # (parallel/health.py RESTART_EXIT_CODE protocol).
                _obs.metrics.counter('recovery.device_loss').inc()
                self.rollback(reason=repr(e)[:200])
                raise
            if not is_divergence(e):
                raise
            self._consecutive += 1
            _obs.metrics.counter('recovery.divergences').inc()
            window = int(getattr(e, 'nan_window_steps', 0) or 0)
            if window > 1:
                # a DEFERRED verdict poll tripped (executor nan_poll > 1):
                # the divergence is localized to the last `window` steps,
                # not one step — the rollback below restores the last
                # checkpoint saved before that window (nan_clean-aligned
                # saves guarantee it predates the poison)
                _obs.metrics.counter('recovery.deferred_trips').inc()
                _flight.record('recovery.deferred_trip',
                               window_steps=window)
            if self._consecutive > self.max_retries:
                _obs.metrics.counter('recovery.giveups').inc()
                _flight.record('recovery.giveup', error=repr(e)[:300],
                               consecutive=self._consecutive)
                # the re-raise kills the run; leave the postmortem behind
                _flight.maybe_dump('recovery_giveup')
                raise
            self.rollback(reason=repr(e)[:200])
            _obs.metrics.counter('recovery.skipped_steps').inc()
            return None

    def rollback(self, reason=''):
        """Restore the last good checkpoint into the scope (+ RNG/run
        counters) and optionally scale the LR down.  Raises if there is
        no valid checkpoint — recovery without a restore point would mean
        silently training on poisoned state."""
        with _tc.root_span('recovery.rollback', cat='recovery',
                           args={'reason': reason}):
            meta = self.checkpointer.restore()
            if meta is None:
                _obs.metrics.counter('recovery.no_checkpoint').inc()
                raise RuntimeError(
                    'divergence recovery failed: no valid checkpoint to '
                    'roll back to (save one before training starts)')
            _obs.metrics.counter('recovery.rollbacks').inc()
            _obs.tracing.instant('recovery.rollback', cat='recovery',
                                 args={'to_step': meta.get('step_id'),
                                       'reason': reason})
            if self.lr_var and self.lr_scale:
                scope = self.checkpointer._scope()
                if self.lr_var in scope:
                    lr = np.asarray(scope.get(self.lr_var))
                    scope.set(self.lr_var,
                              (lr * self.lr_scale).astype(lr.dtype))
                    _obs.metrics.counter('recovery.lr_scaled').inc()
        # drop any verdicts still accumulating on device: they were
        # computed over the poisoned (pre-restore) stream and would trip
        # a later poll against the clean restored state
        exe = getattr(self.checkpointer, 'executor', None)
        if exe is not None and hasattr(exe, 'reset_nan_window'):
            exe.reset_nan_window()
        # the restore + replay window is an intentional gap, not a stall:
        # forget the launch-gap baseline so the first replayed launch is
        # not measured against the pre-rollback timeline
        _obs.stall.clear_window(exe)
        # divergences survive rollback history: a spike right after a
        # rollback should still count toward give-up, but the loss
        # history predates the poisoned step and stays valid
        return meta
