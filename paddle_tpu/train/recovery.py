"""Divergence guard: detect a blown-up step, explain it, heal, retry.

A NaN/Inf step (the executor's fused ``check_nan`` verdict) or a loss
spike (this module's heuristic) used to simply raise and kill the run —
hours of soak lost to one bad superbatch.  `RecoveryPolicy` turns the
raise into a bounded recovery loop.  After every divergence the policy
rolls back to the last good checkpoint (params + optimizer accumulators
+ RNG counters, via train/checkpoint.py), runs forensics on the
condemned window when it can (train/forensics.py — the caller passed
``launch=`` records), then climbs an **escalation ladder**, each rung
metered under ``recovery.escalation.*``:

  1. **quarantine-sample** — forensics named poison batch rows: their
     reader indices go into the data plane's quarantine
     (data_feeder.SampleQuarantine, persisted in checkpoint META) and
     the whole window is REPLAYED with those rows substituted — no data
     is skipped, the run continues as if the poison never existed;
  2. **skip-batch** — no named sample (or the quarantine replay
     re-tripped): the offending superbatch is dropped (`run()` returns
     None and the caller moves to the next batch);
  3. **LR-scale** — divergences keep coming (``lr_after`` consecutive):
     scale the named LR variable down on rollback;
  4. **give up** — after ``max_retries`` consecutive divergences the
     original exception re-raises with a flight dump: a systematically-
     diverging run should die loudly, not loop forever.

Every action is counted in observability (``recovery.*`` — see
docs/robustness.md) so a "healthy" run that silently rolled back 50
times is visible for what it is.
"""
import numpy as np

from .. import observability as _obs
from ..observability import flight as _flight
from ..observability import trace_context as _tc

__all__ = ['DivergenceError', 'RecoveryPolicy', 'is_divergence']

# sentinel: the quarantine-replay rung failed (distinct from a launch
# legitimately returning None)
_REPLAY_FAILED = object()


class DivergenceError(RuntimeError):
    """Training diverged per the loss-spike heuristic (check_nan failures
    arrive as the executor's own RuntimeError)."""


def _is_device_loss(exc):
    # lazy: train/ must not drag the parallel package (jax mesh imports)
    # in at module load just to classify an exception
    from ..parallel.health import DeviceLossError
    return isinstance(exc, DeviceLossError)


def is_divergence(exc):
    """Is this exception a numeric divergence the policy may absorb?
    Anything else (shape errors, OOM, bugs) must propagate untouched."""
    if isinstance(exc, (DivergenceError, FloatingPointError)):
        return True
    return isinstance(exc, RuntimeError) and \
        str(exc).startswith('check_nan')


class RecoveryPolicy(object):
    """Wrap each training launch: ``out = policy.run(lambda: exe.run(...))``.
    ``None`` means "this superbatch was rolled back and skipped — feed me
    the next one"."""

    def __init__(self, checkpointer, max_retries=3, lr_var=None,
                 lr_scale=None, spike_factor=None, window=32, min_history=5,
                 quarantine=None, forensics=None, sample_index_of=None,
                 lr_after=2, max_window_records=64):
        if checkpointer is None:
            raise ValueError('RecoveryPolicy needs a Checkpointer to roll '
                             'back to')
        self.checkpointer = checkpointer
        self.max_retries = max(1, int(max_retries))
        self.lr_var = lr_var
        self.lr_scale = lr_scale
        self.spike_factor = float(spike_factor) if spike_factor else None
        self.window = max(2, int(window))
        self.min_history = max(2, int(min_history))
        self._history = []
        self._consecutive = 0
        # ---- forensics / escalation-ladder state -------------------------
        # the quarantine usually rides the Checkpointer (META persistence);
        # an explicit one wins
        self.quarantine = quarantine if quarantine is not None \
            else getattr(checkpointer, 'quarantine', None)
        if forensics is None:
            from . import forensics as _forensics
            forensics = _forensics.enabled()
        self.forensics = bool(forensics)
        self.sample_index_of = sample_index_of
        # LR-scale rung: dampen only from the lr_after-th CONSECUTIVE
        # divergence on — the first trip gets quarantine/skip a chance
        # to heal at full speed
        self.lr_after = max(1, int(lr_after))
        self.max_window_records = max(1, int(max_window_records))
        self._window_records = []   # LaunchRecords since the last checkpoint
        self.last_report = None     # most recent ForensicReport
        self.last_replay = None     # [(step0, steps, out)] from rung 1

    # ------------------------------------------------------------ heuristic
    def check_loss(self, loss):
        """Loss-spike divergence heuristic: a finite-history median sets
        the scale; a loss beyond ``spike_factor`` times it (plus a small
        absolute floor, so near-zero-loss runs don't trip on noise)
        raises DivergenceError.  Non-finite losses always diverge."""
        v = float(np.max(np.asarray(loss, dtype=np.float64)))
        if not np.isfinite(v):
            raise DivergenceError(
                'loss is non-finite (%r) — training diverged' % v)
        if self.spike_factor and len(self._history) >= self.min_history:
            ref = float(np.median(self._history))
            limit = self.spike_factor * max(abs(ref), 1e-6)
            if v > limit:
                raise DivergenceError(
                    'loss spike: %.6g > %.3g x median(%.6g) over the last '
                    '%d steps' % (v, self.spike_factor, ref,
                                  len(self._history)))
        self._history.append(v)
        if len(self._history) > self.window:
            self._history.pop(0)

    # -------------------------------------------------------------- driver
    def note_checkpoint(self, step_id):
        """Tell the policy a checkpoint covering steps <= ``step_id``
        landed: buffered launch records at or before it can never be
        condemned again and are dropped.  Callers that pass ``launch=``
        should call this after every ``save``/``maybe_save`` hit so the
        forensic window stays aligned with the restore point."""
        s = int(step_id)
        self._window_records = [r for r in self._window_records
                                if r.step0 + r.nsteps - 1 > s]

    def run(self, fn, loss_index=0, launch=None):
        """Run one launch.  Returns its fetches, or None when the launch
        diverged and was rolled back AND skipped (the caller drops the
        whole in-flight window and moves to the next batch).  When the
        quarantine rung heals the window instead, the CURRENT launch's
        fetches are returned and ``last_replay`` holds every replayed
        launch's output.  Re-raises after ``max_retries`` consecutive
        divergences, and immediately for non-divergence errors.

        ``launch`` (a forensics.LaunchRecord) opts this launch into the
        forensic window: without records the policy degrades to plain
        rollback-and-skip."""
        self.last_replay = None
        if launch is not None:
            self._window_records.append(launch)
            if len(self._window_records) > self.max_window_records:
                # bounded buffer: an over-long window aborts forensics
                # (window_gap) rather than replaying from a wrong base
                self._window_records.pop(0)
        try:
            out = fn()
            if out and loss_index is not None and self.spike_factor:
                self.check_loss(out[loss_index])
            self._consecutive = 0
            return out
        except Exception as e:  # noqa: BLE001 - filtered right below
            if _is_device_loss(e):
                # a pod fault, not a divergence: the mesh this run was
                # compiled for no longer exists, so skipping-and-continuing
                # is meaningless.  Roll the scope back to the last good
                # manifest (so the NEXT incarnation restores clean state
                # even if this process's shards were mid-write) and
                # re-raise — the supervisor restarts on a smaller mesh
                # (parallel/health.py RESTART_EXIT_CODE protocol).
                _obs.metrics.counter('recovery.device_loss').inc()
                self.rollback(reason=repr(e)[:200])
                raise
            if not is_divergence(e):
                raise
            self._consecutive += 1
            _obs.metrics.counter('recovery.divergences').inc()
            window = int(getattr(e, 'nan_window_steps', 0) or 0)
            if window > 1:
                # a DEFERRED verdict poll tripped (executor nan_poll > 1):
                # the divergence is localized to the last `window` steps,
                # not one step — the rollback below restores the last
                # checkpoint saved before that window (nan_clean-aligned
                # saves guarantee it predates the poison)
                _obs.metrics.counter('recovery.deferred_trips').inc()
                _flight.record('recovery.deferred_trip',
                               window_steps=window)
            if self._consecutive > self.max_retries:
                _obs.metrics.counter('recovery.giveups').inc()
                _obs.metrics.counter('recovery.escalation.giveup').inc()
                _flight.record('recovery.giveup', error=repr(e)[:300],
                               consecutive=self._consecutive)
                # the re-raise kills the run; leave the postmortem behind
                _flight.maybe_dump('recovery_giveup')
                raise
            meta = self.rollback(reason=repr(e)[:200])
            report = self._investigate(meta)
            # ---- rung 1: quarantine-sample + heal the window ----------
            if report is not None and report.sample_indices and \
                    self.quarantine is not None and self._consecutive == 1:
                self.quarantine.add(report.sample_indices,
                                    reason='forensics step %s'
                                    % report.step)
                out = self._replay_window()
                if out is not _REPLAY_FAILED:
                    _obs.metrics.counter(
                        'recovery.escalation.quarantine').inc()
                    _obs.tracing.instant(
                        'recovery.quarantine_heal', cat='recovery',
                        args={'samples': report.sample_indices,
                              'step': report.step})
                    self._consecutive = 0
                    return out
                # the replay re-tripped with the rows substituted: the
                # verdict was wrong or incomplete — roll back again and
                # fall through to skip-batch
                _obs.metrics.counter(
                    'recovery.escalation.quarantine_failed').inc()
                self.rollback(reason='quarantine replay re-tripped')
            # ---- rung 2: skip-batch -----------------------------------
            _obs.metrics.counter('recovery.escalation.skip').inc()
            _obs.metrics.counter('recovery.skipped_steps').inc()
            # the caller drops the in-flight window on None: those
            # launches will never be replayed, so their records are dead
            self._window_records = []
            return None

    def _investigate(self, meta):
        """Run forensics over the buffered window (best-effort: a
        forensics bug must never turn a recoverable divergence into a
        crash)."""
        if not self.forensics or not self._window_records or meta is None:
            return None
        from . import forensics as _forensics
        try:
            report = _forensics.investigate(
                self.checkpointer, list(self._window_records), meta=meta,
                sample_index_of=self.sample_index_of)
        except Exception as fe:   # noqa: BLE001 - forensics is best-effort
            _obs.metrics.counter('recovery.forensics_errors').inc()
            _flight.record('forensics.error', error=repr(fe)[:300])
            return None
        if report is not None:
            self.last_report = report
        return report

    def _replay_window(self):
        """Rung 1's heal: re-run every buffered launch from the restored
        checkpoint with quarantined rows substituted out of the feeds.
        Returns the LAST launch's fetches (what the condemned call would
        have returned) or _REPLAY_FAILED when the window re-trips."""
        exe = getattr(self.checkpointer, 'executor', None)
        if exe is None:
            return _REPLAY_FAILED
        scope = self.checkpointer.scope
        self.last_replay = []
        out = None
        try:
            # the replay is an intentional slow window (sync fetches,
            # no prefetch): launch gaps inside it are not pipeline stalls
            with _obs.stall.suppress('quarantine_replay'):
                for rec in self._window_records:
                    feed = rec.feed
                    if self.quarantine is not None and len(self.quarantine):
                        feed, _ = self.quarantine.apply(
                            feed, rec.step0, rec.steps or 1)
                    if rec.steps is None:
                        out = exe.run(rec.program, feed=feed,
                                      fetch_list=rec.fetch_list,
                                      scope=scope)
                    else:
                        out = exe.run_steps(rec.program, feed_list=feed,
                                            steps=rec.steps,
                                            fetch_list=rec.fetch_list,
                                            scope=scope)
                    self.last_replay.append((rec.step0, rec.nsteps, out))
                # the launches above pushed fresh verdicts; force the poll
                # NOW so a still-poisoned window fails HERE, not at a
                # later poll that would condemn innocent steps
                if hasattr(exe, 'poll_nan'):
                    exe.poll_nan()
        except Exception as e:   # noqa: BLE001 - classified right below
            if not is_divergence(e):
                raise
            self.last_replay = None
            return _REPLAY_FAILED
        finally:
            # the next production launch must not be measured against the
            # replay's timeline
            _obs.stall.clear_window(exe)
        return out

    def rollback(self, reason=''):
        """Restore the last good checkpoint into the scope (+ RNG/run
        counters) and optionally scale the LR down.  Raises if there is
        no valid checkpoint — recovery without a restore point would mean
        silently training on poisoned state."""
        with _tc.root_span('recovery.rollback', cat='recovery',
                           args={'reason': reason}):
            meta = self.checkpointer.restore()
            if meta is None:
                _obs.metrics.counter('recovery.no_checkpoint').inc()
                raise RuntimeError(
                    'divergence recovery failed: no valid checkpoint to '
                    'roll back to (save one before training starts)')
            _obs.metrics.counter('recovery.rollbacks').inc()
            _obs.tracing.instant('recovery.rollback', cat='recovery',
                                 args={'to_step': meta.get('step_id'),
                                       'reason': reason})
            if self.lr_var and self.lr_scale and \
                    self._consecutive >= self.lr_after:
                # rung 3: quarantine/skip didn't stop the bleeding — the
                # divergence is systemic, not one bad sample.  Dampen.
                scope = self.checkpointer._scope()
                if self.lr_var in scope:
                    lr = np.asarray(scope.get(self.lr_var))
                    scope.set(self.lr_var,
                              (lr * self.lr_scale).astype(lr.dtype))
                    _obs.metrics.counter('recovery.lr_scaled').inc()
                    _obs.metrics.counter(
                        'recovery.escalation.lr_scale').inc()
        # drop any verdicts still accumulating on device: they were
        # computed over the poisoned (pre-restore) stream and would trip
        # a later poll against the clean restored state
        exe = getattr(self.checkpointer, 'executor', None)
        if exe is not None and hasattr(exe, 'reset_nan_window'):
            exe.reset_nan_window()
        # the restore + replay window is an intentional gap, not a stall:
        # forget the launch-gap baseline so the first replayed launch is
        # not measured against the pre-rollback timeline
        _obs.stall.clear_window(exe)
        # divergences survive rollback history: a spike right after a
        # rollback should still count toward give-up, but the loss
        # history predates the poisoned step and stays valid
        return meta
