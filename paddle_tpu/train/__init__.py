from . import checkpoint
from . import forensics
from . import recovery
from .checkpoint import CheckpointConfig, Checkpointer  # noqa: F401
from .forensics import ForensicReport, LaunchRecord  # noqa: F401
from .recovery import RecoveryPolicy, DivergenceError  # noqa: F401

__all__ = ['checkpoint', 'forensics', 'recovery', 'CheckpointConfig',
           'Checkpointer', 'ForensicReport', 'LaunchRecord',
           'RecoveryPolicy', 'DivergenceError']
