from . import checkpoint
from . import recovery
from .checkpoint import CheckpointConfig, Checkpointer  # noqa: F401
from .recovery import RecoveryPolicy, DivergenceError  # noqa: F401

__all__ = ['checkpoint', 'recovery', 'CheckpointConfig', 'Checkpointer',
           'RecoveryPolicy', 'DivergenceError']
