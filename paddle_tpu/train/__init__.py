from . import checkpoint
from .checkpoint import CheckpointConfig, Checkpointer  # noqa: F401

__all__ = ['checkpoint', 'CheckpointConfig', 'Checkpointer']
