"""Checkpoint / inference-model save & load.

Parity: reference python/paddle/fluid/io.py (save_vars, save_params,
save_persistables, load_*, save_inference_model, load_inference_model).
Programs serialize to a JSON-able dict (no protobuf); tensors to .npz.
"""
import json
import os
import numpy as np

from .core.framework import (Program, Variable, Parameter,
                             default_main_program)
from .core import sharding as _sharding
from .core.executor import global_scope
from .core.retry import retry_with_backoff
from .testing import faults as _faults

__all__ = ['save_vars', 'save_params', 'save_persistables', 'load_vars',
           'load_params', 'load_persistables', 'save_inference_model',
           'load_inference_model', 'program_to_desc', 'desc_to_program',
           'save_checkpoint', 'load_checkpoint']

_PARAMS_FILE = '__params__.npz'
_PROGRAM_FILE = '__model__.json'


def _resolve(main_program):
    return main_program if main_program is not None else \
        default_main_program()


def _store_path(dirname, filename):
    """np.savez APPENDS '.npz' to paths missing it — normalize here so a
    save/load pair with the same user filename always meets on disk."""
    name = os.fspath(filename) if filename else _PARAMS_FILE
    if not name.endswith('.npz'):
        name += '.npz'
    return os.path.join(dirname, name)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = _resolve(main_program)
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    arrays = {}
    for v in vars:
        name = v.name if isinstance(v, Variable) else v
        if name in scope:
            arrays[name] = np.asarray(scope.get(name))
    path = _store_path(dirname, filename)

    def _write():
        _faults.maybe_fail('io_write')
        np.savez(path, **arrays)

    # transient disk errors retry with backoff; a persistent failure
    # propagates — a save the caller asked for must not vanish silently
    retry_with_backoff(_write, retry_on=(OSError,), name='io_write')


def _is_param(v):
    return isinstance(v, Parameter)


def _is_persistable(v):
    return v.persistable


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, None, _is_param, filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, None, _is_persistable,
              filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = _resolve(main_program)
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    path = _store_path(dirname, filename)

    def _read():
        _faults.maybe_fail('io_read')
        return np.load(path, allow_pickle=False)

    # a missing file propagates immediately (caller's contract unchanged);
    # transient read errors retry with backoff
    data = retry_with_backoff(_read, retry_on=(OSError,),
                              give_up_on=(FileNotFoundError,),
                              name='io_read')
    scope = global_scope()
    names = {v.name if isinstance(v, Variable) else v for v in vars}
    for name in data.files:
        if name in names:
            scope.set(name, data[name])


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, None, _is_param, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, None, _is_persistable,
              filename)


# ------------------------------------------------- program serialization

def program_to_desc(program):
    """Serialize a Program to a JSON-able dict (replaces the reference's
    ProgramDesc protobuf, framework/framework.proto)."""
    blocks = []
    for b in program.blocks:
        vars_ = []
        for v in b.vars.values():
            vars_.append({
                'name': v.name,
                'shape': list(v.shape) if v.shape is not None else None,
                'dtype': v.dtype,
                'lod_level': v.lod_level,
                'persistable': v.persistable,
                'stop_gradient': v.stop_gradient,
                'is_data': v.is_data,
                'is_parameter': isinstance(v, Parameter),
                'trainable': getattr(v, 'trainable', False),
                'lod_length_name': getattr(v, 'lod_length_name', None),
                'sharding': _sharding.spec_to_jsonable(v.sharding),
            })
        ops = []
        for op in b.ops:
            ops.append({
                'type': op.type,
                'inputs': op.inputs,
                'outputs': op.outputs,
                'input_is_list': op.input_is_list,
                'output_is_list': op.output_is_list,
                'attrs': _jsonable_attrs(op.attrs),
                # lint diagnostics on a re-loaded model still point at
                # the model code that built the op (analysis package)
                'source_loc': (list(op.source_loc)
                               if getattr(op, 'source_loc', None)
                               else None),
            })
        blocks.append({'idx': b.idx, 'parent_idx': b.parent_idx,
                       'vars': vars_, 'ops': ops})
    return {'version': 1, 'random_seed': program.random_seed,
            'blocks': blocks,
            'mesh_axes': ([list(p) for p in program._mesh_axes]
                          if program._mesh_axes is not None else None),
            'device_limit_bytes': program._device_limit_bytes,
            'kv_plan': program._kv_plan}


def _jsonable_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {'__ndarray__': v.tolist(), 'dtype': str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


def desc_to_program(desc):
    from .core.framework import Block, Operator
    program = Program()
    program.random_seed = desc.get('random_seed', 0)
    program.blocks = []
    for bd in desc['blocks']:
        b = Block(program, bd['idx'], bd['parent_idx'])
        for vd in bd['vars']:
            if vd.get('is_parameter'):
                v = Parameter(b, shape=vd['shape'], dtype=vd['dtype'],
                              name=vd['name'], trainable=vd.get(
                                  'trainable', True))
            else:
                v = Variable(b, name=vd['name'], shape=vd['shape'],
                             dtype=vd['dtype'], lod_level=vd['lod_level'],
                             persistable=vd['persistable'],
                             stop_gradient=vd['stop_gradient'],
                             is_data=vd['is_data'])
            if vd.get('lod_length_name'):
                v.lod_length_name = vd['lod_length_name']
            if vd.get('sharding') is not None:
                # sync the legacy side-table + PartitionSpec view too
                v.sharding = _sharding.spec_from_jsonable(vd['sharding'])
            b.vars[v.name] = v
        for od in bd['ops']:
            op = Operator(b, od['type'])
            op.inputs = {k: list(v) for k, v in od['inputs'].items()}
            op.outputs = {k: list(v) for k, v in od['outputs'].items()}
            op.input_is_list = od['input_is_list']
            op.output_is_list = od['output_is_list']
            attrs = {}
            for k, v in od['attrs'].items():
                if isinstance(v, dict) and '__ndarray__' in v:
                    attrs[k] = np.asarray(v['__ndarray__'],
                                          dtype=v['dtype'])
                else:
                    attrs[k] = v
            op.attrs = attrs
            if od.get('source_loc'):
                op.source_loc = tuple(od['source_loc'])
            b.ops.append(op)
        program.blocks.append(b)
    if desc.get('mesh_axes') is not None:
        program._mesh_axes = tuple((str(n), int(s))
                                   for n, s in desc['mesh_axes'])
    if desc.get('device_limit_bytes') is not None:
        program._device_limit_bytes = int(desc['device_limit_bytes'])
    if desc.get('kv_plan') is not None:
        program._kv_plan = dict(desc['kv_plan'])
    program._bump()
    return program


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    main_program = _resolve(main_program)
    pruned = main_program._prune(feeded_var_names, target_vars)
    os.makedirs(dirname, exist_ok=True)
    desc = program_to_desc(pruned)
    desc['feed_names'] = list(feeded_var_names)
    desc['fetch_names'] = [t.name if isinstance(t, Variable) else t
                           for t in target_vars]
    with open(os.path.join(dirname, model_filename or _PROGRAM_FILE),
              'w') as f:
        json.dump(desc, f)
    save_vars(executor, dirname, pruned, None, _is_persistable,
              params_filename)
    return desc['fetch_names']


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, pserver_endpoints=None):
    with open(os.path.join(dirname, model_filename or _PROGRAM_FILE)) as f:
        desc = json.load(f)
    program = desc_to_program(desc)
    load_vars(executor, dirname, program, None, _is_persistable,
              params_filename)
    feed_names = desc['feed_names']
    fetch_vars = [program.global_block().var(n)
                  for n in desc['fetch_names']]
    return program, feed_names, fetch_vars


# ------------------------------------------------- checkpoint / resume

def save_checkpoint(executor, dirname, main_program=None, step=0,
                    max_keep=3):
    """Step-numbered checkpoint with resume metadata (parity: reference
    trainer.py checkpoint feature)."""
    ckpt_dir = os.path.join(dirname, 'ckpt_%d' % step)
    save_persistables(executor, ckpt_dir, main_program)
    with open(os.path.join(ckpt_dir, 'META'), 'w') as f:
        json.dump({'step': step}, f)
    # rotate
    kept = sorted([d for d in os.listdir(dirname)
                   if d.startswith('ckpt_')],
                  key=lambda d: int(d.split('_')[1]))
    for d in kept[:-max_keep]:
        import shutil
        shutil.rmtree(os.path.join(dirname, d), ignore_errors=True)


def load_checkpoint(executor, dirname, main_program=None):
    """Load the newest checkpoint; returns the step to resume from (0 if
    none found)."""
    if not os.path.isdir(dirname):
        return 0
    kept = sorted([d for d in os.listdir(dirname)
                   if d.startswith('ckpt_')],
                  key=lambda d: int(d.split('_')[1]))
    if not kept:
        return 0
    ckpt_dir = os.path.join(dirname, kept[-1])
    load_persistables(executor, ckpt_dir, main_program)
    with open(os.path.join(ckpt_dir, 'META')) as f:
        return json.load(f)['step']
