"""Host-side streaming metrics (parity: python/paddle/fluid/metrics.py)."""
import numpy as np

__all__ = ['MetricBase', 'CompositeMetric', 'Precision', 'Recall', 'Accuracy',
           'ChunkEvaluator', 'EditDistance', 'DetectionMAP', 'Auc']


class MetricBase(object):
    def __init__(self, name):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        """Zero every public accumulator attribute in place, keeping its
        type (numerics to zero, arrays to zeros, anything else cleared)."""
        for attr in list(vars(self)):
            if attr.startswith('_'):
                continue
            cur = getattr(self, attr)
            if isinstance(cur, (np.ndarray, np.generic)):
                new = np.zeros_like(cur)
            elif isinstance(cur, (int, float)):
                new = type(cur)(0)
            else:
                new = None
            setattr(self, attr, new)

    def update(self, preds, labels):
        raise NotImplementedError()

    def eval(self):
        raise NotImplementedError()


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super(Precision, self).__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype('int32').reshape(-1)
        labels = np.asarray(labels).astype('int32').reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels != 1)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else .0


class Recall(MetricBase):
    def __init__(self, name=None):
        super(Recall, self).__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype('int32').reshape(-1)
        labels = np.asarray(labels).astype('int32').reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds != 1) & (labels == 1)))

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else .0


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = .0
        self.weight = .0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError('weight is 0: call update first')
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = float(self.num_correct_chunks) / self.num_infer_chunks \
            if self.num_infer_chunks else 0.
        recall = float(self.num_correct_chunks) / self.num_label_chunks \
            if self.num_label_chunks else 0.
        f1 = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super(EditDistance, self).__init__(name)
        self.total_distance = .0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError('no data: call update first')
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    def __init__(self, name, curve='ROC', num_thresholds=4095):
        super(Auc, self).__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        labels = np.asarray(labels).reshape(-1)
        preds = np.asarray(preds)
        p1 = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
        buckets = np.clip((p1 * self._num_thresholds).astype(int), 0,
                          self._num_thresholds)
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def eval(self):
        tot_pos = tot_neg = auc = 0.0
        idx = self._num_thresholds
        while idx >= 0:
            tot_pos_prev, tot_neg_prev = tot_pos, tot_neg
            tot_pos += self._stat_pos[idx]
            tot_neg += self._stat_neg[idx]
            auc += self.trapezoid_area(tot_neg, tot_neg_prev, tot_pos,
                                       tot_pos_prev)
            idx -= 1
        return auc / tot_pos / tot_neg if tot_pos > 0.0 and tot_neg > 0.0 \
            else 0.0


class DetectionMAP(MetricBase):
    """Mean average precision over accumulated detection results."""

    def __init__(self, name=None, overlap_threshold=0.5, ap_version=
                 'integral', class_num=None):
        super(DetectionMAP, self).__init__(name)
        self._overlap = overlap_threshold
        self._ap_version = ap_version
        self._class_num = class_num
        self._records = []  # (label, score, tp)

    def update(self, detections, gt_boxes, gt_labels):
        """detections: [M, 6] (label, score, x1, y1, x2, y2) per image."""
        det = np.asarray(detections)
        gtb = np.asarray(gt_boxes)
        gtl = np.asarray(gt_labels).reshape(-1)
        matched = np.zeros(len(gtb), dtype=bool)
        order = np.argsort(-det[:, 1]) if len(det) else []
        for i in order:
            lab, score = det[i, 0], det[i, 1]
            if lab < 0:
                continue
            box = det[i, 2:6]
            best_iou, best_j = 0.0, -1
            for j, (gb, gl) in enumerate(zip(gtb, gtl)):
                if gl != lab or matched[j]:
                    continue
                xi = max(box[0], gb[0])
                yi = max(box[1], gb[1])
                xa = min(box[2], gb[2])
                ya = min(box[3], gb[3])
                inter = max(xa - xi, 0) * max(ya - yi, 0)
                a1 = max(box[2] - box[0], 0) * max(box[3] - box[1], 0)
                a2 = max(gb[2] - gb[0], 0) * max(gb[3] - gb[1], 0)
                iou = inter / max(a1 + a2 - inter, 1e-10)
                if iou > best_iou:
                    best_iou, best_j = iou, j
            tp = best_iou >= self._overlap and best_j >= 0
            if tp:
                matched[best_j] = True
            self._records.append((int(lab), float(score), bool(tp),
                                  len(gtl)))

    def eval(self):
        if not self._records:
            return 0.0
        labels = sorted({r[0] for r in self._records})
        aps = []
        for lab in labels:
            rec = sorted([r for r in self._records if r[0] == lab],
                         key=lambda r: -r[1])
            npos = sum(r[3] for r in self._records if r[0] == lab) or 1
            tp_cum = np.cumsum([1.0 if r[2] else 0.0 for r in rec])
            fp_cum = np.cumsum([0.0 if r[2] else 1.0 for r in rec])
            recall = tp_cum / npos
            precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-10)
            ap = 0.0
            prev_r = 0.0
            for p, r in zip(precision, recall):
                ap += p * (r - prev_r)
                prev_r = r
            aps.append(ap)
        return float(np.mean(aps))
