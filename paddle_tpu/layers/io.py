"""Input layers & readers.

Parity: reference python/paddle/fluid/layers/io.py (data, py_reader, batch,
shuffle, double_buffer, read_file, open_files).  TPU-native: readers are
host-side prefetch pipelines (the device pipeline is the jitted step); a
ragged (lod_level>0) data var is declared as padded [-1, -1, ...] plus a
companion `<name>@LENGTH` int32 vector fed automatically from a LoDTensor.
"""
from ..core.framework import default_main_program
from ..core.lod import LENGTH_SUFFIX, OUTER_SUFFIX

__all__ = ['data', 'py_reader', 'shuffle', 'batch', 'double_buffer',
           'read_file', 'open_files', 'random_data_generator', 'load',
           'create_py_reader_by_data', 'Preprocessor']


def data(name, shape, dtype='float32', lod_level=0, type=None,
         append_batch_size=True, stop_gradient=True):
    """Declare an input variable (reference layers/io.py data())."""
    shape = list(shape)
    if append_batch_size:
        # negative dims inside shape are normalized to -1 like the ref
        shape = [-1] + shape
    shape = [d if (d is None or d >= 0) else -1 for d in shape]
    if lod_level > 0:
        # padded layout: [batch, time, *feature]
        shape = [shape[0], -1] + shape[1:]
    block = default_main_program().global_block()
    var = block.create_var(name=name, shape=shape, dtype=dtype,
                           lod_level=lod_level, is_data=True,
                           stop_gradient=stop_gradient)
    if lod_level > 0:
        block.create_var(name=name + LENGTH_SUFFIX, shape=[-1],
                         dtype='int32', is_data=True, stop_gradient=True)
        var.lod_length_name = name + LENGTH_SUFFIX
    if lod_level > 1:
        # lengths-of-lengths companion (nested LoD): #inner sequences
        # per outer group, fed automatically from a 2-level LoDTensor
        block.create_var(name=name + OUTER_SUFFIX, shape=[-1],
                         dtype='int32', is_data=True, stop_gradient=True)
        var.lod_outer_length_name = name + OUTER_SUFFIX
    return var


class _PyReader(object):
    """Host-side prefetching reader (parity: py_reader / double_buffer).

    decorate_paddle_reader / decorate_tensor_provider feed a generator whose
    batches are handed to Executor.run via feed dict by `next_feed()`.
    """

    def __init__(self, feed_list=None, capacity=64, shapes=None, dtypes=None,
                 lod_levels=None, name=None):
        self.feed_list = feed_list or []
        self.capacity = capacity
        self._gen = None
        self._iter = None

    def decorate_paddle_reader(self, reader, places=None):
        self._gen = reader

    decorate_sample_list_generator = decorate_paddle_reader
    decorate_batch_generator = decorate_paddle_reader
    decorate_tensor_provider = decorate_paddle_reader

    def start(self):
        self._iter = iter(self._gen())

    def reset(self):
        self._iter = None

    def next_feed(self):
        if self._iter is None:
            self.start()
        try:
            sample = next(self._iter)
        except StopIteration:
            self._iter = None
            raise
        feed = {}
        for var, val in zip(self.feed_list, sample):
            feed[var.name] = val
        return feed


def py_reader(capacity=64, shapes=None, dtypes=None, lod_levels=None,
              name=None, use_double_buffer=True):
    vars_ = []
    for i, (s, d) in enumerate(zip(shapes, dtypes)):
        lod = lod_levels[i] if lod_levels else 0
        vars_.append(data('_py_reader_%s_%d' % (name or 'r', i),
                          shape=list(s)[1:], dtype=d, lod_level=lod))
    return _PyReader(feed_list=vars_, capacity=capacity)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    return _PyReader(feed_list=feed_list, capacity=capacity)


def read_file(reader):
    return list(reader.feed_list)


def shuffle(reader, buffer_size):
    from ..reader import shuffle as _shuffle
    if isinstance(reader, _PyReader):
        return reader
    return _shuffle(reader, buffer_size)


def batch(reader, batch_size):
    from ..batch import batch as _batch
    if isinstance(reader, _PyReader):
        return reader
    return _batch(reader, batch_size)


def double_buffer(reader, place=None, name=None):
    return reader


def open_files(filenames, shapes, lod_levels, dtypes, thread_num=1,
               buffer_size=None, pass_num=1, is_test=None, batch_size=1,
               shuffle_capacity=0, seed=0):
    """File reader over ptrec files via the native C++ pipeline.

    Parity: reference layers/io.py open_files (recordio multi-file reader
    with background threads).  Returns a _PyReader whose batches come from
    paddle_tpu.native.BatchReader — parsing/shuffle/batch assembly run in
    C++ threads off the GIL, prefetch depth = buffer_size.
    """
    from ..native import BatchReader
    from ..core import unique_name
    if isinstance(filenames, str):
        filenames = [filenames]
    r = py_reader(capacity=buffer_size or 4, shapes=shapes, dtypes=dtypes,
                  lod_levels=lod_levels,
                  name=unique_name.generate('open_files'))
    loop = pass_num <= 0

    def gen():
        for _ in range(max(pass_num, 1) if not loop else 1):
            for batch_ in BatchReader(
                    filenames, batch_size=batch_size,
                    shuffle_capacity=shuffle_capacity, seed=seed,
                    loop_forever=loop, prefetch=buffer_size or 4):
                yield batch_

    r.decorate_paddle_reader(gen)
    return r


def random_data_generator(low, high, shapes, lod_levels, for_parallel=True):
    import numpy as np
    vars_ = [data('_rand_gen_%d' % i, shape=list(s)[1:], dtype='float32')
             for i, s in enumerate(shapes)]
    r = _PyReader(feed_list=vars_)

    def gen():
        while True:
            yield [np.random.uniform(low, high, size=s).astype('float32')
                   for s in shapes]
    r.decorate_paddle_reader(gen)
    return r


def load(out, file_path, load_as_fp16=None):
    import numpy as np
    val = np.load(file_path + '.npy')
    from ..core.executor import global_scope
    global_scope().set(out.name, val)


class Preprocessor(object):
    def __init__(self, reader, name=None):
        self.reader = reader

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            yield
        return cm()
