"""Control-flow layers.

Parity: reference python/paddle/fluid/layers/control_flow.py (While, Switch,
IfElse, DynamicRNN, StaticRNN, array ops, Print).

TPU-native: XLA requires structured control flow.  `While` lowers to
`lax.while_loop` over the carried block-written vars (see
core/control_flow_exec.py); `StaticRNN`/`DynamicRNN` lower to `lax.scan`
over the padded time axis.  Tensor arrays with static length lower to
stacked tensors.
"""
import numpy as np

from ..core.framework import Variable, default_main_program
from ..core.layer_helper import LayerHelper
from . import tensor as tensor_layers
from . import nn as nn_layers

__all__ = ['While', 'Switch', 'ConditionalBlock', 'increment', 'array_write',
           'create_array', 'less_than', 'equal', 'array_read', 'array_length',
           'IfElse', 'DynamicRNN', 'StaticRNN', 'reorder_lod_tensor_by_rank',
           'Print', 'is_empty']


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper('increment')
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='increment', inputs={'X': x},
                     outputs={'Out': out}, attrs={'step': float(value)})
    return out


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper('less_than')
    if cond is None:
        cond = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='less_than', inputs={'X': x, 'Y': y},
                     outputs={'Out': cond}, attrs={})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper('equal')
    if cond is None:
        cond = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='equal', inputs={'X': x, 'Y': y},
                     outputs={'Out': cond}, attrs={})
    return cond


def is_empty(x, cond=None):
    helper = LayerHelper('is_empty')
    if cond is None:
        cond = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='is_empty', inputs={'X': x},
                     outputs={'Out': cond}, attrs={})
    return cond


# ----------------------------------------------------------- tensor array

class _TensorArray(object):
    """Tensor array with a dual representation.

    Parity: reference LoDTensorArray (a C++ vector<LoDTensor> mutated by
    lod_array ops at runtime).  TPU-native split:

    * **Build-level** (``self.vars`` list): writes at statically-known
      indices outside control-flow sub-blocks just track Variables in
      Python — reads resolve to the variable directly and the "array"
      never exists at runtime (StaticRNN / beam-search builders).
    * **Graph-level** (``self.var``): a write with a runtime index, or any
      write inside a While/conditional sub-block, upgrades the array to a
      graph variable carried as a fixed-capacity stacked buffer + length
      (core/control_flow_exec.TensorArrayVal).  Capacity comes from the
      enclosing loop's static bound or an explicit ``capacity=``.
    """

    def __init__(self, dtype='float32', capacity=None):
        self.dtype = dtype
        self.capacity = capacity
        self.vars = []
        self.var = None          # graph Variable once upgraded
        self.elem_shape = None

    def _to_graph(self):
        if self.var is not None:
            return self.var
        from ..core import unique_name
        prog = default_main_program()
        root = prog.global_block()
        v = root.create_var(name=unique_name.generate('tensor_array'),
                            dtype=self.dtype, shape=None)
        v.is_tensor_array = True
        self.var = v
        # migrate build-level entries: they must land in the buffer before
        # any runtime write, so the writes go at the root block (which is
        # always positionally before any not-yet-appended while op)
        for idx, x in enumerate(self.vars):
            iv = root.create_var(
                name=unique_name.generate('ta_idx'), dtype='int64',
                shape=(1,))
            root.append_op(type='fill_constant', inputs={},
                           outputs={'Out': iv},
                           attrs={'shape': [1], 'dtype': 'int64',
                                  'value': idx})
            root.append_op(type='write_to_array',
                           inputs={'X': x, 'I': iv, 'A': v},
                           outputs={'Out': v},
                           attrs={'capacity': self.capacity},
                           infer_shape=False)
            if x.shape is not None:
                self.elem_shape = tuple(x.shape)
        self.vars = []
        return v


def create_array(dtype, capacity=None):
    return _TensorArray(dtype, capacity=capacity)


def _static_index(i):
    """Extract a python int from an index Variable produced by
    fill_constant/increment chains at build time, if possible."""
    if isinstance(i, (int, np.integer)):
        return int(i)
    op = i.op
    if op is not None and op.type == 'fill_constant':
        return int(op.attrs['value'])
    return None


def _in_sub_block():
    return default_main_program().current_block().parent_idx >= 0


def array_write(x, i, array=None):
    if array is None:
        array = create_array(x.dtype)
    idx = _static_index(i)
    if array.var is None and idx is not None and not _in_sub_block():
        # build-level path: array never materializes at runtime
        if idx >= len(array.vars):
            while len(array.vars) <= idx:
                array.vars.append(x)
        array.vars[idx] = x
        return array
    v = array._to_graph()
    if x.shape is not None:
        array.elem_shape = tuple(x.shape)
    default_main_program().current_block().append_op(
        type='write_to_array', inputs={'X': x, 'I': i, 'A': v},
        outputs={'Out': v}, attrs={'capacity': array.capacity},
        infer_shape=False)
    return array


def array_read(array, i):
    if array.var is None:
        idx = _static_index(i)
        if idx is not None and idx < len(array.vars) and not _in_sub_block():
            return array.vars[idx]
        if array.vars:
            # dynamic read of a build-level array: stack + gather
            stacked = nn_layers.stack(array.vars, axis=0)
            iv = tensor_layers.cast(i, 'int64')
            row = nn_layers.gather(stacked, iv)
            return nn_layers.squeeze(row, axes=[0])
    v = array._to_graph()
    helper = LayerHelper('array_read')
    out = helper.create_variable_for_type_inference(array.dtype)
    out.shape = array.elem_shape
    helper.append_op(type='read_from_array', inputs={'A': v, 'I': i},
                     outputs={'Out': out}, attrs={}, infer_shape=False)
    return out


def array_length(array):
    if array.var is None:
        return tensor_layers.fill_constant([1], 'int64', len(array.vars))
    helper = LayerHelper('array_length')
    out = helper.create_variable_for_type_inference('int64')
    out.shape = (1,)
    helper.append_op(type='array_length', inputs={'A': array.var},
                     outputs={'Out': out}, attrs={}, infer_shape=False)
    return out


# ----------------------------------------------------------- While

class While(object):
    """While loop over a sub-block, lowered to lax.while_loop.

    Usage parity with reference control_flow.py While:
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            ...   # must update `cond` via layers.assign/less_than(cond=...)
    Vars written in the body that exist before the loop become loop
    carries.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper('while', name=name)

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            prog = default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                parent.append_op(
                    type='while',
                    inputs={'Condition': self.cond_var},
                    outputs={},
                    attrs={'sub_block': sub.idx},
                    infer_shape=False)
        return cm()


class ConditionalBlock(object):
    """Run a sub-block only when a boolean condition holds.

    Parity: reference control_flow.py ConditionalBlock /
    paddle/fluid/operators/conditional_block_op.cc.  Lowered to `lax.cond`
    over the vars the body writes (core/control_flow_exec.py) — the false
    branch passes them through unchanged, so vars assigned in the body must
    exist beforehand to be visible after the block.
    """

    def __init__(self, inputs, is_scalar_condition=False, name=None):
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self.cond_vars = list(inputs)
        self.helper = LayerHelper('conditional_block', name=name)

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            prog = default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                cond = self.cond_vars[0]
                if len(self.cond_vars) > 1:
                    for c in self.cond_vars[1:]:
                        cond = nn_layers.logical_and(cond, c)
                parent.append_op(
                    type='conditional_block',
                    inputs={'Condition': cond},
                    outputs={},
                    attrs={'sub_block': sub.idx},
                    infer_shape=False)
        return cm()


class Switch(object):
    """Mutually-exclusive cases (ref Switch).  Branch-free lowering: each
    case body runs and results blend via masks — all cases must write the
    same output vars via layers.assign.  Like the reference's if/elif
    chain, the FIRST matching case wins when conditions overlap.
    Usable bare or as a context manager (`with Switch() as switch:`,
    the reference's documented form)."""

    def __init__(self, name=None):
        self.helper = LayerHelper('switch', name=name)
        self._cases = []   # conds registered at case ENTRY, in order

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def case(self, condition):
        return _SwitchCase(self, condition)

    def default(self):
        return _SwitchCase(self, None)


class _SwitchCase(object):
    """One case scope.  The effective mask (cond AND no-earlier-case) is
    computed ONCE at entry — every assign inside the body blends with
    the same mask, and a case with zero assigns still claims its rows
    from default()."""

    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition
        self.eff = None   # None => unconditional (default with no cases)

    def __enter__(self):
        if _switch_stack:
            raise NotImplementedError(
                'nested Switch is not supported: flatten the conditions '
                '(logical_and of the outer and inner case predicates)')
        sw = self.switch
        taken = None
        for prev in sw._cases:
            taken = prev if taken is None else \
                nn_layers.logical_or(taken, prev)
        if self.condition is None:      # default: rows no case claimed
            self.eff = (None if taken is None
                        else nn_layers.logical_not(taken))
        else:
            self.eff = (self.condition if taken is None
                        else nn_layers.logical_and(
                            self.condition,
                            nn_layers.logical_not(taken)))
            sw._cases.append(self.condition)
        _switch_stack.append(self)
        return self

    def __exit__(self, *a):
        _switch_stack.pop()
        return False


_switch_stack = []


def _raw_assign(value, output):
    """Append a plain assign op, bypassing the switch-aware public
    layers.assign (which would re-enter the blend)."""
    helper = LayerHelper('assign')
    helper.append_op(type='assign', inputs={'X': value},
                     outputs={'Out': output}, attrs={})
    return output


def _in_switch_assign(output, value):
    """Blend `value` into `output` under the active switch case's mask
    (first matching case wins — the reference's if/elif semantics).
    Invoked by layers.assign whenever a Switch case is active."""
    case = _switch_stack[-1]
    if case.eff is None:   # default with no preceding cases
        _raw_assign(value, output)
        return
    mask = tensor_layers.cast(case.eff, 'float32')
    blended = mask * value + (1.0 - mask) * output
    _raw_assign(blended, output)


class IfElse(object):
    """Row-wise if/else over a [B, 1] boolean condition.

    Parity: reference control_flow.py:1265 (split_lod_tensor → branch
    bodies → merge_lod_tensor).  TPU-native lowering: there is no
    data-dependent row compaction — both branch bodies run on the FULL
    batch and `merge_lod_tensor` select-masks rows back together, which
    is exactly what XLA wants (static shapes, fused select).  Identical
    results for row-wise branch bodies; a branch body that reduces over
    the batch axis would see all rows, unlike the reference (document’d
    divergence).
    """
    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        if not isinstance(cond, Variable):
            raise TypeError('IfElse cond must be a Variable')
        self.helper = LayerHelper('ifelse', name=name)
        self.cond = cond
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        self.input_table = {}
        self.output_table = ([], [])   # (false_outs, true_outs)

    def _block(self, is_true):
        import contextlib

        @contextlib.contextmanager
        def cm():
            self.status = (IfElse.IN_IF_ELSE_TRUE_BLOCKS if is_true
                           else IfElse.IN_IF_ELSE_FALSE_BLOCKS)
            try:
                yield
            finally:
                self.status = IfElse.OUT_IF_ELSE_BLOCKS
        return cm()

    def true_block(self):
        return self._block(True)

    def false_block(self):
        return self._block(False)

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError('IfElse.input must be called inside '
                             'true_block/false_block')
        if id(x) not in self.input_table:
            helper = LayerHelper('ifelse_input')
            out_true = helper.create_variable_for_type_inference(x.dtype)
            out_false = helper.create_variable_for_type_inference(x.dtype)
            helper.append_op(
                type='split_lod_tensor',
                inputs={'X': x, 'Mask': self.cond},
                outputs={'OutTrue': out_true, 'OutFalse': out_false},
                attrs={'level': 0})
            self.input_table[id(x)] = (out_true, out_false)
        out_true, out_false = self.input_table[id(x)]
        return (out_true if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
                else out_false)

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError('IfElse.output must be called inside '
                             'true_block/false_block')
        table = self.output_table[
            1 if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS else 0]
        for o in outs:
            if not isinstance(o, Variable):
                raise TypeError('each IfElse output must be a Variable')
            table.append(o)

    def __call__(self):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError('IfElse() must be called outside the blocks')
        false_outs, true_outs = self.output_table
        if not false_outs and not true_outs:
            raise ValueError('IfElse has no outputs')
        if not false_outs or not true_outs:
            # single-branch: the reference returns just that branch's
            # compacted rows; compaction is a dynamic shape, so here the
            # un-selected rows are ZEROED instead (row order preserved)
            only = list(true_outs or false_outs)
            masked = []
            for o in only:
                helper = LayerHelper('ifelse_mask')
                zero = helper.create_variable_for_type_inference(o.dtype)
                helper.append_op(type='fill_zeros_like',
                                 inputs={'X': o}, outputs={'Out': zero},
                                 attrs={})
                out = helper.create_variable_for_type_inference(o.dtype)
                t, f = (o, zero) if true_outs else (zero, o)
                helper.append_op(
                    type='merge_lod_tensor',
                    inputs={'InTrue': t, 'InFalse': f, 'Mask': self.cond,
                            'X': self.cond},
                    outputs={'Out': out}, attrs={'level': 0})
                masked.append(out)
            return masked
        if len(false_outs) != len(true_outs):
            raise ValueError('true/false blocks must produce the same '
                             'number of outputs')
        merged = []
        for f, t in zip(false_outs, true_outs):
            helper = LayerHelper('ifelse_merge')
            out = helper.create_variable_for_type_inference(t.dtype)
            helper.append_op(
                type='merge_lod_tensor',
                inputs={'InTrue': t, 'InFalse': f, 'Mask': self.cond,
                        'X': self.cond},
                outputs={'Out': out}, attrs={'level': 0})
            merged.append(out)
        return merged


class _MemoryLink(object):
    def __init__(self, init, pre_mem):
        self.init = init
        self.pre_mem = pre_mem
        self.mem = None          # set by update_memory


class _RecurrentBase(object):
    """Shared builder for StaticRNN / DynamicRNN: collects the step block
    and appends ONE `recurrent` op lowered to lax.scan
    (core/control_flow_exec._exec_recurrent)."""

    _time_major = True

    def __init__(self, name=None, kind='static_rnn'):
        self.helper = LayerHelper(kind, name=name)
        self.memories = {}       # pre_mem name -> _MemoryLink (ordered)
        self.inputs = []         # (step_var, seq_source_var)
        self.outputs = []        # parent-level stacked vars
        self._step_outs = []
        self.seq_len = None
        self._sub = None
        self._parent = None
        self._done = False

    # -- block management
    def _enter(self):
        prog = default_main_program()
        self._parent = prog.current_block()
        self._sub = prog._create_block()

    def _exit(self):
        default_main_program()._rollback()
        self._complete()
        self._done = True

    def _guard(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            self._enter()
            try:
                yield
            except BaseException:
                # body failed: leave the sub-block but do NOT append the
                # recurrent op — a half-built step block must not survive
                # in the program (and _complete's own errors must not
                # mask the user's)
                default_main_program()._rollback()
                self._done = True
                raise
            self._exit()
        return cm()

    def _assert_in_block(self, method):
        if self._sub is None or self._done:
            raise ValueError('%s must be called inside the rnn block'
                             % method)

    def update_memory(self, mem, var):
        self._assert_in_block('update_memory')
        if not isinstance(mem, Variable) or not isinstance(var, Variable):
            raise TypeError('update_memory takes (pre_mem, new) Variables')
        if mem.name not in self.memories:
            raise ValueError('%s is not a memory created by memory()'
                             % mem.name)
        self.memories[mem.name].mem = var

    def _make_memory(self, init):
        from ..core import unique_name
        pre = self._sub.create_var(
            name=unique_name.generate(self.helper.name + '_mem'),
            dtype=init.dtype,
            shape=tuple(init.shape) if init.shape is not None else None)
        self.memories[pre.name] = _MemoryLink(init, pre)
        return pre

    def _complete(self):
        if not self.inputs:
            raise ValueError('rnn block needs at least one step_input')
        links = list(self.memories.values())
        attrs = {
            'sub_block': self._sub.idx,
            'step_vars': [sv.name for sv, _ in self.inputs],
            'seq_vars': [src.name for _, src in self.inputs],
            'mem_vars': [ln.pre_mem.name for ln in links],
            'init_vars': [ln.init.name for ln in links],
            # a memory never updated carries through unchanged
            'update_vars': [(ln.mem or ln.pre_mem).name for ln in links],
            'out_vars': [o.name for o in self._step_outs],
            'stack_vars': [o.name for o in self.outputs],
            'time_major': self._time_major,
            'length_var': self._length_name(),
        }
        inputs = {'Seq': [src for _, src in self.inputs],
                  'Init': [ln.init for ln in links]}
        lv = self._length_name()
        if lv:
            inputs['Length'] = [self._parent._find_var_recursive(lv)]
        self._parent.append_op(
            type='recurrent', inputs=inputs,
            outputs={'Out': list(self.outputs)}, attrs=attrs,
            infer_shape=False)

    def _length_name(self):
        return None

    def output(self, *outputs):
        self._assert_in_block('output')
        for o in outputs:
            if not isinstance(o, Variable):
                raise TypeError('rnn output takes Variables')
            self._step_outs.append(o)
            self.outputs.append(self._make_stacked_out(o))

    def __call__(self, *args, **kwargs):
        if not self._done:
            raise ValueError('rnn outputs can only be retrieved after the '
                             'rnn block closes')
        if not self.outputs:
            raise ValueError('rnn has no output')
        return self.outputs[0] if len(self.outputs) == 1 else self.outputs


class StaticRNN(_RecurrentBase):
    """RNN over a statically-known number of time steps.

    Parity: reference control_flow.py:278 (StaticRNN) +
    operators/recurrent_op.cc.  Sequence inputs are TIME-MAJOR
    [T, B, ...]; `step_input` yields the [B, ...] slice, `memory`/
    `update_memory` chain state across steps, `output` stacks per-step
    values back to [T, B, ...].  Lowered to one `lax.scan` (the
    reference re-runs the step block T times on the host)."""

    _time_major = True

    def __init__(self, name=None):
        super(StaticRNN, self).__init__(name=name, kind='static_rnn')

    def step(self):
        return self._guard()

    def step_input(self, x):
        self._assert_in_block('step_input')
        if not isinstance(x, Variable):
            raise TypeError('step_input takes a Variable')
        if x.shape is None:
            raise ValueError('step_input needs a known [T, B, ...] shape')
        if self.seq_len is None:
            self.seq_len = int(x.shape[0])
        elif self.seq_len != int(x.shape[0]):
            raise ValueError('StaticRNN needs a fixed seq_len; got %s vs %s'
                             % (x.shape[0], self.seq_len))
        from ..core import unique_name
        ipt = self._sub.create_var(
            name=unique_name.generate(self.helper.name + '_step_in'),
            dtype=x.dtype, shape=tuple(x.shape[1:]))
        self.inputs.append((ipt, x))
        return ipt

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._assert_in_block('memory')
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError('memory() needs init= or (shape, '
                                 'batch_ref)')
            from ..core import unique_name
            # the boot op lives in the PARENT block (it runs once, before
            # the scan), but batch_ref is usually the step-local input —
            # shapes are static here, so resolve the batch dim at build
            # time and emit a plain fill_constant
            bs = list(shape)
            if batch_ref.shape is not None and \
                    batch_ref.shape[ref_batch_dim_idx] not in (-1, None):
                # statically-known batch: a plain fill_constant suffices
                bs[init_batch_dim_idx] = int(
                    batch_ref.shape[ref_batch_dim_idx])
                boot = self._parent.create_var(
                    name=unique_name.generate(self.helper.name + '_boot'),
                    dtype=batch_ref.dtype, shape=tuple(bs))
                self._parent.append_op(
                    type='fill_constant',
                    inputs={}, outputs={'Out': boot},
                    attrs={'shape': bs, 'value': float(init_value),
                           'dtype': batch_ref.dtype},
                    infer_shape=False)
                return self.memory(init=boot)
            # batch dim is -1 (default append_batch_size programs): boot
            # via fill_constant_batch_size_like, like DynamicRNN.  The
            # boot op runs in the PARENT block, so when batch_ref is the
            # step-local slice, size off its parent [T, B, ...] sequence
            # (batch at axis 1) instead.
            ref, ref_dim = batch_ref, ref_batch_dim_idx
            for ipt, seq in self.inputs:
                if batch_ref is ipt:
                    ref, ref_dim = seq, 1
                    break
            bs[init_batch_dim_idx] = -1
            boot = self._parent.create_var(
                name=unique_name.generate(self.helper.name + '_boot'),
                dtype=batch_ref.dtype, shape=tuple(bs))
            self._parent.append_op(
                type='fill_constant_batch_size_like',
                inputs={'Input': ref}, outputs={'Out': boot},
                attrs={'shape': bs, 'value': float(init_value),
                       'dtype': batch_ref.dtype,
                       'input_dim_idx': ref_dim,
                       'output_dim_idx': init_batch_dim_idx},
                infer_shape=False)
            return self.memory(init=boot)
        return self._make_memory(init)

    def step_output(self, o):
        self.output(o)

    def _make_stacked_out(self, o):
        from ..core import unique_name
        shape = ((self.seq_len,) + tuple(o.shape)
                 if o.shape is not None else None)
        return self._parent.create_var(
            name=unique_name.generate(self.helper.name + '_out'),
            dtype=o.dtype, shape=shape)


class DynamicRNN(_RecurrentBase):
    """RNN over padded variable-length batches.

    Parity: reference control_flow.py:1395 (DynamicRNN).  The reference
    sorts sequences by length (rank table) and shrinks the batch as
    sequences end; here sequences stay in feed order as a padded
    [B, T, ...] LoDTensor + lengths, and one `lax.scan` runs all T steps
    with masked carries: a finished row's memory freezes and its outputs
    are zero past its length.  Same results, static shapes, no row
    reordering (so `need_reorder` is a no-op by design)."""

    _time_major = False

    def __init__(self, name=None):
        super(DynamicRNN, self).__init__(name=name, kind='dynamic_rnn')
        self._lengths_name = None

    def block(self):
        return self._guard()

    def step_input(self, x, level=0):
        self._assert_in_block('step_input')
        if not isinstance(x, Variable):
            raise TypeError('step_input takes a Variable')
        if x.block is self._sub:
            raise ValueError(
                'step_input sequence %r was built INSIDE the rnn block; '
                'build the full [B, T, ...] sequence (e.g. the embedding) '
                'before entering block()' % x.name)
        if x.shape is None or len(x.shape) < 2:
            raise ValueError('DynamicRNN step_input needs a padded '
                             '[B, T, ...] variable')
        if self._lengths_name is None:
            lv = nn_layers._len_var(x)
            if lv is None:
                raise ValueError(
                    'DynamicRNN step_input needs sequence lengths: feed a '
                    'lod_level=1 LoDTensor (its @LENGTH companion rides '
                    'along) — got plain dense var %s' % x.name)
            self._lengths_name = lv.name
        if self.seq_len is None:
            self.seq_len = int(x.shape[1])
        from ..core import unique_name
        ipt = self._sub.create_var(
            name=unique_name.generate(self.helper.name + '_step_in'),
            dtype=x.dtype, shape=(x.shape[0],) + tuple(x.shape[2:]))
        self.inputs.append((ipt, x))
        return ipt

    def static_input(self, x):
        """A non-sequence input visible unchanged at every step (the
        reference reorders it by the rank table; rows here never move)."""
        self._assert_in_block('static_input')
        return x

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype='float32'):
        self._assert_in_block('memory')
        if init is not None:
            return self._make_memory(init)
        if shape is None:
            raise ValueError('memory() needs init= or shape=')
        if not self.inputs:
            raise ValueError('memory(shape=...) must come after '
                             'step_input (batch size reference)')
        from ..core import unique_name
        ref = self.inputs[0][1]
        boot = self._parent.create_var(
            name=unique_name.generate(self.helper.name + '_boot'),
            dtype=dtype, shape=(ref.shape[0],) + tuple(shape))
        self._parent.append_op(
            type='fill_constant_batch_size_like',
            inputs={'Input': ref}, outputs={'Out': boot},
            attrs={'shape': [-1] + list(shape), 'value': float(value),
                   'dtype': dtype, 'input_dim_idx': 0,
                   'output_dim_idx': 0},
            infer_shape=False)
        return self._make_memory(boot)

    def _length_name(self):
        return self._lengths_name

    def _make_stacked_out(self, o):
        from ..core import unique_name
        shape = None
        if o.shape is not None:
            shape = (o.shape[0], self.seq_len) + tuple(o.shape[1:])
        out = self._parent.create_var(
            name=unique_name.generate(self.helper.name + '_out'),
            dtype=o.dtype, shape=shape)
        out.lod_level = 1
        out.lod_length_name = self._lengths_name
        return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """Identity BY DESIGN — read before relying on reference semantics.

    The reference (control_flow.py reorder_lod_tensor_by_rank) physically
    permutes rows into rank-table order (longest sequence first) because
    its DynamicRNN shrinks the batch as sequences end.  This framework's
    padded+lengths layout never reorders rows — DynamicRNN masks finished
    rows instead — so every consumer sees rows in ORIGINAL feed order.
    Code that assumes rank-sorted row order after this call will behave
    differently than under the reference."""
    return x


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase='both'):
    """Debug print via jax.debug.print at lowering (ref print_op)."""
    helper = LayerHelper('print')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='print', inputs={'X': input},
                     outputs={'Out': out},
                     attrs={'message': message or ''})
    return out
