"""Control-flow layers.

Parity: reference python/paddle/fluid/layers/control_flow.py (While, Switch,
IfElse, DynamicRNN, StaticRNN, array ops, Print).

TPU-native: XLA requires structured control flow.  `While` lowers to
`lax.while_loop` over the carried block-written vars (see
core/control_flow_exec.py); `StaticRNN`/`DynamicRNN` lower to `lax.scan`
over the padded time axis.  Tensor arrays with static length lower to
stacked tensors.
"""
import numpy as np

from ..core.framework import Variable, default_main_program
from ..core.layer_helper import LayerHelper
from . import tensor as tensor_layers
from . import nn as nn_layers

__all__ = ['While', 'Switch', 'ConditionalBlock', 'increment', 'array_write',
           'create_array', 'less_than', 'equal', 'array_read', 'array_length',
           'IfElse', 'DynamicRNN', 'StaticRNN', 'reorder_lod_tensor_by_rank',
           'Print', 'is_empty']


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper('increment')
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='increment', inputs={'X': x},
                     outputs={'Out': out}, attrs={'step': float(value)})
    return out


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper('less_than')
    if cond is None:
        cond = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='less_than', inputs={'X': x, 'Y': y},
                     outputs={'Out': cond}, attrs={})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper('equal')
    if cond is None:
        cond = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='equal', inputs={'X': x, 'Y': y},
                     outputs={'Out': cond}, attrs={})
    return cond


def is_empty(x, cond=None):
    helper = LayerHelper('is_empty')
    if cond is None:
        cond = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='is_empty', inputs={'X': x},
                     outputs={'Out': cond}, attrs={})
    return cond


# ----------------------------------------------------------- tensor array

class _TensorArray(object):
    """Tensor array with a dual representation.

    Parity: reference LoDTensorArray (a C++ vector<LoDTensor> mutated by
    lod_array ops at runtime).  TPU-native split:

    * **Build-level** (``self.vars`` list): writes at statically-known
      indices outside control-flow sub-blocks just track Variables in
      Python — reads resolve to the variable directly and the "array"
      never exists at runtime (StaticRNN / beam-search builders).
    * **Graph-level** (``self.var``): a write with a runtime index, or any
      write inside a While/conditional sub-block, upgrades the array to a
      graph variable carried as a fixed-capacity stacked buffer + length
      (core/control_flow_exec.TensorArrayVal).  Capacity comes from the
      enclosing loop's static bound or an explicit ``capacity=``.
    """

    def __init__(self, dtype='float32', capacity=None):
        self.dtype = dtype
        self.capacity = capacity
        self.vars = []
        self.var = None          # graph Variable once upgraded
        self.elem_shape = None

    def _to_graph(self):
        if self.var is not None:
            return self.var
        from ..core import unique_name
        prog = default_main_program()
        root = prog.global_block()
        v = root.create_var(name=unique_name.generate('tensor_array'),
                            dtype=self.dtype, shape=None)
        v.is_tensor_array = True
        self.var = v
        # migrate build-level entries: they must land in the buffer before
        # any runtime write, so the writes go at the root block (which is
        # always positionally before any not-yet-appended while op)
        for idx, x in enumerate(self.vars):
            iv = root.create_var(
                name=unique_name.generate('ta_idx'), dtype='int64',
                shape=(1,))
            root.append_op(type='fill_constant', inputs={},
                           outputs={'Out': iv},
                           attrs={'shape': [1], 'dtype': 'int64',
                                  'value': idx})
            root.append_op(type='write_to_array',
                           inputs={'X': x, 'I': iv, 'A': v},
                           outputs={'Out': v},
                           attrs={'capacity': self.capacity},
                           infer_shape=False)
            if x.shape is not None:
                self.elem_shape = tuple(x.shape)
        self.vars = []
        return v


def create_array(dtype, capacity=None):
    return _TensorArray(dtype, capacity=capacity)


def _static_index(i):
    """Extract a python int from an index Variable produced by
    fill_constant/increment chains at build time, if possible."""
    if isinstance(i, (int, np.integer)):
        return int(i)
    op = i.op
    if op is not None and op.type == 'fill_constant':
        return int(op.attrs['value'])
    return None


def _in_sub_block():
    return default_main_program().current_block().parent_idx >= 0


def array_write(x, i, array=None):
    if array is None:
        array = create_array(x.dtype)
    idx = _static_index(i)
    if array.var is None and idx is not None and not _in_sub_block():
        # build-level path: array never materializes at runtime
        if idx >= len(array.vars):
            while len(array.vars) <= idx:
                array.vars.append(x)
        array.vars[idx] = x
        return array
    v = array._to_graph()
    if x.shape is not None:
        array.elem_shape = tuple(x.shape)
    default_main_program().current_block().append_op(
        type='write_to_array', inputs={'X': x, 'I': i, 'A': v},
        outputs={'Out': v}, attrs={'capacity': array.capacity},
        infer_shape=False)
    return array


def array_read(array, i):
    if array.var is None:
        idx = _static_index(i)
        if idx is not None and idx < len(array.vars) and not _in_sub_block():
            return array.vars[idx]
        if array.vars:
            # dynamic read of a build-level array: stack + gather
            stacked = nn_layers.stack(array.vars, axis=0)
            iv = tensor_layers.cast(i, 'int64')
            row = nn_layers.gather(stacked, iv)
            return nn_layers.squeeze(row, axes=[0])
    v = array._to_graph()
    helper = LayerHelper('array_read')
    out = helper.create_variable_for_type_inference(array.dtype)
    out.shape = array.elem_shape
    helper.append_op(type='read_from_array', inputs={'A': v, 'I': i},
                     outputs={'Out': out}, attrs={}, infer_shape=False)
    return out


def array_length(array):
    if array.var is None:
        return tensor_layers.fill_constant([1], 'int64', len(array.vars))
    helper = LayerHelper('array_length')
    out = helper.create_variable_for_type_inference('int64')
    out.shape = (1,)
    helper.append_op(type='array_length', inputs={'A': array.var},
                     outputs={'Out': out}, attrs={}, infer_shape=False)
    return out


# ----------------------------------------------------------- While

class While(object):
    """While loop over a sub-block, lowered to lax.while_loop.

    Usage parity with reference control_flow.py While:
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            ...   # must update `cond` via layers.assign/less_than(cond=...)
    Vars written in the body that exist before the loop become loop
    carries.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper('while', name=name)

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            prog = default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                parent.append_op(
                    type='while',
                    inputs={'Condition': self.cond_var},
                    outputs={},
                    attrs={'sub_block': sub.idx},
                    infer_shape=False)
        return cm()


class ConditionalBlock(object):
    """Run a sub-block only when a boolean condition holds.

    Parity: reference control_flow.py ConditionalBlock /
    paddle/fluid/operators/conditional_block_op.cc.  Lowered to `lax.cond`
    over the vars the body writes (core/control_flow_exec.py) — the false
    branch passes them through unchanged, so vars assigned in the body must
    exist beforehand to be visible after the block.
    """

    def __init__(self, inputs, is_scalar_condition=False, name=None):
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self.cond_vars = list(inputs)
        self.helper = LayerHelper('conditional_block', name=name)

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            prog = default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                cond = self.cond_vars[0]
                if len(self.cond_vars) > 1:
                    for c in self.cond_vars[1:]:
                        cond = nn_layers.logical_and(cond, c)
                parent.append_op(
                    type='conditional_block',
                    inputs={'Condition': cond},
                    outputs={},
                    attrs={'sub_block': sub.idx},
                    infer_shape=False)
        return cm()


class Switch(object):
    """Mutually-exclusive cases (ref Switch).  Branch-free lowering: each
    case body runs and results blend via masks — all cases must write the
    same output vars via layers.assign."""

    def __init__(self, name=None):
        self.helper = LayerHelper('switch', name=name)
        self._cases = []
        self._assigns = []  # (cond or None, [(target, value)])
        self._current = None

    def case(self, condition):
        return _SwitchCase(self, condition)

    def default(self):
        return _SwitchCase(self, None)


class _SwitchCase(object):
    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition

    def __enter__(self):
        _switch_stack.append((self.switch, self.condition))
        return self

    def __exit__(self, *a):
        _switch_stack.pop()
        return False


_switch_stack = []


def _in_switch_assign(output, value):
    """Blend `value` into `output` under the innermost active switch case."""
    sw, cond = _switch_stack[-1]
    if cond is None:
        # default: apply where no previous case hit
        taken = None
        for prev_cond in sw._cases:
            taken = prev_cond if taken is None else \
                nn_layers.logical_or(taken, prev_cond)
        if taken is None:
            tensor_layers.assign(value, output)
            return
        mask = tensor_layers.cast(nn_layers.logical_not(taken), 'float32')
    else:
        sw._cases.append(cond)
        mask = tensor_layers.cast(cond, 'float32')
    blended = mask * value + (1.0 - mask) * output
    tensor_layers.assign(blended, output)


# patch tensor.assign to respect active switch scope
_orig_assign = tensor_layers.assign


def _switch_aware_assign(input, output=None):
    if _switch_stack and output is not None:
        _in_switch_assign(output, input)
        return output
    return _orig_assign(input, output)


tensor_layers.assign = _switch_aware_assign


class IfElse(object):
    def __init__(self, cond, name=None):
        raise NotImplementedError(
            'IfElse: use branch-free masking (layers.Switch) or build two '
            'programs; data-dependent subgraph selection does not map to '
            'one XLA executable')


class StaticRNN(object):
    """Unrolled RNN over a fixed number of steps (ref StaticRNN).

    TPU-native: memories are python-tracked; step ops append normally and
    the unroll happens at graph level (XLA fuses the unrolled steps).
    """

    def __init__(self, name=None):
        self.helper = LayerHelper('static_rnn', name=name)
        self._mems = []  # (mem_var_current, init)
        self._outputs = []
        self._seq_len = None
        self._step_idx = None
        self._in_rnn = False
        self._step_inputs = []
        self._mem_map = {}

    def step(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            self._in_rnn = True
            yield
            self._in_rnn = False
        return cm()

    def step_input(self, x):
        # x: [B, T, ...] → per-step slices handled by unroll at graph level
        self._seq_len = x.shape[1]
        self._step_inputs.append(x)
        return x

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is None:
            init = tensor_layers.fill_constant_batch_size_like(
                batch_ref, [0] + list(shape), 'float32', init_value)
        self._mem_map[id(init)] = init
        return init

    def update_memory(self, mem, var):
        pass  # graph-level unrolling handles chaining

    def output(self, *outputs):
        self._outputs.extend(outputs)

    def __call__(self):
        return self._outputs if len(self._outputs) > 1 else self._outputs[0]


class DynamicRNN(object):
    def __init__(self, name=None):
        raise NotImplementedError(
            'DynamicRNN: use dynamic_lstm/dynamic_gru (lax.scan-based) '
            'layers; arbitrary per-step Python bodies over ragged batches '
            'do not map to a single XLA loop. See SURVEY.md §2.2.')


def reorder_lod_tensor_by_rank(x, rank_table):
    # padded representation never reorders rows for efficiency
    return x


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase='both'):
    """Debug print via jax.debug.print at lowering (ref print_op)."""
    helper = LayerHelper('print')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='print', inputs={'X': input},
                     outputs={'Out': out},
                     attrs={'message': message or ''})
    return out
