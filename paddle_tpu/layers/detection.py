"""Detection layers.

Parity: reference python/paddle/fluid/layers/detection.py.
"""
import numpy as np

from ..core.layer_helper import LayerHelper
from . import nn
from . import tensor as tensor_layers

__all__ = ['prior_box', 'density_prior_box', 'multi_box_head',
           'bipartite_match', 'target_assign', 'detection_output', 'ssd_loss',
           'detection_map', 'rpn_target_assign', 'anchor_generator',
           'roi_perspective_transform', 'generate_proposal_labels',
           'generate_proposals', 'generate_mask_labels', 'iou_similarity',
           'box_coder', 'polygon_box_transform', 'yolov3_loss',
           'multiclass_nms']


def iou_similarity(x, y, name=None):
    helper = LayerHelper('iou_similarity', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='iou_similarity', inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True,
              name=None):
    helper = LayerHelper('box_coder', name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    ins = {'PriorBox': prior_box, 'TargetBox': target_box}
    if prior_box_var is not None:
        ins['PriorBoxVar'] = prior_box_var
    helper.append_op(type='box_coder', inputs=ins,
                     outputs={'OutputBox': out},
                     attrs={'code_type': code_type,
                            'box_normalized': box_normalized})
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper('prior_box', name=name)
    box = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='prior_box',
                     inputs={'Input': input, 'Image': image},
                     outputs={'Boxes': box, 'Variances': var},
                     attrs={'min_sizes': list(min_sizes),
                            'max_sizes': list(max_sizes or []),
                            'aspect_ratios': list(aspect_ratios),
                            'variances': list(variance), 'flip': flip,
                            'clip': clip, 'step_w': steps[0],
                            'step_h': steps[1], 'offset': offset})
    return box, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=[0.1, 0.1, 0.2, 0.2],
                      clip=False, steps=[0.0, 0.0], offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper('density_prior_box', name=name)
    box = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='density_prior_box',
                     inputs={'Input': input, 'Image': image},
                     outputs={'Boxes': box, 'Variances': var},
                     attrs={'densities': list(densities),
                            'fixed_sizes': list(fixed_sizes),
                            'fixed_ratios': list(fixed_ratios),
                            'variances': list(variance), 'clip': clip,
                            'offset': offset})
    if flatten_to_2d:
        box = nn.reshape(box, [-1, 4])
        var = nn.reshape(var, [-1, 4])
    return box, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper('anchor_generator', name=name)
    anchor = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='anchor_generator', inputs={'Input': input},
                     outputs={'Anchors': anchor, 'Variances': var},
                     attrs={'anchor_sizes': list(anchor_sizes),
                            'aspect_ratios': list(aspect_ratios),
                            'variances': list(variance),
                            'stride': list(stride), 'offset': offset})
    return anchor, var


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper('bipartite_match', name=name)
    match_indices = helper.create_variable_for_type_inference('int32')
    match_distance = helper.create_variable_for_type_inference(
        dist_matrix.dtype)
    helper.append_op(type='bipartite_match',
                     inputs={'DistMat': dist_matrix},
                     outputs={'ColToRowMatchIndices': match_indices,
                              'ColToRowMatchDist': match_distance},
                     attrs={'match_type': match_type or 'bipartite'})
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper('target_assign', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference('float32')
    helper.append_op(type='target_assign',
                     inputs={'X': input, 'MatchIndices': matched_indices},
                     outputs={'Out': out, 'OutWeight': out_weight},
                     attrs={'mismatch_value': mismatch_value})
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold=0.01, nms_top_k=-1,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    helper = LayerHelper('multiclass_nms', name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(type='multiclass_nms',
                     inputs={'BBoxes': bboxes, 'Scores': scores},
                     outputs={'Out': out},
                     attrs={'score_threshold': score_threshold,
                            'nms_threshold': nms_threshold,
                            'keep_top_k': keep_top_k,
                            'background_label': background_label})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type='decode_center_size')
    sm = nn.softmax(scores)
    sm_t = nn.transpose(sm, perm=[0, 2, 1])
    return multiclass_nms(decoded, sm_t, score_threshold=score_threshold,
                          nms_threshold=nms_threshold, keep_top_k=keep_top_k,
                          background_label=background_label)


def yolov3_loss(x, gtbox, gtlabel, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, name=None):
    helper = LayerHelper('yolov3_loss', name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='yolov3_loss',
                     inputs={'X': x, 'GTBox': gtbox, 'GTLabel': gtlabel},
                     outputs={'Loss': loss},
                     attrs={'anchors': list(anchors),
                            'anchor_mask': list(anchor_mask),
                            'class_num': class_num,
                            'ignore_thresh': ignore_thresh,
                            'downsample_ratio': downsample_ratio})
    return loss


def polygon_box_transform(input, name=None):
    helper = LayerHelper('polygon_box_transform', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='polygon_box_transform', inputs={'Input': input},
                     outputs={'Output': out}, attrs={})
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head over multiple feature maps (ref detection.py)."""
    if min_sizes is None:
        num_layer = len(inputs)
        min_sizes = []
        max_sizes = []
        step = int(np.floor((max_ratio - min_ratio) / (num_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.)
            max_sizes.append(base_size * (ratio + step) / 100.)
        min_sizes = [base_size * .10] + min_sizes
        max_sizes = [base_size * .20] + max_sizes
    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, inp in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) else aspect_ratios
        st = steps[i] if steps else [step_w or 0., step_h or 0.]
        if isinstance(st, (int, float)):
            st = [st, st]
        box, var = prior_box(inp, image, [mins] if np.isscalar(mins) else
                             mins, [maxs] if np.isscalar(maxs) else maxs,
                             list(ar), variance, flip, clip, st, offset)
        num_boxes = box.shape[2]
        loc = nn.conv2d(inp, num_boxes * 4, kernel_size, padding=pad,
                        stride=stride)
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = nn.reshape(loc, [0, -1, 4])
        conf = nn.conv2d(inp, num_boxes * num_classes, kernel_size,
                         padding=pad, stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = nn.reshape(conf, [0, -1, num_classes])
        boxes_l.append(nn.reshape(box, [-1, 4]))
        vars_l.append(nn.reshape(var, [-1, 4]))
        locs.append(loc)
        confs.append(conf)
    mbox_locs = tensor_layers.concat(locs, axis=1)
    mbox_confs = tensor_layers.concat(confs, axis=1)
    boxes = tensor_layers.concat(boxes_l, axis=0)
    variances = tensor_layers.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type='per_prediction',
             mining_type='max_negative', normalize=True,
             sample_size=None):
    """SSD multibox loss (ref detection.py ssd_loss) — batched dense
    formulation: match per image, hard-negative mine by top-k."""
    iou = iou_similarity(gt_box, prior_box)
    matched, _ = bipartite_match(iou)
    loc_tgt, loc_w = target_assign(gt_box, matched, mismatch_value=0)
    lbl_tgt, conf_w = target_assign(gt_label, matched,
                                    mismatch_value=background_label)
    loc_loss = nn.smooth_l1(location, nn.reshape(loc_tgt, [0, -1, 4])
                            if False else loc_tgt)
    conf_loss = nn.softmax_with_cross_entropy(
        confidence, tensor_layers.cast(lbl_tgt, 'int64'))
    loss = loc_loss_weight * nn.reduce_sum(loc_loss) + \
        conf_loss_weight * nn.reduce_sum(conf_loss)
    return loss


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version='integral', detect_count=None,
                  label_count=None):
    """Batch mAP (ref layers/detection.py detection_map; op semantics from
    operators/detection/detection_map_op.h).  detect_res [B, Nd, 6]
    (label, score, box), label [B, Ng, 5 or 6]; optional per-image counts
    mask padding.  Cross-batch accumulation lives in
    evaluator.DetectionMAP."""
    helper = LayerHelper('detection_map')
    m = helper.create_variable_for_type_inference('float32')
    ins = {'DetectRes': detect_res, 'Label': label}
    if detect_count is not None:
        ins['DetectCount'] = detect_count
    if label_count is not None:
        ins['LabelCount'] = label_count
    helper.append_op(
        type='detection_map', inputs=ins, outputs={'MAP': m},
        attrs={'class_num': class_num, 'background_label': background_label,
               'overlap_threshold': overlap_threshold,
               'evaluate_difficult': evaluate_difficult,
               'ap_version': ap_version})
    return m


def rpn_target_assign(*args, **kwargs):
    raise NotImplementedError(
        'rpn_target_assign: RCNN proposal target assignment is host-side '
        'preprocessing in this framework; see SURVEY.md §2.2')


def generate_proposals(*args, **kwargs):
    raise NotImplementedError(
        'generate_proposals: variable-count proposals are not '
        'XLA-compatible; use multiclass_nms fixed-size path')


def generate_proposal_labels(*args, **kwargs):
    raise NotImplementedError('host-side preprocessing; SURVEY.md §2.2')


def generate_mask_labels(*args, **kwargs):
    raise NotImplementedError('host-side preprocessing; SURVEY.md §2.2')


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_batch=None):
    """Perspective-warp quad ROIs (R, 8) to fixed (th, tw) output.
    Ref: layers/detection.py roi_perspective_transform /
    operators/detection/roi_perspective_transform_op.cc."""
    helper = LayerHelper('roi_perspective_transform')
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {'X': input, 'ROIs': rois}
    if rois_batch is not None:
        ins['RoisBatch'] = rois_batch
    helper.append_op(type='roi_perspective_transform', inputs=ins,
                     outputs={'Out': out},
                     attrs={'transformed_height': transformed_height,
                            'transformed_width': transformed_width,
                            'spatial_scale': spatial_scale})
    return out
