"""Detection layers.

Parity: reference python/paddle/fluid/layers/detection.py.
"""
import numpy as np

from ..core.layer_helper import LayerHelper
from . import nn
from . import tensor as tensor_layers

__all__ = ['prior_box', 'density_prior_box', 'multi_box_head',
           'bipartite_match', 'target_assign', 'detection_output', 'ssd_loss',
           'detection_map', 'rpn_target_assign', 'anchor_generator',
           'roi_perspective_transform', 'generate_proposal_labels',
           'generate_proposals', 'generate_mask_labels', 'iou_similarity',
           'box_coder', 'polygon_box_transform', 'yolov3_loss',
           'multiclass_nms']


def iou_similarity(x, y, name=None):
    helper = LayerHelper('iou_similarity', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='iou_similarity', inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True,
              name=None):
    helper = LayerHelper('box_coder', name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    ins = {'PriorBox': prior_box, 'TargetBox': target_box}
    if prior_box_var is not None:
        ins['PriorBoxVar'] = prior_box_var
    helper.append_op(type='box_coder', inputs=ins,
                     outputs={'OutputBox': out},
                     attrs={'code_type': code_type,
                            'box_normalized': box_normalized})
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper('prior_box', name=name)
    box = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='prior_box',
                     inputs={'Input': input, 'Image': image},
                     outputs={'Boxes': box, 'Variances': var},
                     attrs={'min_sizes': list(min_sizes),
                            'max_sizes': list(max_sizes or []),
                            'aspect_ratios': list(aspect_ratios),
                            'variances': list(variance), 'flip': flip,
                            'clip': clip, 'step_w': steps[0],
                            'step_h': steps[1], 'offset': offset})
    return box, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=[0.1, 0.1, 0.2, 0.2],
                      clip=False, steps=[0.0, 0.0], offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper('density_prior_box', name=name)
    box = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='density_prior_box',
                     inputs={'Input': input, 'Image': image},
                     outputs={'Boxes': box, 'Variances': var},
                     attrs={'densities': list(densities),
                            'fixed_sizes': list(fixed_sizes),
                            'fixed_ratios': list(fixed_ratios),
                            'variances': list(variance), 'clip': clip,
                            'offset': offset})
    if flatten_to_2d:
        box = nn.reshape(box, [-1, 4])
        var = nn.reshape(var, [-1, 4])
    return box, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper('anchor_generator', name=name)
    anchor = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='anchor_generator', inputs={'Input': input},
                     outputs={'Anchors': anchor, 'Variances': var},
                     attrs={'anchor_sizes': list(anchor_sizes),
                            'aspect_ratios': list(aspect_ratios),
                            'variances': list(variance),
                            'stride': list(stride), 'offset': offset})
    return anchor, var


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper('bipartite_match', name=name)
    match_indices = helper.create_variable_for_type_inference('int32')
    match_distance = helper.create_variable_for_type_inference(
        dist_matrix.dtype)
    helper.append_op(type='bipartite_match',
                     inputs={'DistMat': dist_matrix},
                     outputs={'ColToRowMatchIndices': match_indices,
                              'ColToRowMatchDist': match_distance},
                     attrs={'match_type': match_type or 'bipartite'})
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper('target_assign', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference('float32')
    helper.append_op(type='target_assign',
                     inputs={'X': input, 'MatchIndices': matched_indices},
                     outputs={'Out': out, 'OutWeight': out_weight},
                     attrs={'mismatch_value': mismatch_value})
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold=0.01, nms_top_k=-1,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    helper = LayerHelper('multiclass_nms', name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(type='multiclass_nms',
                     inputs={'BBoxes': bboxes, 'Scores': scores},
                     outputs={'Out': out},
                     attrs={'score_threshold': score_threshold,
                            'nms_threshold': nms_threshold,
                            'keep_top_k': keep_top_k,
                            'background_label': background_label})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type='decode_center_size')
    sm = nn.softmax(scores)
    sm_t = nn.transpose(sm, perm=[0, 2, 1])
    return multiclass_nms(decoded, sm_t, score_threshold=score_threshold,
                          nms_threshold=nms_threshold, keep_top_k=keep_top_k,
                          background_label=background_label)


def yolov3_loss(x, gtbox, gtlabel, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, name=None):
    helper = LayerHelper('yolov3_loss', name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='yolov3_loss',
                     inputs={'X': x, 'GTBox': gtbox, 'GTLabel': gtlabel},
                     outputs={'Loss': loss},
                     attrs={'anchors': list(anchors),
                            'anchor_mask': list(anchor_mask),
                            'class_num': class_num,
                            'ignore_thresh': ignore_thresh,
                            'downsample_ratio': downsample_ratio})
    return loss


def polygon_box_transform(input, name=None):
    helper = LayerHelper('polygon_box_transform', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='polygon_box_transform', inputs={'Input': input},
                     outputs={'Output': out}, attrs={})
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head over multiple feature maps (ref detection.py)."""
    if min_sizes is None:
        num_layer = len(inputs)
        min_sizes = []
        max_sizes = []
        step = int(np.floor((max_ratio - min_ratio) / (num_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.)
            max_sizes.append(base_size * (ratio + step) / 100.)
        min_sizes = [base_size * .10] + min_sizes
        max_sizes = [base_size * .20] + max_sizes
    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, inp in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) else aspect_ratios
        st = steps[i] if steps else [step_w or 0., step_h or 0.]
        if isinstance(st, (int, float)):
            st = [st, st]
        box, var = prior_box(inp, image, [mins] if np.isscalar(mins) else
                             mins, [maxs] if np.isscalar(maxs) else maxs,
                             list(ar), variance, flip, clip, st, offset)
        num_boxes = box.shape[2]
        loc = nn.conv2d(inp, num_boxes * 4, kernel_size, padding=pad,
                        stride=stride)
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = nn.reshape(loc, [0, -1, 4])
        conf = nn.conv2d(inp, num_boxes * num_classes, kernel_size,
                         padding=pad, stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = nn.reshape(conf, [0, -1, num_classes])
        boxes_l.append(nn.reshape(box, [-1, 4]))
        vars_l.append(nn.reshape(var, [-1, 4]))
        locs.append(loc)
        confs.append(conf)
    mbox_locs = tensor_layers.concat(locs, axis=1)
    mbox_confs = tensor_layers.concat(confs, axis=1)
    boxes = tensor_layers.concat(boxes_l, axis=0)
    variances = tensor_layers.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type='per_prediction',
             mining_type='max_negative', normalize=True,
             sample_size=None):
    """SSD multibox loss (ref detection.py ssd_loss) — batched dense
    formulation: match per image, hard-negative mine by top-k."""
    iou = iou_similarity(gt_box, prior_box)
    matched, _ = bipartite_match(iou)
    loc_tgt, loc_w = target_assign(gt_box, matched, mismatch_value=0)
    lbl_tgt, conf_w = target_assign(gt_label, matched,
                                    mismatch_value=background_label)
    loc_loss = nn.smooth_l1(location, nn.reshape(loc_tgt, [0, -1, 4])
                            if False else loc_tgt)
    conf_loss = nn.softmax_with_cross_entropy(
        confidence, tensor_layers.cast(lbl_tgt, 'int64'))
    loss = loc_loss_weight * nn.reduce_sum(loc_loss) + \
        conf_loss_weight * nn.reduce_sum(conf_loss)
    return loss


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version='integral', detect_count=None,
                  label_count=None):
    """Batch mAP (ref layers/detection.py detection_map; op semantics from
    operators/detection/detection_map_op.h).  detect_res [B, Nd, 6]
    (label, score, box), label [B, Ng, 5 or 6]; optional per-image counts
    mask padding.  Cross-batch accumulation lives in
    evaluator.DetectionMAP."""
    helper = LayerHelper('detection_map')
    m = helper.create_variable_for_type_inference('float32')
    ins = {'DetectRes': detect_res, 'Label': label}
    if detect_count is not None:
        ins['DetectCount'] = detect_count
    if label_count is not None:
        ins['LabelCount'] = label_count
    helper.append_op(
        type='detection_map', inputs=ins, outputs={'MAP': m},
        attrs={'class_num': class_num, 'background_label': background_label,
               'overlap_threshold': overlap_threshold,
               'evaluate_difficult': evaluate_difficult,
               'ap_version': ap_version})
    return m


def _gt_length_input(ins, gt_boxes):
    from .nn import _len_var
    lv = _len_var(gt_boxes)
    if lv is not None:
        ins['GtLength'] = lv


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN anchor target assignment (ref layers/detection.py:55 /
    operators/detection/rpn_target_assign_op.cc).

    TPU-native: fixed-size outputs — K = rpn_batch_size_per_im score rows
    and Kf = K*rpn_fg_fraction location rows PER IMAGE (the reference
    returns ragged gathered rows).  `use_random` subsampling is replaced
    by deterministic top-K-by-IoU.  Rows that are padding or ignore-zone
    anchors carry target_label == -1: compute the cls loss with
    ignore_index=-1 (sigmoid_cross_entropy_with_logits supports it), and
    bbox_inside_weight zeroes fake location rows — with those masks the
    losses match the reference's sampled losses.  gt_boxes is the padded
    [N, G, 4] LoDTensor (lengths ride along).  Returns (predicted_scores,
    predicted_location, target_label, target_bbox, bbox_inside_weight)
    like the reference."""
    from .nn import gather, reshape
    helper = LayerHelper('rpn_target_assign')
    N = bbox_pred.shape[0] if bbox_pred.shape else -1
    K = rpn_batch_size_per_im
    Kf = max(1, int(K * rpn_fg_fraction))
    loc_index = helper.create_variable_for_type_inference('int32')
    score_index = helper.create_variable_for_type_inference('int32')
    target_label = helper.create_variable_for_type_inference('int32')
    target_bbox = helper.create_variable_for_type_inference(
        anchor_box.dtype)
    inside_w = helper.create_variable_for_type_inference(anchor_box.dtype)
    score_w = helper.create_variable_for_type_inference('float32')
    ins = {'Anchor': anchor_box, 'GtBoxes': gt_boxes}
    if is_crowd is not None:
        ins['IsCrowd'] = is_crowd
    if im_info is not None:
        ins['ImInfo'] = im_info
    _gt_length_input(ins, gt_boxes)
    helper.append_op(
        type='rpn_target_assign', inputs=ins,
        outputs={'LocationIndex': loc_index, 'ScoreIndex': score_index,
                 'TargetLabel': target_label, 'TargetBBox': target_bbox,
                 'BBoxInsideWeight': inside_w, 'ScoreWeight': score_w},
        attrs={'rpn_batch_size_per_im': rpn_batch_size_per_im,
               'rpn_straddle_thresh': rpn_straddle_thresh,
               'rpn_positive_overlap': rpn_positive_overlap,
               'rpn_negative_overlap': rpn_negative_overlap,
               'rpn_fg_fraction': rpn_fg_fraction,
               'use_random': use_random},
        infer_shape=False)
    for v, shp in ((loc_index, (N, Kf)), (score_index, (N, K)),
                   (target_label, (N, K, 1)), (target_bbox, (N, Kf, 4)),
                   (inside_w, (N, Kf, 4)), (score_w, (N, K, 1))):
        v.shape = shp
        v.stop_gradient = True
    # gather the predictions at the sampled rows, batched
    pred_scores = _batched_row_gather(cls_logits, score_index, 1)
    pred_loc = _batched_row_gather(bbox_pred, loc_index, 4)
    return pred_scores, pred_loc, target_label, target_bbox, inside_w


def _batched_row_gather(x, idx, feat):
    """x [N, M, feat], idx [N, K] -> [N, K, feat] via a gather op."""
    helper = LayerHelper('rcnn_gather')
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='batched_gather', inputs={'X': x, 'Index': idx},
                     outputs={'Out': out}, attrs={}, infer_shape=False)
    out.shape = (x.shape[0] if x.shape else -1,
                 idx.shape[1] if idx.shape else -1, feat)
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """Faster-RCNN proposal generation (ref layers/detection.py:1878 /
    operators/detection/generate_proposals_op.cc): decode deltas at
    anchors, clip to image, drop tiny boxes, NMS.  Fixed-size output
    [N, post_nms_top_n, 4] + probs (invalid rows prob 0) instead of the
    reference's ragged LoD rois."""
    helper = LayerHelper('generate_proposals')
    rois = helper.create_variable_for_type_inference(bbox_deltas.dtype)
    probs = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        type='generate_proposals',
        inputs={'Scores': scores, 'BboxDeltas': bbox_deltas,
                'ImInfo': im_info, 'Anchors': anchors,
                'Variances': variances},
        outputs={'RpnRois': rois, 'RpnRoiProbs': probs},
        attrs={'pre_nms_topN': pre_nms_top_n,
               'post_nms_topN': post_nms_top_n,
               'nms_thresh': nms_thresh, 'min_size': min_size,
               'eta': eta},
        infer_shape=False)
    N = scores.shape[0] if scores.shape else -1
    rois.shape = (N, post_nms_top_n, 4)
    probs.shape = (N, post_nms_top_n, 1)
    rois.stop_gradient = True
    probs.stop_gradient = True
    return rois, probs


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info=None, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True):
    """Fast-RCNN RoI targets (ref layers/detection.py:1649 /
    generate_proposal_labels_op.cc): label proposals by best-IoU gt,
    fixed batch_size_per_im rows per image with class-slotted bbox
    targets; deterministic top-K stands in for host RNG sampling."""
    helper = LayerHelper('generate_proposal_labels')
    class_nums = class_nums or 81
    rois = helper.create_variable_for_type_inference(rpn_rois.dtype)
    labels = helper.create_variable_for_type_inference('int32')
    tgt = helper.create_variable_for_type_inference(rpn_rois.dtype)
    in_w = helper.create_variable_for_type_inference(rpn_rois.dtype)
    out_w = helper.create_variable_for_type_inference(rpn_rois.dtype)
    ins = {'RpnRois': rpn_rois, 'GtClasses': gt_classes,
           'GtBoxes': gt_boxes}
    if is_crowd is not None:
        ins['IsCrowd'] = is_crowd
    if im_info is not None:
        ins['ImInfo'] = im_info
    _gt_length_input(ins, gt_boxes)
    helper.append_op(
        type='generate_proposal_labels', inputs=ins,
        outputs={'Rois': rois, 'LabelsInt32': labels, 'BboxTargets': tgt,
                 'BboxInsideWeights': in_w, 'BboxOutsideWeights': out_w},
        attrs={'batch_size_per_im': batch_size_per_im,
               'fg_fraction': fg_fraction, 'fg_thresh': fg_thresh,
               'bg_thresh_hi': bg_thresh_hi, 'bg_thresh_lo': bg_thresh_lo,
               'bbox_reg_weights': list(bbox_reg_weights),
               'class_nums': class_nums, 'use_random': use_random},
        infer_shape=False)
    N = rpn_rois.shape[0] if rpn_rois.shape else -1
    B = batch_size_per_im
    for v, shp in ((rois, (N, B, 4)), (labels, (N, B, 1)),
                   (tgt, (N, B, 4 * class_nums)),
                   (in_w, (N, B, 4 * class_nums)),
                   (out_w, (N, B, 4 * class_nums))):
        v.shape = shp
        v.stop_gradient = True
    return rois, labels, tgt, in_w, out_w


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         roi_gt_index=None):
    """Mask-RCNN mask targets (ref layers/detection.py:1744 /
    generate_mask_labels_op.cc).  gt_segms is ONE padded polygon per
    instance [N, G, P, 2] (the reference accepts multi-polygon LoD);
    rasterization is a vectorized even-odd crossing test on the
    resolution grid.  `roi_gt_index` [N, B, 1] maps each roi to its gt
    (as produced alongside generate_proposal_labels)."""
    helper = LayerHelper('generate_mask_labels')
    if roi_gt_index is None:
        raise ValueError('generate_mask_labels needs roi_gt_index '
                         '(matched gt per roi)')
    mask_rois = helper.create_variable_for_type_inference(rois.dtype)
    has_mask = helper.create_variable_for_type_inference('int32')
    mask = helper.create_variable_for_type_inference('int32')
    helper.append_op(
        type='generate_mask_labels',
        inputs={'Rois': rois, 'LabelsInt32': labels_int32,
                'GtSegms': gt_segms, 'RoiGtIndex': roi_gt_index},
        outputs={'MaskRois': mask_rois, 'RoiHasMaskInt32': has_mask,
                 'MaskInt32': mask},
        attrs={'num_classes': num_classes, 'resolution': resolution},
        infer_shape=False)
    N = rois.shape[0] if rois.shape else -1
    B = rois.shape[1] if rois.shape else -1
    mask_rois.shape = (N, B, 4)
    has_mask.shape = (N, B, 1)
    mask.shape = (N, B, num_classes * resolution * resolution)
    for v in (mask_rois, has_mask, mask):
        v.stop_gradient = True
    return mask_rois, has_mask, mask


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_batch=None):
    """Perspective-warp quad ROIs (R, 8) to fixed (th, tw) output.
    Ref: layers/detection.py roi_perspective_transform /
    operators/detection/roi_perspective_transform_op.cc."""
    helper = LayerHelper('roi_perspective_transform')
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {'X': input, 'ROIs': rois}
    if rois_batch is not None:
        ins['RoisBatch'] = rois_batch
    helper.append_op(type='roi_perspective_transform', inputs=ins,
                     outputs={'Out': out},
                     attrs={'transformed_height': transformed_height,
                            'transformed_width': transformed_width,
                            'spatial_scale': spatial_scale})
    return out
