"""Layer codegen utilities.

Parity: reference layers/layer_function_generator.py, which generates
thin layer wrappers + docstrings from C++ OpProto descriptors.  There
are no OpProtos here — ops are pure-JAX impls in the registry — so
`generate_layer_fn` builds the wrapper from the registry entry instead:
single-output ops get a `fn(x, ..., name=None) -> Variable` that appends
the op.  The doc decorators are kept as identity-with-annotation so
reference code importing them keeps working.
"""
import functools
import warnings

from ..core import registry
from ..core.layer_helper import LayerHelper

__all__ = ['deprecated', 'generate_layer_fn', 'generate_layer_fn_noattr',
           'autodoc', 'templatedoc']


def deprecated(since, instead, extra_message=''):
    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(
                '%s is deprecated since %s, use %s instead. %s'
                % (func.__name__, since, instead, extra_message),
                DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)
        return wrapper
    return decorator


def autodoc(comment=''):
    def decorator(func):
        func.__doc__ = (comment + '\n' + (func.__doc__ or '')).strip()
        return func
    return decorator


def templatedoc(op_type=None):
    """The reference fills ${comment} placeholders from OpProto; there
    is no proto, so the docstring is left as written."""
    def decorator(func):
        return func
    return decorator


def _make(op_type, single_input_slot, out_slot):
    if not registry.has_op(op_type):
        raise ValueError('cannot generate a layer for unregistered op %r'
                         % op_type)

    def layer_fn(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type,
                         inputs={single_input_slot: x},
                         outputs={out_slot: out}, attrs=attrs)
        return out

    layer_fn.__name__ = op_type
    layer_fn.__doc__ = ('Generated layer for the registered op %r '
                        '(single input %r -> output %r).'
                        % (op_type, single_input_slot, out_slot))
    return layer_fn


def generate_layer_fn(op_type):
    """Build `fn(x, **attrs) -> out` for a registered single-input op
    (reference generate_layer_fn, minus OpProto introspection: input
    slot 'X' and output slot 'Out' by convention)."""
    return _make(op_type, 'X', 'Out')


def generate_layer_fn_noattr(op_type):
    return _make(op_type, 'X', 'Out')
