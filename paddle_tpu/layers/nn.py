"""fluid.layers.nn equivalents — the main model-building API.

Parity: reference python/paddle/fluid/layers/nn.py (151 public functions).
Each function builds graph ops; lowering is whole-block to XLA
(core/executor.py).  Sequence layers operate on padded [B, T, ...] + length
vars (see layers/io.py data(lod_level>0)).
"""
import numpy as np

from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from ..core.lod import LENGTH_SUFFIX
from ..param_attr import ParamAttr
from ..initializer import Constant, Normal, Xavier

__all__ = [
    'fc', 'embedding', 'dynamic_lstm', 'dynamic_lstmp', 'dynamic_gru',
    'gru_unit', 'lstm', 'lstm_unit', 'conv2d', 'conv3d', 'conv2d_transpose',
    'conv3d_transpose', 'pool2d', 'pool3d', 'adaptive_pool2d',
    'adaptive_pool3d', 'batch_norm', 'data_norm', 'layer_norm', 'group_norm',
    'softmax', 'softmax_with_cross_entropy', 'cross_entropy', 'bpr_loss',
    'square_error_cost', 'cos_sim', 'dropout', 'split', 'matmul', 'topk',
    'transpose', 'reshape', 'squeeze', 'unsqueeze', 'reduce_sum',
    'reduce_mean', 'reduce_max', 'reduce_min', 'reduce_prod', 'l2_normalize',
    'one_hot', 'lrn', 'pad', 'pad2d', 'pad_constant_like', 'label_smooth',
    'image_resize', 'image_resize_short', 'resize_bilinear', 'resize_nearest',
    'gather', 'scatter', 'random_crop', 'crop', 'relu', 'log', 'mean', 'mul',
    'sigmoid_cross_entropy_with_logits', 'smooth_l1', 'huber_loss',
    'log_loss', 'rank_loss', 'margin_rank_loss', 'nce', 'hsigmoid',
    'multiplex', 'flatten', 'stack', 'unstack', 'expand', 'scale',
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min',
    'elementwise_pow', 'clip', 'clip_by_norm', 'slice', 'shape',
    'logical_and', 'logical_or', 'logical_xor', 'logical_not', 'maxout',
    'space_to_depth', 'affine_grid', 'affine_channel', 'grid_sampler',
    'add_position_encoding', 'bilinear_tensor_product', 'prelu', 'brelu',
    'leaky_relu', 'soft_relu', 'elu', 'relu6', 'pow', 'stanh',
    'hard_sigmoid', 'swish', 'selu', 'mean_iou', 'dice_loss', 'im2sequence',
    'row_conv', 'uniform_random_batch_size_like', 'gaussian_random',
    'sampling_id', 'gaussian_random_batch_size_like', 'sum',
    'shuffle_channel', 'similarity_focus', 'hash', 'lod_reset',
    'autoincreased_step_counter', 'py_func',
    'merge_selected_rows', 'get_tensor_from_selected_rows',
    # sequence family
    'sequence_conv', 'sequence_pool', 'sequence_softmax', 'sequence_expand',
    'sequence_expand_as', 'sequence_pad', 'sequence_unpad',
    'sequence_first_step', 'sequence_last_step', 'sequence_slice',
    'sequence_reshape', 'sequence_scatter', 'sequence_mask',
    'sequence_enumerate', 'sequence_concat', 'sequence_reverse',
    'sequence_erase',
    'warpctc', 'ctc_greedy_decoder', 'edit_distance', 'chunk_eval',
    'flash_attention', 'ring_attention', 'rms_norm', 'rope',
    'sample_tokens',
    'linear_chain_crf', 'crf_decoding', 'one_hot', 'group_norm',
    'teacher_student_sigmoid_loss', 'roi_pool', 'roi_align', 'psroi_pool',
    'conv_shift', 'tree_conv', 'beam_search', 'beam_search_decode',
]


def _prod(xs):
    return int(np.prod([int(x) for x in xs])) if len(xs) else 1


def _copy_lod(x, out):
    if isinstance(x, Variable) and x.lod_level > 0:
        out.lod_level = x.lod_level
        out.lod_length_name = getattr(x, 'lod_length_name', None)


def _len_var(x):
    """The companion int32 lengths Variable of a lod var, or None."""
    name = getattr(x, 'lod_length_name', None)
    if name is None and x.lod_level > 0:
        name = x.name + LENGTH_SUFFIX
    if name is None:
        return None
    try:
        return x.block.var(name)
    except ValueError:
        return None


def _seq_inputs(x, extra=None):
    ins = {'X': x}
    lv = _len_var(x)
    if lv is not None:
        ins['Length'] = lv
    if extra:
        ins.update(extra)
    return ins


# ------------------------------------------------------------------ fc

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None, amp_keep_bf16=False):
    """Reference nn.py fc: y = act(x W + b); lowers to one MXU GEMM.
    On padded sequence input [B, T, D] the weight applies per-token.
    amp_keep_bf16 (TPU extension): under AMP, keep the GEMM output in
    bf16 instead of casting back to f32 — for projections whose
    consumers upcast internally (softmax_with_cross_entropy), halving
    the output buffer's HBM traffic in both directions of autodiff."""
    helper = LayerHelper('fc', input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    inputs = helper.multiple_input()
    param_attrs = helper.multiple_param_attr(len(inputs))
    mul_results = []
    ncd_final = num_flatten_dims
    for input_var, p_attr in zip(inputs, param_attrs):
        ncd = num_flatten_dims + (1 if input_var.lod_level > 0 else 0)
        ncd_final = ncd
        input_shape = input_var.shape
        param_shape = [_prod(input_shape[ncd:]), size]
        w = helper.create_parameter(p_attr, param_shape, dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type='mul', inputs={'X': input_var, 'Y': w},
                         outputs={'Out': tmp},
                         attrs={'x_num_col_dims': ncd, 'y_num_col_dims': 1,
                                'amp_keep_bf16': amp_keep_bf16})
        _copy_lod(input_var, tmp)
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type='sum', inputs={'X': mul_results},
                         outputs={'Out': pre_bias}, attrs={})
        _copy_lod(inputs[0], pre_bias)
    pre_act = helper.append_bias_op(pre_bias, dim_start=ncd_final)
    _copy_lod(inputs[0], pre_act)
    out = helper.append_activation(pre_act)
    _copy_lod(inputs[0], out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype='float32'):
    """Reference nn.py embedding / lookup_table_op.  is_sparse is a no-op:
    on TPU dense gathers are fast and the table can be mesh-sharded
    (parallel/sharded_embedding.py)."""
    helper = LayerHelper('embedding', param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, size, dtype,
                                default_initializer=Xavier())
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = -1 if padding_idx is None else \
        (padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(type='lookup_table',
                     inputs={'W': w, 'Ids': input},
                     outputs={'Out': out},
                     attrs={'padding_idx': padding_idx,
                            'is_sparse': is_sparse})
    _copy_lod(input, out)
    return out


# ------------------------------------------------------------------ RNN

def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation='sigmoid', cell_activation='tanh',
                 candidate_activation='tanh', dtype='float32', name=None):
    """Reference dynamic_lstm (lstm_op): input is pre-projected [B,T,4D];
    size = 4*D.  Lowered to a lax.scan recurrence with per-step masking."""
    helper = LayerHelper('lstm', param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    D = size // 4
    weight = helper.create_parameter(helper.param_attr, [D, 4 * D], dtype)
    bias_size = [1, 7 * D] if use_peepholes else [1, 4 * D]
    bias = helper.create_parameter(helper.bias_attr, bias_size, dtype,
                                   is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    ins = _seq_inputs(input)
    ins = {'Input': ins['X'], 'Weight': weight, 'Bias': bias}
    lv = _len_var(input)
    if lv is not None:
        ins['Length'] = lv
    if h_0 is not None:
        ins['H0'] = h_0
    if c_0 is not None:
        ins['C0'] = c_0
    helper.append_op(type='lstm', inputs=ins,
                     outputs={'Hidden': hidden, 'Cell': cell},
                     attrs={'use_peepholes': use_peepholes,
                            'is_reverse': is_reverse,
                            'gate_activation': gate_activation,
                            'cell_activation': cell_activation,
                            'candidate_activation': candidate_activation})
    _copy_lod(input, hidden)
    _copy_lod(input, cell)
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation='sigmoid', cell_activation='tanh',
                  candidate_activation='tanh', proj_activation='tanh',
                  dtype='float32', name=None):
    """LSTM with recurrent projection (ref lstmp_op)."""
    helper = LayerHelper('lstmp', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = size // 4
    weight = helper.create_parameter(helper.param_attr,
                                     [proj_size, 4 * D], dtype)
    proj_attr = ParamAttr._to_attr(param_attr)
    if proj_attr.name is not None:
        # a named param_attr must not alias weight and proj_weight
        proj_attr = ParamAttr(name=proj_attr.name + '_proj',
                              initializer=proj_attr.initializer)
    proj_weight = helper.create_parameter(proj_attr, [D, proj_size], dtype)
    bias_size = [1, 7 * D] if use_peepholes else [1, 4 * D]
    bias = helper.create_parameter(helper.bias_attr, bias_size, dtype,
                                   is_bias=True)
    projection = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    ins = {'Input': input, 'Weight': weight, 'ProjWeight': proj_weight,
           'Bias': bias}
    lv = _len_var(input)
    if lv is not None:
        ins['Length'] = lv
    helper.append_op(type='lstmp', inputs=ins,
                     outputs={'Projection': projection, 'Cell': cell},
                     attrs={'use_peepholes': use_peepholes,
                            'is_reverse': is_reverse,
                            'gate_activation': gate_activation,
                            'cell_activation': cell_activation,
                            'candidate_activation': candidate_activation,
                            'proj_activation': proj_activation})
    _copy_lod(input, projection)
    _copy_lod(input, cell)
    return projection, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation='sigmoid',
                candidate_activation='tanh', h_0=None, name=None):
    helper = LayerHelper('gru', param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dtype = input.dtype
    weight = helper.create_parameter(helper.param_attr, [size, 3 * size],
                                     dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * size], dtype,
                                   is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    ins = {'Input': input, 'Weight': weight, 'Bias': bias}
    lv = _len_var(input)
    if lv is not None:
        ins['Length'] = lv
    if h_0 is not None:
        ins['H0'] = h_0
    helper.append_op(type='gru', inputs=ins, outputs={'Hidden': hidden},
                     attrs={'is_reverse': is_reverse,
                            'gate_activation': gate_activation,
                            'activation': candidate_activation})
    _copy_lod(input, hidden)
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation='tanh', gate_activation='sigmoid', name=None):
    helper = LayerHelper('gru_unit', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    D = size // 3
    weight = helper.create_parameter(helper.param_attr, [D, 3 * D], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * D], dtype,
                                   is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden = helper.create_variable_for_type_inference(dtype)
    updated = helper.create_variable_for_type_inference(dtype)
    act_map = {'identity': 0, 'sigmoid': 1, 'tanh': 2, 'relu': 3}
    helper.append_op(type='gru_unit',
                     inputs={'Input': input, 'HiddenPrev': hidden,
                             'Weight': weight, 'Bias': bias},
                     outputs={'Hidden': updated, 'Gate': gate,
                              'ResetHiddenPrev': reset_hidden},
                     attrs={'activation': act_map[activation],
                            'gate_activation': act_map[gate_activation]})
    return updated, reset_hidden, gate


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer (cudnn-style) LSTM, ref nn.py lstm().  Stacked scans."""
    x = input
    last_hs, last_cs = [], []
    for layer in range(num_layers):
        directions = []
        for rev in ([False, True] if is_bidirec else [False]):
            proj = fc(x, 4 * hidden_size, num_flatten_dims=2,
                      bias_attr=False)
            h, c = dynamic_lstm(proj, 4 * hidden_size, use_peepholes=False,
                                is_reverse=rev)
            directions.append((h, c))
        if is_bidirec:
            from .tensor import concat
            x = concat([directions[0][0], directions[1][0]], axis=2)
        else:
            x = directions[0][0]
        if dropout_prob > 0.0 and not is_test:
            x = dropout(x, dropout_prob,
                        dropout_implementation='upscale_in_train')
        last_hs.append(directions[0][0])
        last_cs.append(directions[0][1])
    return x, last_hs[-1], last_cs[-1]


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper('lstm_unit', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = x_t.dtype
    size = cell_t_prev.shape[1]
    from .tensor import concat
    concat_in = concat([x_t, hidden_t_prev], axis=1)
    fc_out = fc(concat_in, 4 * size, param_attr=param_attr,
                bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(dtype)
    h = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='lstm_unit',
                     inputs={'X': fc_out, 'C_prev': cell_t_prev},
                     outputs={'C': c, 'H': h},
                     attrs={'forget_bias': forget_bias})
    return h, c


# ------------------------------------------------------------------ conv

def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper('conv2d', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) else \
        list(dilation)
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    fan_in = (num_channels // groups) * _prod(filter_size)
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(helper.param_attr, filter_shape, dtype,
                                default_initializer=Normal(0.0, std))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='conv2d',
                     inputs={'Input': input, 'Filter': w},
                     outputs={'Output': pre_bias},
                     attrs={'strides': stride, 'paddings': padding,
                            'dilations': dilation, 'groups': groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper('conv3d', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1

    def triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)
    filter_size = triple(filter_size)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    fan_in = (num_channels // groups) * _prod(filter_size)
    w = helper.create_parameter(helper.param_attr, filter_shape, dtype,
                                default_initializer=Normal(
                                    0.0, (2.0 / fan_in) ** 0.5))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='conv3d',
                     inputs={'Input': input, 'Filter': w},
                     outputs={'Output': pre_bias},
                     attrs={'strides': triple(stride),
                            'paddings': triple(padding),
                            'dilations': triple(dilation), 'groups': groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper('conv2d_transpose', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation] * 2 if isinstance(dilation, int) else list(dilation)
    if filter_size is None:
        h_in, w_in = input.shape[2], input.shape[3]
        out_size = [output_size] * 2 if isinstance(output_size, int) else \
            list(output_size)
        filter_size = [
            (out_size[0] - (h_in - 1) * stride[0] + 2 * padding[0] - 1) //
            dilation[0] + 1,
            (out_size[1] - (w_in - 1) * stride[1] + 2 * padding[1] - 1) //
            dilation[1] + 1]
    elif isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='conv2d_transpose',
                     inputs={'Input': input, 'Filter': w},
                     outputs={'Output': pre_bias},
                     attrs={'strides': stride, 'paddings': padding,
                            'dilations': dilation, 'groups': groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper('conv3d_transpose', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]

    def triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)
    filter_size = triple(filter_size)
    filter_shape = [num_channels, num_filters] + filter_size
    w = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='conv3d_transpose',
                     inputs={'Input': input, 'Filter': w},
                     outputs={'Output': pre_bias},
                     attrs={'strides': triple(stride),
                            'paddings': triple(padding),
                            'dilations': triple(dilation)})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


# ------------------------------------------------------------------ pool

def pool2d(input, pool_size=-1, pool_type='max', pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    helper = LayerHelper('pool2d', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)

    def pair(v):
        return [v, v] if isinstance(v, int) else list(v)
    helper.append_op(type='pool2d', inputs={'X': input}, outputs={'Out': out},
                     attrs={'pooling_type': pool_type,
                            'ksize': pair(pool_size),
                            'strides': pair(pool_stride),
                            'paddings': pair(pool_padding),
                            'global_pooling': global_pooling,
                            'ceil_mode': ceil_mode, 'exclusive': exclusive})
    return out


def pool3d(input, pool_size=-1, pool_type='max', pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    helper = LayerHelper('pool3d', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)

    def triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)
    helper.append_op(type='pool3d', inputs={'X': input}, outputs={'Out': out},
                     attrs={'pooling_type': pool_type,
                            'ksize': triple(pool_size),
                            'strides': triple(pool_stride),
                            'paddings': triple(pool_padding),
                            'global_pooling': global_pooling,
                            'ceil_mode': ceil_mode, 'exclusive': exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type='max', require_index=False,
                    name=None):
    helper = LayerHelper('adaptive_pool2d', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='adaptive_pool2d', inputs={'X': input},
                     outputs={'Out': out},
                     attrs={'ksize': pool_size if isinstance(
                         pool_size, (list, tuple)) else [pool_size] * 2,
                         'pooling_type': pool_type})
    return out


def adaptive_pool3d(input, pool_size, pool_type='max', require_index=False,
                    name=None):
    helper = LayerHelper('adaptive_pool3d', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='adaptive_pool3d', inputs={'X': input},
                     outputs={'Out': out},
                     attrs={'ksize': pool_size if isinstance(
                         pool_size, (list, tuple)) else [pool_size] * 3,
                         'pooling_type': pool_type})
    return out


# ------------------------------------------------------------------ norm

def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=
               False, fuse_with_relu=False, use_global_stats=False):
    helper = LayerHelper('batch_norm', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    channel_num = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    param_shape = [channel_num]
    scale = helper.create_parameter(helper.param_attr, param_shape, dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(helper.bias_attr, param_shape, dtype,
                                   is_bias=True)
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, initializer=Constant(0.0),
                  trainable=False), param_shape, dtype)
    mean.stop_gradient = True
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, initializer=Constant(1.0),
                  trainable=False), param_shape, dtype)
    variance.stop_gradient = True
    saved_mean = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='batch_norm',
                     inputs={'X': input, 'Scale': scale, 'Bias': bias,
                             'Mean': mean, 'Variance': variance},
                     outputs={'Y': out, 'MeanOut': mean,
                              'VarianceOut': variance,
                              'SavedMean': saved_mean,
                              'SavedVariance': saved_var},
                     attrs={'momentum': momentum, 'epsilon': epsilon,
                            'is_test': is_test, 'data_layout': data_layout,
                            'use_global_stats': use_global_stats})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper('layer_norm', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    param_shape = [_prod(input.shape[begin_norm_axis:])]
    inputs = {'X': input}
    if scale:
        inputs['Scale'] = helper.create_parameter(
            helper.param_attr, param_shape, dtype,
            default_initializer=Constant(1.0))
    if shift:
        inputs['Bias'] = helper.create_parameter(
            helper.bias_attr, param_shape, dtype, is_bias=True)
    mean_out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='layer_norm', inputs=inputs,
                     outputs={'Y': out, 'Mean': mean_out,
                              'Variance': var_out},
                     attrs={'epsilon': epsilon,
                            'begin_norm_axis': begin_norm_axis})
    _copy_lod(input, out)
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-05, param_attr=None, bias_attr=None,
               act=None, data_layout='NCHW', name=None):
    helper = LayerHelper('group_norm', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    param_shape = [input.shape[1]]
    inputs = {'X': input}
    if param_attr is not False:
        inputs['Scale'] = helper.create_parameter(
            helper.param_attr, param_shape, dtype,
            default_initializer=Constant(1.0))
    if bias_attr is not False:
        inputs['Bias'] = helper.create_parameter(
            helper.bias_attr, param_shape, dtype, is_bias=True)
    mean_out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='group_norm', inputs=inputs,
                     outputs={'Y': out, 'Mean': mean_out,
                              'Variance': var_out},
                     attrs={'epsilon': epsilon, 'groups': groups})
    return helper.append_activation(out)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout='NCHW', in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    helper = LayerHelper('data_norm', name=name)
    dtype = input.dtype
    c = input.shape[-1]
    batch_size = helper.create_parameter(
        ParamAttr(initializer=Constant(1e4), trainable=True), [c], dtype)
    batch_sum = helper.create_parameter(
        ParamAttr(initializer=Constant(0.0), trainable=True), [c], dtype)
    batch_square_sum = helper.create_parameter(
        ParamAttr(initializer=Constant(1e4), trainable=True), [c], dtype)
    means = helper.create_variable_for_type_inference(dtype)
    scales = helper.create_variable_for_type_inference(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='data_norm',
                     inputs={'X': input, 'BatchSize': batch_size,
                             'BatchSum': batch_sum,
                             'BatchSquareSum': batch_square_sum},
                     outputs={'Y': out, 'Means': means, 'Scales': scales},
                     attrs={'epsilon': epsilon})
    return helper.append_activation(out)


# -------------------------------------------------------------- generic

def _simple(op_type, x, attrs=None, name=None, outs=('Out',), ins_name='X',
            extra_ins=None, dtype=None, lod_from=None):
    helper = LayerHelper(op_type, name=name)
    dtype = dtype or (x[0].dtype if isinstance(x, (list, tuple)) else x.dtype)
    out_vars = {o: helper.create_variable_for_type_inference(dtype)
                for o in outs}
    ins = {ins_name: x}
    if extra_ins:
        ins.update(extra_ins)
    helper.append_op(type=op_type, inputs=ins, outputs=out_vars,
                     attrs=attrs or {})
    src = lod_from if lod_from is not None else (
        x[0] if isinstance(x, (list, tuple)) else x)
    for v in out_vars.values():
        _copy_lod(src, v)
    if len(outs) == 1:
        return out_vars[outs[0]]
    return tuple(out_vars[o] for o in outs)


def softmax(input, use_cudnn=True, name=None, axis=-1):
    return _simple('softmax', input, {'axis': axis}, name)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return _simple('cross_entropy', input,
                   {'soft_label': soft_label, 'ignore_index': ignore_index},
                   outs=('Y',), extra_ins={'Label': label})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, label_smooth_eps=0.0):
    """Reference nn.py softmax_with_cross_entropy, plus a fused
    `label_smooth_eps` (hard labels only): equivalent to
    one_hot -> label_smooth -> soft_label=True but without ever
    materializing the [..., V] smoothed-label tensor."""
    if soft_label and label_smooth_eps:
        raise ValueError(
            'label_smooth_eps applies to hard labels only — with '
            'soft_label=True smooth the labels yourself (label_smooth)')
    helper = LayerHelper('softmax_with_cross_entropy')
    loss = helper.create_variable_for_type_inference(logits.dtype)
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(type='softmax_with_cross_entropy',
                     inputs={'Logits': logits, 'Label': label},
                     outputs={'Loss': loss, 'Softmax': softmax_out},
                     attrs={'soft_label': soft_label,
                            'ignore_index': ignore_index,
                            'label_smooth_eps': float(label_smooth_eps)})
    if return_softmax:
        return loss, softmax_out
    return loss


def bpr_loss(input, label, name=None):
    return _simple('bpr_loss', input, name=name, outs=('Y',),
                   extra_ins={'Label': label})


def square_error_cost(input, label):
    return _simple('square_error_cost', input, extra_ins={'Y': label})


def cos_sim(X, Y):
    return _simple('cos_sim', X, outs=('Out', 'XNorm', 'YNorm'),
                   extra_ins={'Y': Y})[0]


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation='downgrade_in_infer'):
    return _simple('dropout', x,
                   {'dropout_prob': dropout_prob, 'is_test': is_test,
                    'seed': seed if seed is not None else 0,
                    'dropout_implementation': dropout_implementation},
                   name, outs=('Out', 'Mask'))[0]


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper('split', name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(max(num, len(sections)) or 1)]
    helper.append_op(type='split', inputs={'X': input}, outputs={'Out': outs},
                     attrs={'axis': dim, 'num': num, 'sections': sections})
    return outs


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None,
           amp_keep_bf16=False):
    # amp_keep_bf16 (TPU extension): keep the GEMM output bf16 under AMP
    # for consumers that tolerate it (attention interiors) — see fc
    return _simple('matmul', x, {'transpose_X': transpose_x,
                                 'transpose_Y': transpose_y,
                                 'alpha': float(alpha),
                                 'amp_keep_bf16': amp_keep_bf16}, name,
                   extra_ins={'Y': y})


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return _simple('mul', x, {'x_num_col_dims': x_num_col_dims,
                              'y_num_col_dims': y_num_col_dims}, name,
                   extra_ins={'Y': y})


def topk(input, k, name=None):
    helper = LayerHelper('top_k', name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='top_k', inputs={'X': input},
                     outputs={'Out': values, 'Indices': indices},
                     attrs={'k': k})
    return values, indices


def transpose(x, perm, name=None):
    return _simple('transpose', x, {'axis': list(perm)}, name)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper('reshape', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='reshape', inputs={'X': x}, outputs={'Out': out},
                     attrs={'shape': list(shape)})
    return helper.append_activation(out, act)


def squeeze(input, axes, name=None):
    return _simple('squeeze', input, {'axes': list(axes)}, name)


def unsqueeze(input, axes, name=None):
    return _simple('unsqueeze', input, {'axes': list(axes)}, name)


def _reduce(op, input, dim, keep_dim, name):
    if dim is None:
        attrs = {'reduce_all': True, 'keep_dim': keep_dim}
    else:
        attrs = {'dim': [dim] if isinstance(dim, int) else list(dim),
                 'keep_dim': keep_dim}
    return _simple(op, input, attrs, name)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_sum', input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_mean', input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_max', input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_min', input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_prod', input, dim, keep_dim, name)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return _simple('l2_normalize', x, {'axis': axis, 'epsilon': epsilon},
                   name, outs=('Out', 'Norm'))[0]


def one_hot(input, depth):
    return _simple('one_hot', input, {'depth': depth})


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    return _simple('lrn', input, {'n': n, 'k': k, 'alpha': alpha,
                                  'beta': beta}, name,
                   outs=('Out', 'MidOut'))[0]


def pad(x, paddings, pad_value=0.0, name=None):
    return _simple('pad', x, {'paddings': list(paddings),
                              'pad_value': float(pad_value)}, name)


def pad2d(input, paddings=[0, 0, 0, 0], mode='constant', pad_value=0.0,
          data_format='NCHW', name=None):
    return _simple('pad2d', input, {'paddings': list(paddings),
                                    'mode': mode, 'pad_value': pad_value,
                                    'data_format': data_format}, name)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _simple('pad_constant_like', x, {'pad_value': float(pad_value)},
                   name, extra_ins={'Y': y}, lod_from=y)


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype='float32',
                 name=None):
    extra = {'PriorDist': prior_dist} if prior_dist is not None else None
    return _simple('label_smooth', label, {'epsilon': float(epsilon)}, name,
                   extra_ins=extra)


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample='BILINEAR', actual_shape=None, align_corners=True,
                 align_mode=1):
    op = 'bilinear_interp' if resample == 'BILINEAR' else 'nearest_interp'
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    return _simple(op, input, {'out_h': int(out_shape[0]),
                               'out_w': int(out_shape[1]),
                               'align_corners': align_corners}, name)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, 'BILINEAR',
                        actual_shape, align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, 'NEAREST',
                        actual_shape, align_corners)


def image_resize_short(input, out_short_len, resample='BILINEAR'):
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    out_shape = [int(h * out_short_len / short),
                 int(w * out_short_len / short)]
    return image_resize(input, out_shape, resample=resample)


def gather(input, index):
    return _simple('gather', input, extra_ins={'Index': index})


def scatter(input, index, updates, name=None, overwrite=True):
    return _simple('scatter', input, {'overwrite': overwrite}, name,
                   extra_ins={'Ids': index, 'Updates': updates})


def random_crop(x, shape, seed=None):
    return _simple('random_crop', x, {'shape': list(shape),
                                      'seed': seed or 0})


def crop(x, shape=None, offsets=None, name=None):
    attrs = {}
    extra = None
    if isinstance(shape, Variable):
        extra = {'Y': shape}
    else:
        attrs['shape'] = list(shape)
    attrs['offsets'] = list(offsets) if offsets else None
    return _simple('crop', x, attrs, name, extra_ins=extra)


def relu(x, name=None):
    return _simple('relu', x, name=name)


def log(x, name=None):
    return _simple('log', x, name=name)


def mean(x, name=None):
    return _simple('mean', x, name=name)


def sum(x):
    return _simple('sum', x if isinstance(x, list) else [x])


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    return _simple('sigmoid_cross_entropy_with_logits', x,
                   {'ignore_index': ignore_index, 'normalize': normalize},
                   name, extra_ins={'Label': label})


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    extra = {'Y': y}
    if inside_weight is not None:
        extra['InsideWeight'] = inside_weight
    if outside_weight is not None:
        extra['OutsideWeight'] = outside_weight
    return _simple('smooth_l1_loss', x, {'sigma': sigma or 1.0},
                   outs=('Out', 'Diff'), extra_ins=extra)[0]


def huber_loss(input, label, delta):
    return _simple('huber_loss', input, {'delta': float(delta)},
                   outs=('Out', 'Residual'), extra_ins={'Y': label})[0]


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper('log_loss', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='log_loss',
                     inputs={'Predicted': input, 'Labels': label},
                     outputs={'Loss': out}, attrs={'epsilon': epsilon})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper('rank_loss', name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(type='rank_loss',
                     inputs={'Label': label, 'Left': left, 'Right': right},
                     outputs={'Out': out}, attrs={})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper('margin_rank_loss', name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(type='margin_rank_loss',
                     inputs={'Label': label, 'X1': left, 'X2': right},
                     outputs={'Out': out, 'Activated': act},
                     attrs={'margin': margin})
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler='uniform',
        custom_dist=None, seed=0, is_sparse=False):
    helper = LayerHelper('nce', param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[1]
    w = helper.create_parameter(helper.param_attr,
                                [num_total_classes, dim], input.dtype)
    inputs = {'Input': input, 'Label': label, 'Weight': w}
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr,
                                    [num_total_classes, 1], input.dtype,
                                    is_bias=True)
        inputs['Bias'] = b
    cost = helper.create_variable_for_type_inference(input.dtype)
    sl = helper.create_variable_for_type_inference(input.dtype)
    slab = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='nce', inputs=inputs,
                     outputs={'Cost': cost, 'SampleLogits': sl,
                              'SampleLabels': slab},
                     attrs={'num_total_classes': num_total_classes,
                            'num_neg_samples': num_neg_samples or 10,
                            'seed': seed})
    return cost / (1 + (num_neg_samples or 10))


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    helper = LayerHelper('hierarchical_sigmoid', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[1]
    w = helper.create_parameter(helper.param_attr, [num_classes - 1, dim],
                                input.dtype)
    inputs = {'X': input, 'Label': label, 'W': w}
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, [num_classes - 1, 1],
                                    input.dtype, is_bias=True)
        inputs['Bias'] = b
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='hierarchical_sigmoid', inputs=inputs,
                     outputs={'Out': out, 'PreOut': pre_out},
                     attrs={'num_classes': num_classes})
    return out


def multiplex(inputs, index):
    return _simple('multiplex', inputs, ins_name='X',
                   extra_ins={'Ids': index})


def flatten(x, axis=1, name=None):
    return _simple('flatten', x, {'axis': axis}, name)


def stack(x, axis=0):
    x = x if isinstance(x, list) else [x]
    return _simple('stack', x, {'axis': axis}, outs=('Y',))


def unstack(x, axis=0, num=None):
    helper = LayerHelper('unstack')
    num = num if num is not None else x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op(type='unstack', inputs={'X': x}, outputs={'Y': outs},
                     attrs={'axis': axis})
    return outs


def expand(x, expand_times, name=None):
    return _simple('expand', x, {'expand_times': list(expand_times)}, name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    helper = LayerHelper('scale', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='scale', inputs={'X': x}, outputs={'Out': out},
                     attrs={'scale': float(scale), 'bias': float(bias),
                            'bias_after_scale': bias_after_scale})
    _copy_lod(x, out)
    return helper.append_activation(out, act)


def _elementwise(op, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op, inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={'axis': axis})
    _copy_lod(x, out)
    return helper.append_activation(out, act)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_add', x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_sub', x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_mul', x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_div', x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_max', x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_min', x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_pow', x, y, axis, act, name)


def clip(x, min, max, name=None):
    return _simple('clip', x, {'min': float(min), 'max': float(max)}, name)


def clip_by_norm(x, max_norm, name=None):
    return _simple('clip_by_norm', x, {'max_norm': float(max_norm)}, name)


def slice(input, axes, starts, ends):
    helper = LayerHelper('slice')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='slice', inputs={'Input': input},
                     outputs={'Out': out},
                     attrs={'axes': list(axes), 'starts': list(starts),
                            'ends': list(ends)})
    return out


def shape(input):
    helper = LayerHelper('shape')
    out = helper.create_variable_for_type_inference('int32')
    helper.append_op(type='shape', inputs={'Input': input},
                     outputs={'Out': out}, attrs={})
    return out


def logical_and(x, y, out=None, name=None):
    return _simple('logical_and', x, extra_ins={'Y': y}, dtype='bool')


def logical_or(x, y, out=None, name=None):
    return _simple('logical_or', x, extra_ins={'Y': y}, dtype='bool')


def logical_xor(x, y, out=None, name=None):
    return _simple('logical_xor', x, extra_ins={'Y': y}, dtype='bool')


def logical_not(x, out=None, name=None):
    return _simple('logical_not', x, dtype='bool')


def maxout(x, groups, name=None):
    return _simple('maxout', x, {'groups': groups}, name)


def space_to_depth(x, blocksize, name=None):
    return _simple('space_to_depth', x, {'blocksize': blocksize}, name)


def affine_grid(theta, out_shape, name=None):
    return _simple('affine_grid', theta,
                   {'output_shape': list(out_shape)},
                   name, ins_name='Theta', outs=('Output',))


def affine_channel(x, scale=None, bias=None, data_layout='NCHW', name=None):
    return _simple('affine_channel', x, name=name,
                   extra_ins={'Scale': scale, 'Bias': bias})


def grid_sampler(x, grid, name=None):
    return _simple('grid_sampler', x, name=name,
                   extra_ins={'Grid': grid}, outs=('Output',))


def add_position_encoding(input, alpha, beta, name=None):
    return _simple('add_position_encoding', input,
                   {'alpha': float(alpha), 'beta': float(beta)}, name)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper('bilinear_tensor_product', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = x.dtype
    w = helper.create_parameter(helper.param_attr,
                                [size, x.shape[1], y.shape[1]], dtype)
    inputs = {'X': x, 'Y': y, 'Weight': w}
    if helper.bias_attr is not False:
        inputs['Bias'] = helper.create_parameter(
            helper.bias_attr, [1, size], dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='bilinear_tensor_product', inputs=inputs,
                     outputs={'Out': out}, attrs={})
    return helper.append_activation(out)


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper('prelu', param_attr=param_attr, name=name)
    alpha_shape = [1]
    if mode == 'channel':
        alpha_shape = [x.shape[1]]
    elif mode == 'element':
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(helper.param_attr, alpha_shape, x.dtype,
                                    default_initializer=Constant(0.25))
    return _simple('prelu', x, {'mode': mode}, name,
                   extra_ins={'Alpha': alpha})


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple('brelu', x, {'t_min': t_min, 't_max': t_max}, name)


def leaky_relu(x, alpha=0.02, name=None):
    return _simple('leaky_relu', x, {'alpha': alpha}, name)


def soft_relu(x, threshold=40.0, name=None):
    return _simple('soft_relu', x, {'threshold': threshold}, name)


def elu(x, alpha=1.0, name=None):
    return _simple('elu', x, {'alpha': alpha}, name)


def relu6(x, threshold=6.0, name=None):
    return _simple('relu6', x, {'threshold': threshold}, name)


def pow(x, factor=1.0, name=None):
    return _simple('pow', x, {'factor': factor}, name)


def stanh(x, scale_a=2.0 / 3.0, scale_b=1.7159, name=None):
    return _simple('stanh', x, {'scale_a': scale_a, 'scale_b': scale_b},
                   name)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _simple('hard_sigmoid', x, {'slope': slope, 'offset': offset},
                   name)


def swish(x, beta=1.0, name=None):
    return _simple('swish', x, {'beta': beta}, name)


def selu(x, scale=None, alpha=None, name=None):
    attrs = {}
    if scale is not None:
        attrs['scale'] = scale
    if alpha is not None:
        attrs['alpha'] = alpha
    return _simple('selu', x, attrs, name)


def mean_iou(input, label, num_classes):
    helper = LayerHelper('mean_iou')
    miou = helper.create_variable_for_type_inference('float32')
    wrong = helper.create_variable_for_type_inference('float32')
    correct = helper.create_variable_for_type_inference('float32')
    helper.append_op(type='mean_iou',
                     inputs={'Predictions': input, 'Labels': label},
                     outputs={'OutMeanIou': miou, 'OutWrong': wrong,
                              'OutCorrect': correct},
                     attrs={'num_classes': num_classes})
    return miou, wrong, correct


def dice_loss(input, label, epsilon=1e-5):
    return _simple('dice_loss', input, {'epsilon': epsilon},
                   extra_ins={'Label': label})


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=
                None, out_stride=1, name=None):
    def pair(v):
        return [v, v] if isinstance(v, int) else list(v)
    return _simple('im2sequence', input,
                   {'kernels': pair(filter_size), 'strides': pair(stride),
                    'paddings': pair(padding)}, name)


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper('row_conv', param_attr=param_attr, act=act)
    dtype = input.dtype
    filter_shape = [future_context_size + 1, input.shape[-1]]
    w = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='row_conv',
                     inputs={'X': input, 'Filter': w},
                     outputs={'Out': out}, attrs={})
    _copy_lod(input, out)
    return helper.append_activation(out)


def uniform_random_batch_size_like(input, shape, dtype='float32',
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    return _simple('uniform_random_batch_size_like', input,
                   {'shape': list(shape), 'input_dim_idx': input_dim_idx,
                    'output_dim_idx': output_dim_idx, 'min': min, 'max': max,
                    'seed': seed, 'dtype': dtype},
                   ins_name='Input', dtype=dtype)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype='float32'):
    helper = LayerHelper('gaussian_random')
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='gaussian_random', inputs={},
                     outputs={'Out': out},
                     attrs={'shape': list(shape), 'mean': mean, 'std': std,
                            'seed': seed, 'dtype': dtype})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype='float32'):
    return _simple('gaussian_random_batch_size_like', input,
                   {'shape': list(shape), 'input_dim_idx': input_dim_idx,
                    'output_dim_idx': output_dim_idx, 'mean': mean,
                    'std': std, 'seed': seed, 'dtype': dtype},
                   ins_name='Input', dtype=dtype)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype='float32'):
    return _simple('sampling_id', x, {'seed': seed}, dtype='int64')


def shuffle_channel(x, group, name=None):
    return _simple('shuffle_channel', x, {'group': group}, name)


def similarity_focus(input, axis, indexes, name=None):
    return _simple('similarity_focus', input,
                   {'axis': axis, 'indexes': list(indexes)}, name)


def hash(input, hash_size, num_hash=1, name=None):
    return _simple('hash', input, {'mod_by': hash_size,
                                   'num_hash': num_hash}, name,
                   dtype='int64')


def lod_reset(x, y=None, target_lod=None):
    """In padded representation the data layout is unchanged; only the
    lengths binding moves (ref lod_reset_op)."""
    out = _simple('assign', x)
    if y is not None:
        out.lod_level = max(1, y.lod_level)
        out.lod_length_name = getattr(y, 'lod_length_name', None)
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    helper = LayerHelper('global_step_counter')
    counter_name = counter_name or '@STEP_COUNTER@'
    counter = helper.create_or_get_global_variable(
        name=counter_name, dtype='int64', shape=[1], persistable=True)
    if counter.op is None:
        from ..initializer import Constant
        Constant(value=float(begin - 1))(counter)
        helper.append_op(type='increment', inputs={'X': counter},
                         outputs={'Out': counter},
                         attrs={'step': float(step)})
        counter.stop_gradient = True
    return counter


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=
            None):
    """Run a Python callable as an op (parity: reference nn.py py_func /
    py_func_op.cc).  `out` variables are pre-created by the caller with
    their shapes/dtypes, exactly as in the reference.

    TPU-native lowering: the callable runs on the HOST via
    jax.pure_callback inside the one jitted step (XLA inserts the
    device<->host transfers); `backward_func`, if given, becomes the
    custom VJP and receives (inputs..., outputs..., out-grads...) minus
    `skip_vars_in_backward_input`, returning one grad per input.  The
    callable must be functionally pure — it can be retraced, cached, or
    re-run by XLA like any other op."""
    helper = LayerHelper('py_func')
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        if o.shape is None:
            raise ValueError(
                'py_func out var %r has no shape: XLA needs static output '
                'shapes, so create it with create_parameter/create_'
                'global_var or set var.shape (use -1 for the batch dim)'
                % o.name)
    skip = skip_vars_in_backward_input or []
    skip_names = {getattr(v, 'name', v) for v in
                  (skip if isinstance(skip, (list, tuple)) else [skip])}
    skip_idx = [i for i, v in enumerate(xs + outs) if v.name in skip_names]
    helper.append_op(
        type='py_func', inputs={'X': xs}, outputs={'Out': outs},
        attrs={'func': func, 'backward_func': backward_func,
               'skip_bwd_idx': skip_idx,
               'out_shapes': [list(o.shape) for o in outs],
               'out_dtypes': [o.dtype for o in outs]})
    return out


# ------------------------------------------------------- sequence family

def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  name=None):
    helper = LayerHelper('sequence_conv', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    filter_shape = [filter_size * input.shape[-1], num_filters]
    w = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='sequence_conv',
                     inputs=_seq_inputs(input, {'Filter': w}),
                     outputs={'Out': out},
                     attrs={'contextStride': filter_stride,
                            'contextStart': -int(filter_size // 2),
                            'contextLength': filter_size})
    _copy_lod(input, out)
    pre_act = helper.append_bias_op(out, dim_start=2)
    _copy_lod(input, pre_act)
    res = helper.append_activation(pre_act)
    _copy_lod(input, res)
    return res


def sequence_pool(input, pool_type, is_test=False):
    helper = LayerHelper('sequence_pool')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='sequence_pool', inputs=_seq_inputs(input),
                     outputs={'Out': out},
                     attrs={'pooltype': pool_type.upper(),
                            'is_test': is_test})
    return out


def sequence_first_step(input):
    return sequence_pool(input, 'first')


def sequence_last_step(input):
    return sequence_pool(input, 'last')


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper('sequence_softmax', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='sequence_softmax', inputs=_seq_inputs(input),
                     outputs={'Out': out}, attrs={})
    _copy_lod(input, out)
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper('sequence_expand', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='sequence_expand', inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={'ref_level': ref_level})
    _copy_lod(y, out)
    return out


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper('sequence_expand_as', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='sequence_expand_as', inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={})
    _copy_lod(y, out)
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    helper = LayerHelper('sequence_pad', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='sequence_pad',
                     inputs=_seq_inputs(x, {'PadValue': pad_value}),
                     outputs={'Out': out, 'Length': length},
                     attrs={'padded_length': maxlen or -1})
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper('sequence_unpad', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference('int32')
    helper.append_op(type='sequence_unpad',
                     inputs={'X': x, 'Length': length},
                     outputs={'Out': out, 'OutLength': out_len},
                     attrs={})
    out.lod_level = 1
    out.lod_length_name = out_len.name
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper('sequence_slice', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference('int32')
    ins = {'X': input, 'Offset': offset, 'Length': length}
    lv = _len_var(input)
    if lv is not None:  # source lengths, so the op can clamp requests
        ins['XLength'] = lv
    helper.append_op(type='sequence_slice', inputs=ins,
                     outputs={'Out': out, 'OutLength': out_len}, attrs={})
    # the output sequence's lengths are the requested slice lengths,
    # clamped to the tokens actually available past each row's offset
    out.lod_level = max(input.lod_level, 1)
    out.lod_length_name = out_len.name
    return out


def sequence_erase(input, tokens, name=None):
    """Remove every occurrence of `tokens` from each sequence,
    compacting the survivors left (parity: reference
    sequence_erase_op.cc; the reference reaches it through
    edit_distance's ignored_tokens)."""
    helper = LayerHelper('sequence_erase', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference('int32')
    helper.append_op(type='sequence_erase', inputs=_seq_inputs(input),
                     outputs={'Out': out, 'OutLength': out_len},
                     attrs={'tokens': list(tokens)})
    out.lod_level = max(input.lod_level, 1)
    out.lod_length_name = out_len.name
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper('sequence_reshape')
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference('int32')
    helper.append_op(type='sequence_reshape', inputs=_seq_inputs(input),
                     outputs={'Out': out, 'OutLength': out_len},
                     attrs={'new_dim': new_dim})
    # lengths rescale by D/new_dim, so bind the op's recomputed lengths
    out.lod_level = max(input.lod_level, 1)
    out.lod_length_name = out_len.name
    return out


def sequence_scatter(input, index, updates, name=None):
    helper = LayerHelper('sequence_scatter', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='sequence_scatter',
                     inputs={'X': input, 'Ids': index, 'Updates': updates},
                     outputs={'Out': out}, attrs={})
    return out


def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    helper = LayerHelper('sequence_mask', name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='sequence_mask', inputs={'X': x},
                     outputs={'Y': out},
                     attrs={'maxlen': maxlen if maxlen is not None else -1,
                            'out_dtype': dtype})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper('sequence_enumerate', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='sequence_enumerate', inputs={'X': input},
                     outputs={'Out': out},
                     attrs={'win_size': win_size, 'pad_value': pad_value})
    _copy_lod(input, out)
    return out


def sequence_concat(input, name=None):
    """Row-wise sequence concat: row i of the result is input0's row-i
    tokens followed by input1's row-i tokens (contiguous), length =
    sum of lengths.  Parity: reference sequence_concat (nn.py) /
    sequence_concat_op.cc."""
    helper = LayerHelper('sequence_concat', name=name)
    xs = list(input)
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    out_len = helper.create_variable_for_type_inference('int32')
    ins = {'X': xs}
    lvs = [_len_var(x) for x in xs]
    if any(lv is not None for lv in lvs):
        from .tensor import fill_constant_batch_size_like
        lens = []
        for x, lv in zip(xs, lvs):
            if lv is None:  # dense input: every row is full length
                lens.append(fill_constant_batch_size_like(
                    x, [-1], 'int32', float(x.shape[1])))
            else:
                lens.append(lv)
        ins['Length'] = lens
    helper.append_op(type='sequence_concat', inputs=ins,
                     outputs={'Out': out, 'OutLength': out_len}, attrs={})
    out.lod_level = max(max(x.lod_level for x in xs), 1)
    out.lod_length_name = out_len.name
    return out


def sequence_reverse(x, name=None):
    helper = LayerHelper('sequence_reverse', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='sequence_reverse', inputs=_seq_inputs(x),
                     outputs={'Y': out}, attrs={})
    _copy_lod(x, out)
    return out


def warpctc(input, label, blank=0, norm_by_times=False,
            use_cudnn=False):
    helper = LayerHelper('warpctc')
    loss = helper.create_variable_for_type_inference(input.dtype)
    ins = {'Logits': input, 'Label': label}
    lv = _len_var(input)
    if lv is not None:
        ins['LogitsLength'] = lv
    llv = _len_var(label)
    if llv is not None:
        ins['LabelLength'] = llv
    helper.append_op(type='warpctc', inputs=ins, outputs={'Loss': loss},
                     attrs={'blank': blank, 'norm_by_times': norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, name=None):
    helper = LayerHelper('ctc_greedy_decoder', name=name)
    out = helper.create_variable_for_type_inference('int64')
    out_len = helper.create_variable_for_type_inference('int32')
    helper.append_op(type='ctc_align', inputs=_seq_inputs(input),
                     outputs={'Output': out, 'OutLength': out_len},
                     attrs={'blank': blank, 'merge_repeated': True})
    out.lod_level = 1
    out.lod_length_name = out_len.name
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    """The reference erases ignored_tokens with two sequence_erase ops
    before the distance op (nn.py edit_distance); here the op itself
    squeezes them (ops/nn.py), so the attr just forwards."""
    helper = LayerHelper('edit_distance')
    out = helper.create_variable_for_type_inference('float32')
    seq_num = helper.create_variable_for_type_inference('int64')
    ins = {'Hyps': input, 'Refs': label}
    lv = _len_var(input)
    if lv is not None:
        ins['HypsLength'] = lv
    llv = _len_var(label)
    if llv is not None:
        ins['RefsLength'] = llv
    helper.append_op(type='edit_distance', inputs=ins,
                     outputs={'Out': out, 'SequenceNum': seq_num},
                     attrs={'normalized': normalized,
                            'ignored_tokens': list(ignored_tokens or [])})
    return out, seq_num


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """Chunk-level precision/recall/F1 for tagging (ref layers/nn.py
    chunk_eval; op semantics from operators/chunk_eval_op.h).  Returns
    (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks)."""
    helper = LayerHelper('chunk_eval')
    precision = helper.create_variable_for_type_inference('float32')
    recall = helper.create_variable_for_type_inference('float32')
    f1 = helper.create_variable_for_type_inference('float32')
    num_infer = helper.create_variable_for_type_inference('int64')
    num_label = helper.create_variable_for_type_inference('int64')
    num_correct = helper.create_variable_for_type_inference('int64')
    ins = {'Inference': input, 'Label': label}
    lv = _len_var(input) or _len_var(label)
    if lv is not None:
        ins['SeqLength'] = lv
    helper.append_op(
        type='chunk_eval', inputs=ins,
        outputs={'Precision': precision, 'Recall': recall, 'F1-Score': f1,
                 'NumInferChunks': num_infer, 'NumLabelChunks': num_label,
                 'NumCorrectChunks': num_correct},
        attrs={'chunk_scheme': chunk_scheme,
               'num_chunk_types': num_chunk_types,
               'excluded_chunk_types': excluded_chunk_types or []})
    return (precision, recall, f1, num_infer, num_label, num_correct)


def linear_chain_crf(input, label, param_attr=None):
    helper = LayerHelper('linear_chain_crf', param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(helper.param_attr,
                                         [size + 2, size], input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    emission_exps = helper.create_variable_for_type_inference(input.dtype)
    transition_exps = helper.create_variable_for_type_inference(input.dtype)
    log_likelihood = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='linear_chain_crf',
                     inputs=_seq_inputs(input, {'Transition': transition,
                                                'Label': label}),
                     outputs={'Alpha': alpha, 'EmissionExps': emission_exps,
                              'TransitionExps': transition_exps,
                              'LogLikelihood': log_likelihood},
                     attrs={})
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    helper = LayerHelper('crf_decoding', param_attr=param_attr)
    tname = helper.param_attr.name
    tvar = input.block._find_var_recursive(tname) if tname else None
    if tvar is None:
        # standalone decode: create the transition param (shared by name
        # with linear_chain_crf when both are built, like the reference)
        size = input.shape[-1]
        tvar = helper.create_parameter(helper.param_attr,
                                       [size + 2, size], input.dtype)
    out = helper.create_variable_for_type_inference('int64')
    ins = _seq_inputs(input, {'Transition': tvar})
    if label is not None:
        ins['Label'] = label
    helper.append_op(type='crf_decoding', inputs=ins,
                     outputs={'ViterbiPath': out}, attrs={})
    _copy_lod(input, out)
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    return _simple('teacher_student_sigmoid_loss', input,
                   {'soft_max_up_bound': soft_max_up_bound,
                    'soft_max_lower_bound': soft_max_lower_bound},
                   outs=('Y',), extra_ins={'Label': label})


def flash_attention(q, k, v, causal=False, k_lengths=None, name=None):
    """Fused online-softmax attention over [B, H, T, D] tensors
    (pallas kernel on TPU; see ops/attention.py).  New vs reference —
    the reference composes matmul+softmax+matmul.  `k_lengths` (int [B])
    masks suffix padding of K/V."""
    helper = LayerHelper('flash_attention', name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    ins = {'Q': q, 'K': k, 'V': v}
    if k_lengths is not None:
        ins['KLength'] = k_lengths
    helper.append_op(type='flash_attention', inputs=ins,
                     outputs={'Out': out}, attrs={'causal': causal})
    return out


def ring_attention(q, k, v, causal=False, axis_name='seq', name=None):
    """Sequence-parallel exact attention over [B, H, T, D] (long-context
    path; see ops/attention.py ring_attention_op).  Runs the ppermute ring
    when the executor mesh has a >1 `axis_name` axis, flash attention
    otherwise — same program, both scales.  New vs reference."""
    helper = LayerHelper('ring_attention', name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op(type='ring_attention', inputs={'Q': q, 'K': k, 'V': v},
                     outputs={'Out': out},
                     attrs={'causal': causal, 'axis_name': axis_name})
    return out


def rms_norm(input, param_attr=None, epsilon=1e-6, name=None):
    """RMS LayerNorm over the last dim (the LLaMA norm).  New vs reference
    (fluid-era predates RMSNorm); scale param only, no bias/centering."""
    helper = LayerHelper('rms_norm', name=name, param_attr=param_attr)
    from ..initializer import Constant
    d = int(input.shape[-1])
    scale = helper.create_parameter(helper.param_attr, [d], input.dtype,
                                    default_initializer=Constant(1.0))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='rms_norm',
                     inputs={'X': input, 'Scale': scale},
                     outputs={'Y': out}, attrs={'epsilon': epsilon})
    return out


def rope(input, theta=10000.0, positions=None, name=None):
    """Rotary position embedding on [B, H, T, D] head tensors.  New vs
    reference (additive add_position_encoding is the fluid-era analogue)."""
    helper = LayerHelper('rope', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {'X': input}
    if positions is not None:
        ins['Positions'] = positions
    helper.append_op(type='rope', inputs=ins, outputs={'Out': out},
                     attrs={'theta': float(theta)})
    return out


def sample_tokens(logits, temperature=0.0, top_k=0, seed=0, name=None):
    """Draw token ids over the last axis of `logits` (greedy when
    temperature<=0; top_k>0 restricts the draw to the k highest logits).
    New vs reference — `sampling_id` is the fluid-era analogue
    (probabilities only, no temperature/top-k).  seed=0 draws from the
    executor RNG stream, which the optimizer passes pin via the
    `rng_stream` attr, so a PT_OPT-rewritten program samples the same
    tokens as the raw one (see ops/sampling.py)."""
    helper = LayerHelper('sample_tokens', name=name)
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='sample_tokens', inputs={'Logits': logits},
                     outputs={'Out': out},
                     attrs={'temperature': float(temperature),
                            'top_k': int(top_k), 'seed': int(seed)})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_batch=None):
    """Max ROI pooling.  Ref: layers/nn.py:6453 (roi_pool).

    `rois` is (R, 4); the reference carries the per-ROI batch image index in
    the ROIs' LoD — here it is the optional dense `rois_batch` (R,) int input
    (defaults to image 0, the single-image case).
    """
    helper = LayerHelper('roi_pool')
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {'X': input, 'ROIs': rois}
    if rois_batch is not None:
        ins['RoisBatch'] = rois_batch
    helper.append_op(type='roi_pool', inputs=ins, outputs={'Out': out},
                     attrs={'pooled_height': pooled_height,
                            'pooled_width': pooled_width,
                            'spatial_scale': spatial_scale})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_batch=None):
    """Bilinear ROI align.  Ref: layers/nn.py:6491 (roi_align)."""
    helper = LayerHelper('roi_align', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {'X': input, 'ROIs': rois}
    if rois_batch is not None:
        ins['RoisBatch'] = rois_batch
    helper.append_op(type='roi_align', inputs=ins, outputs={'Out': out},
                     attrs={'pooled_height': pooled_height,
                            'pooled_width': pooled_width,
                            'spatial_scale': spatial_scale,
                            'sampling_ratio': sampling_ratio})
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None, rois_batch=None):
    """Position-sensitive ROI pooling (R-FCN).  Ref: layers/nn.py:9942."""
    if not isinstance(output_channels, int):
        raise TypeError("output_channels must be int type")
    if not isinstance(spatial_scale, float):
        raise TypeError("spatial_scale must be float type")
    if not isinstance(pooled_height, int):
        raise TypeError("pooled_height must be int type")
    if not isinstance(pooled_width, int):
        raise TypeError("pooled_width must be int type")
    helper = LayerHelper('psroi_pool', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {'X': input, 'ROIs': rois}
    if rois_batch is not None:
        ins['RoisBatch'] = rois_batch
    helper.append_op(type='psroi_pool', inputs=ins, outputs={'Out': out},
                     attrs={'output_channels': output_channels,
                            'spatial_scale': spatial_scale,
                            'pooled_height': pooled_height,
                            'pooled_width': pooled_width})
    return out


def conv_shift(x, y, name=None):
    """Circular convolution of x (B, M) by kernel y (B, N), N odd.
    Ref: layers/nn.py conv_shift / operators/conv_shift_op.cc."""
    helper = LayerHelper('conv_shift', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='conv_shift', inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={})
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1, max_depth=2,
              act='tanh', param_attr=None, bias_attr=None, name=None):
    """Tree-based convolution (TBCNN).  Ref: layers/nn.py:10044."""
    helper = LayerHelper('tree_conv', name=name, param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    dtype = nodes_vector.dtype
    feature_size = nodes_vector.shape[2]
    W = helper.create_parameter(attr=helper.param_attr,
                                shape=[feature_size, 3, output_size,
                                       num_filters],
                                dtype=dtype, is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='tree_conv',
                     inputs={'NodesVector': nodes_vector,
                             'EdgeSet': edge_set, 'Filter': W},
                     outputs={'Out': out},
                     attrs={'max_depth': max_depth})
    if helper.bias_attr:
        out = helper.append_bias_op(out, dim_start=3)
    return helper.append_activation(out)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id, level=0,
                is_accumulated=True, name=None, return_parent_idx=False):
    """One beam-search step.  Ref: layers/nn.py:3872.

    Dense formulation (static beam width — see ops/sequence.py beam_search).
    At step 0 feed pre_scores = [0, -inf, ...] per source so only beam 0 is
    live.  Set `return_parent_idx=True` to also get the (R,) int32 gather
    indices for reordering decoder state / writing the backtrace array.
    """
    helper = LayerHelper('beam_search', name=name)
    selected_ids = helper.create_variable_for_type_inference(pre_ids.dtype)
    selected_scores = helper.create_variable_for_type_inference(
        pre_scores.dtype)
    parent_idx = helper.create_variable_for_type_inference('int32')
    inputs = {'pre_ids': pre_ids, 'pre_scores': pre_scores, 'scores': scores}
    if ids is not None:
        inputs['ids'] = ids
    helper.append_op(type='beam_search', inputs=inputs,
                     outputs={'selected_ids': selected_ids,
                              'selected_scores': selected_scores,
                              'parent_idx': parent_idx},
                     attrs={'level': level, 'beam_size': beam_size,
                            'end_id': end_id,
                            'is_accumulated': is_accumulated})
    if return_parent_idx:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None,
                       parents=None):
    """Construct full hypotheses from per-step beam results.
    Ref: layers/nn.py:3991.

    `ids`/`scores` are TensorArrays written once per step; `parents` is the
    TensorArray of parent_idx outputs from beam_search (the reference encodes
    these back-pointers in each step's LoD; the dense formulation passes them
    explicitly — identity if omitted, i.e. the caller already reordered rows
    every step).  Returns (R, T) sentence ids and scores.
    """
    from . import control_flow as cf
    helper = LayerHelper('beam_search_decode', name=name)

    def _stacked(v):
        """TensorArray -> stack its steps; a plain 3-D (T, R, 1) /
        (T, R) var is already the stacked dense form."""
        if isinstance(v, cf._TensorArray):
            return stack(v.vars, axis=0)
        return v
    ids_vars = ids.vars if isinstance(ids, cf._TensorArray) else [ids]
    sc_vars = scores.vars if isinstance(scores, cf._TensorArray) else [scores]
    inputs = {'Ids': _stacked(ids), 'Scores': _stacked(scores)}
    if parents is not None:
        inputs['Parents'] = _stacked(parents)
    sentence_ids = helper.create_variable_for_type_inference(
        ids_vars[0].dtype)
    sentence_scores = helper.create_variable_for_type_inference(
        sc_vars[0].dtype)
    out_len = helper.create_variable_for_type_inference('int32')
    out_outer = helper.create_variable_for_type_inference('int32')
    helper.append_op(type='beam_search_decode', inputs=inputs,
                     outputs={'SentenceIds': sentence_ids,
                              'SentenceScores': sentence_scores,
                              'OutLength': out_len,
                              'OutOuterLength': out_outer},
                     attrs={'beam_size': beam_size, 'end_id': end_id})
    # reference emits 2-level LoD: source -> hypotheses -> tokens
    for v in (sentence_ids, sentence_scores):
        v.lod_level = 2
        v.lod_length_name = out_len.name
        v.lod_outer_length_name = out_outer.name
    return sentence_ids, sentence_scores


def merge_selected_rows(x, name=None):
    """Merge duplicate rows of a SelectedRows input by summation.

    Parity: reference nn.py merge_selected_rows /
    operators/merge_selected_rows_op.cc.  SelectedRows is the reference's
    sparse-gradient type ({rows, values} pairs where the same row id may
    appear twice, e.g. two lookups of one embedding id).  This framework
    has no SelectedRows runtime type: sparse gradients are ALREADY merged
    — lookup_table's backward is a scatter-ADD into the dense table, which
    is exactly the merge this op performs — so the op is a documented
    identity on its dense input."""
    helper = LayerHelper('merge_selected_rows', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='assign', inputs={'X': x},
                     outputs={'Out': out}, attrs={})
    return out


def get_tensor_from_selected_rows(x, name=None):
    """Densify a SelectedRows value into an ordinary tensor.

    Parity: reference nn.py get_tensor_from_selected_rows /
    operators/get_tensor_from_selected_rows_op.cc.  Gradients here are
    always dense arrays (see merge_selected_rows), so the conversion is
    an identity copy with the same graph surface."""
    helper = LayerHelper('get_tensor_from_selected_rows', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='assign', inputs={'X': x},
                     outputs={'Out': out}, attrs={})
    return out
