"""Tensor creation / manipulation layers.

Parity: reference python/paddle/fluid/layers/tensor.py.
"""
import numpy as np

from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from ..core import unique_name

__all__ = [
    'create_tensor', 'create_parameter', 'create_global_var', 'cast',
    'tensor_array_to_tensor', 'concat', 'sums', 'assign',
    'fill_constant_batch_size_like', 'fill_constant', 'argmin', 'argmax',
    'argsort', 'ones', 'zeros', 'reverse', 'has_inf', 'has_nan', 'isfinite',
    'zeros_like',
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper('create_tensor', name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr
    helper = LayerHelper('create_parameter', name=name)
    attr = ParamAttr._to_attr(attr)
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..initializer import Constant
    helper = LayerHelper('global_var', name=name)
    var = helper.create_global_variable(
        name=name or unique_name.generate('global_var'), dtype=dtype,
        shape=shape, persistable=persistable)
    helper.set_variable_initializer(var, Constant(value))
    return var


def cast(x, dtype):
    from ..core.dtypes import dtype_str
    helper = LayerHelper('cast')
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='cast', inputs={'X': x}, outputs={'Out': out},
                     attrs={'in_dtype': x.dtype,
                            'out_dtype': dtype_str(dtype)})
    if x.lod_level > 0:
        out.lod_level = x.lod_level
        out.lod_length_name = getattr(x, 'lod_length_name', None)
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper('concat', name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type='concat', inputs={'X': input},
                     outputs={'Out': out}, attrs={'axis': axis})
    if input[0].lod_level > 0:
        out.lod_level = input[0].lod_level
        out.lod_length_name = getattr(input[0], 'lod_length_name', None)
    return out


def sums(input, out=None):
    helper = LayerHelper('sum')
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type='sum', inputs={'X': input}, outputs={'Out': out},
                     attrs={})
    return out


def assign(input, output=None):
    # inside an active Switch case, an assign to an existing var blends
    # under the case mask (first matching case wins) instead of
    # overwriting — the Switch lowering contract (see layers/control_flow)
    if output is not None:
        from . import control_flow as _cf
        if _cf._switch_stack:
            if not isinstance(input, Variable):
                input = assign(input)   # materialize const as a temp var
            _cf._in_switch_assign(output, input)
            return output
    helper = LayerHelper('assign')
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(type='assign', inputs={'X': input},
                         outputs={'Out': output}, attrs={})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(arr.dtype))
        helper.append_op(type='assign_value', inputs={},
                         outputs={'Out': output},
                         attrs={'shape': list(arr.shape),
                                'values': arr.reshape(-1).tolist(),
                                'dtype': str(arr.dtype)})
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper('fill_constant')
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='fill_constant', inputs={},
                     outputs={'Out': out},
                     attrs={'shape': [int(s) for s in shape],
                            'value': float(value), 'dtype': out.dtype})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper('fill_constant_batch_size_like')
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='fill_constant_batch_size_like',
                     inputs={'Input': input}, outputs={'Out': out},
                     attrs={'shape': list(shape), 'value': float(value),
                            'input_dim_idx': input_dim_idx,
                            'output_dim_idx': output_dim_idx,
                            'dtype': dtype})
    out.stop_gradient = True
    return out


def argmin(x, axis=0):
    helper = LayerHelper('arg_min')
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='arg_min', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper('arg_max')
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='arg_max', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': axis})
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper('argsort', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='argsort', inputs={'X': input},
                     outputs={'Out': out, 'Indices': ids},
                     attrs={'axis': axis})
    return out, ids


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def zeros_like(x, out=None):
    helper = LayerHelper('zeros_like')
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='fill_zeros_like', inputs={'X': x},
                     outputs={'Out': out}, attrs={})
    return out


def reverse(x, axis):
    helper = LayerHelper('reverse')
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='reverse', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': [axis] if isinstance(axis, int)
                            else list(axis)})
    return out


def has_inf(x):
    helper = LayerHelper('isinf')
    out = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='has_inf', inputs={'X': x}, outputs={'Out': out},
                     attrs={})
    return out


def has_nan(x):
    helper = LayerHelper('isnan')
    out = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='has_nan', inputs={'X': x}, outputs={'Out': out},
                     attrs={})
    return out


def isfinite(x):
    helper = LayerHelper('isfinite')
    out = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='isfinite', inputs={'X': x}, outputs={'Out': out},
                     attrs={})
    return out


def tensor_array_to_tensor(input, axis=1, name=None):
    return concat(input, axis=axis, name=name), None
