"""Metric layers: accuracy, auc.

Parity: reference python/paddle/fluid/layers/metric_op.py.
"""
from ..core.layer_helper import LayerHelper
from ..initializer import Constant

__all__ = ['accuracy', 'auc']


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper('accuracy')
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='top_k', inputs={'X': input},
                     outputs={'Out': values, 'Indices': indices},
                     attrs={'k': k})
    acc_out = helper.create_variable_for_type_inference('float32')
    if correct is None:
        correct = helper.create_variable_for_type_inference('int32')
    if total is None:
        total = helper.create_variable_for_type_inference('int32')
    helper.append_op(type='accuracy',
                     inputs={'Out': values, 'Indices': indices,
                             'Label': label},
                     outputs={'Accuracy': acc_out, 'Correct': correct,
                              'Total': total}, attrs={})
    return acc_out


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1):
    helper = LayerHelper('auc')
    stat_pos = helper.create_global_variable(
        persistable=True, dtype='float32', shape=[num_thresholds + 1],
        name=helper.name + '_stat_pos')
    stat_neg = helper.create_global_variable(
        persistable=True, dtype='float32', shape=[num_thresholds + 1],
        name=helper.name + '_stat_neg')
    for v in (stat_pos, stat_neg):
        v.stop_gradient = True
        helper.set_variable_initializer(v, Constant(0.0))
    auc_out = helper.create_variable_for_type_inference('float32')
    helper.append_op(type='auc',
                     inputs={'Predict': input, 'Label': label,
                             'StatPos': stat_pos, 'StatNeg': stat_neg},
                     outputs={'AUC': auc_out, 'StatPosOut': stat_pos,
                              'StatNegOut': stat_neg},
                     attrs={'curve': curve,
                            'num_thresholds': num_thresholds})
    return auc_out, [stat_pos, stat_neg], [stat_pos, stat_neg]
