"""paddle_tpu.layers — flattened layer namespace (parity:
python/paddle/fluid/layers/__init__.py)."""
from . import nn
from .nn import *  # noqa
from . import io
from .io import *  # noqa
from . import tensor
from .tensor import *  # noqa
from . import ops
from .ops import *  # noqa
from . import control_flow
from .control_flow import *  # noqa
from . import metric_op
from .metric_op import *  # noqa
from . import learning_rate_scheduler
from .learning_rate_scheduler import *  # noqa
from . import detection
from .detection import *  # noqa
from . import layer_function_generator
from .layer_function_generator import (  # noqa
    deprecated, generate_layer_fn, generate_layer_fn_noattr, autodoc,
    templatedoc)

__all__ = []
__all__ += layer_function_generator.__all__
__all__ += nn.__all__
__all__ += io.__all__
__all__ += tensor.__all__
__all__ += ops.__all__
__all__ += control_flow.__all__
__all__ += metric_op.__all__
__all__ += learning_rate_scheduler.__all__
__all__ += detection.__all__
