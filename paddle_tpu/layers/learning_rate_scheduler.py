"""Learning-rate decay schedules, built as graph ops on the step counter.

Parity: reference python/paddle/fluid/layers/learning_rate_scheduler.py.
The schedule math runs inside the jitted train step, keyed off the
persistable `@LR_DECAY_COUNTER@` variable.
"""
import math

from . import nn
from . import ops
from . import tensor
from .nn import autoincreased_step_counter

__all__ = ['exponential_decay', 'natural_exp_decay', 'inverse_time_decay',
           'polynomial_decay', 'piecewise_decay', 'noam_decay',
           'cosine_decay', 'append_LARS', 'linear_lr_warmup']


def _decay_step_counter(begin=0):
    global_step = autoincreased_step_counter(
        counter_name='@LR_DECAY_COUNTER@', begin=begin, step=1)
    return tensor.cast(global_step, 'float32')


def noam_decay(d_model, warmup_steps):
    global_step = _decay_step_counter(1)
    a = global_step ** -0.5
    b = (warmup_steps ** -1.5) * global_step
    return (d_model ** -0.5) * nn.elementwise_min(a, b)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate * (decay_rate ** div_res)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate * ops.exp(-1 * decay_rate * div_res)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate / (1 + decay_rate * div_res)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    global_step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(global_step / decay_steps)
        one_var = tensor.fill_constant(shape=[1], dtype='float32', value=1.0)
        # max(div_res, 1) when step == 0
        div_res = nn.elementwise_max(div_res, one_var)
        decay_steps_var = decay_steps * div_res
    else:
        decay_steps_var = tensor.fill_constant(
            shape=[1], dtype='float32', value=float(decay_steps))
        global_step = nn.elementwise_min(global_step, decay_steps_var)
    frac = (1 - global_step / decay_steps_var) ** power
    return (learning_rate - end_learning_rate) * frac + end_learning_rate


def piecewise_decay(boundaries, values):
    """lr = values[i] for step in (boundaries[i-1], boundaries[i]]."""
    assert len(values) - len(boundaries) == 1
    global_step = _decay_step_counter()
    # piecewise via sum of indicator windows (branch-free, XLA-friendly)
    prev = None
    pieces = []
    for i, b in enumerate(boundaries):
        bvar = tensor.fill_constant([1], 'float32', float(b))
        ind = tensor.cast(global_step < bvar, 'float32')
        if prev is None:
            w = ind
        else:
            w = ind - prev
        pieces.append(w * values[i])
        prev = ind
    last = 1.0 - prev if prev is not None else 1.0
    out = pieces[0]
    for p in pieces[1:]:
        out = out + p
    out = out + last * values[-1]
    return out


def cosine_decay(learning_rate, step_each_epoch, epochs):
    global_step = _decay_step_counter()
    cur_epoch = ops.floor(global_step / step_each_epoch)
    return learning_rate * 0.5 * (
        ops.cos(cur_epoch * (math.pi / epochs)) + 1)


def append_LARS(params_grads, learning_rate, weight_decay):
    raise NotImplementedError(
        'use paddle_tpu.optimizer.LarsMomentumOptimizer')


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    global_step = _decay_step_counter()
    ws = tensor.fill_constant([1], 'float32', float(warmup_steps))
    warm = tensor.cast(global_step < ws, 'float32')
    warm_lr = start_lr + (end_lr - start_lr) * (global_step / ws)
    if not hasattr(learning_rate, 'block'):
        learning_rate = tensor.fill_constant([1], 'float32',
                                             float(learning_rate))
    return warm * warm_lr + (1.0 - warm) * learning_rate
