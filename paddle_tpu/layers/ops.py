"""Generated thin wrappers for simple unary ops.

Parity: reference python/paddle/fluid/layers/ops.py +
layer_function_generator.py.
"""
from ..core.layer_helper import LayerHelper

__all__ = [
    'sigmoid', 'logsigmoid', 'exp', 'tanh', 'tanh_shrink', 'softshrink',
    'sqrt', 'rsqrt', 'abs', 'ceil', 'floor', 'cos', 'sin', 'round',
    'reciprocal', 'square', 'softplus', 'softsign', 'uniform_random',
    'cumsum', 'thresholded_relu', 'hard_shrink', 'sign', 'erf',
]


def _make_unary(op_type):
    def func(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={'X': x},
                         outputs={'Out': out}, attrs={})
        if x.lod_level > 0:
            out.lod_level = x.lod_level
            out.lod_length_name = getattr(x, 'lod_length_name', None)
        return out
    func.__name__ = op_type
    func.__doc__ = 'Elementwise %s (generated; ref layers/ops.py).' % op_type
    return func


for _op in ['sigmoid', 'logsigmoid', 'exp', 'tanh', 'tanh_shrink', 'sqrt',
            'rsqrt', 'abs', 'ceil', 'floor', 'cos', 'sin', 'round',
            'reciprocal', 'square', 'softplus', 'softsign', 'sign', 'erf']:
    globals()[_op] = _make_unary(_op)


def softshrink(x, alpha=None, name=None):
    helper = LayerHelper('softshrink', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='softshrink', inputs={'X': x},
                     outputs={'Out': out},
                     attrs={'lambda': alpha if alpha is not None else 0.5})
    return out


def hard_shrink(x, threshold=None, name=None):
    helper = LayerHelper('hard_shrink', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='hard_shrink', inputs={'X': x},
                     outputs={'Out': out},
                     attrs={'threshold': threshold if threshold is not None
                            else 0.5})
    return out


def thresholded_relu(x, threshold=None, name=None):
    helper = LayerHelper('thresholded_relu', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='thresholded_relu', inputs={'X': x},
                     outputs={'Out': out},
                     attrs={'threshold': threshold if threshold is not None
                            else 1.0})
    return out


def cumsum(x, axis=None, exclusive=None, reverse=None, name=None):
    helper = LayerHelper('cumsum', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='cumsum', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': axis if axis is not None else -1,
                            'exclusive': bool(exclusive),
                            'reverse': bool(reverse)})
    return out


def uniform_random(shape, dtype='float32', min=-1.0, max=1.0, seed=0):
    helper = LayerHelper('uniform_random')
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='uniform_random', inputs={},
                     outputs={'Out': out},
                     attrs={'shape': list(shape), 'min': min, 'max': max,
                            'seed': seed, 'dtype': dtype})
    return out
