"""RecordIO conversion helpers.

Parity: reference python/paddle/fluid/recordio_writer.py
(convert_reader_to_recordio_file / _files).  Backed by the native C++ record
format in native/ (src/datafeed.cc) instead of the reference's recordio/
library; the feeder_list maps reader tuples onto named slots exactly like the
reference's DataFeeder path.
"""
import contextlib

import numpy as np

from . import native

__all__ = ['convert_reader_to_recordio_file',
           'convert_reader_to_recordio_files']


@contextlib.contextmanager
def create_recordio_writer(filename, compressor=None, max_num_records=None):
    w = native.RecordWriter(filename)
    try:
        yield w
    finally:
        w.close()


def _to_sample(item, feeder=None):
    if feeder is not None:
        item = feeder.feed([item])
        return [np.asarray(v) for v in item.values()]
    return [np.asarray(v) for v in item]


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None,
                                    compressor=None, max_num_records=1000,
                                    feed_order=None):
    """Serialize every sample from the reader into one record file.
    Returns the number of records written."""
    n = 0
    with create_recordio_writer(filename) as w:
        for item in reader_creator():
            w.write(_to_sample(item, feeder))
            n += 1
    return n


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder=None,
                                     compressor=None, max_num_records=1000,
                                     feed_order=None):
    """Shard the reader's samples into multiple record files,
    `batch_per_file` records each.  Returns the file list."""
    fns = []
    w = None
    n = 0
    try:
        for item in reader_creator():
            if n % batch_per_file == 0:
                if w is not None:
                    w.close()
                fn = '%s-%05d' % (filename, len(fns))
                fns.append(fn)
                w = native.RecordWriter(fn)
            w.write(_to_sample(item, feeder))
            n += 1
    finally:
        if w is not None:
            w.close()
    return fns
