"""Process-global chained signal-handler installation.

Two subsystems want a say in what happens on SIGTERM/SIGINT: the
training runtime flushes a final checkpoint (train/checkpoint.py) and
the serving runtime drains in-flight requests (serving/engine.py).
Python gives one handler slot per signal per process, so both chain:
each installer saves the previous handler and invokes it after its own
work.  Chaining by hand is easy to get wrong in exactly two ways this
module exists to prevent:

  * **Double-chain.**  Installing the same owner twice (Trainer.train
    called again, an engine restarted) must not chain a handler to an
    older copy of itself — the flush/drain would run twice per signal.
    Installation is idempotent per ``(token, signum)``.
  * **Worker-thread install.**  ``signal.signal`` raises ``ValueError``
    off the main thread.  A serving worker thread arming process-global
    handlers would also be a trap even if it worked — so the install is
    detected, warned about ONCE, and skipped instead of crashing.
"""
import os
import signal as _signal
import threading
import warnings

__all__ = ['install', 'uninstall', 'installed', 'chain_previous',
           'on_main_thread']

_LOCK = threading.Lock()
_INSTALLED = {}          # (token, signum) -> (handler, prev_handler)
_WARNED_THREAD = [False]


def on_main_thread():
    return threading.current_thread() is threading.main_thread()


def install(token, signums, make_handler):
    """Install chained handlers for ``signums`` under owner ``token``.

    ``make_handler(signum, prev) -> handler`` builds the handler given
    the previously installed one (chain to it via :func:`chain_previous`).
    Returns ``None`` when skipped off the main thread (warned once per
    process), else ``{signum: prev_handler}`` for the signums newly
    installed — already-installed ``(token, signum)`` pairs are skipped
    silently, so a second install never chains a handler to itself.
    """
    if not on_main_thread():
        if not _WARNED_THREAD[0]:
            _WARNED_THREAD[0] = True
            warnings.warn(
                'signal handlers can only be installed from the main '
                'thread; skipping install for %r (signal.signal raises '
                'ValueError on worker threads)' % (token,),
                RuntimeWarning, stacklevel=2)
        return None
    out = {}
    with _LOCK:
        for signum in signums:
            if (token, signum) in _INSTALLED:
                continue
            prev = _signal.getsignal(signum)
            handler = make_handler(signum, prev)
            _signal.signal(signum, handler)
            _INSTALLED[(token, signum)] = (handler, prev)
            out[signum] = prev
    return out


def installed(token, signum=None):
    """Is owner ``token`` currently installed (for ``signum``, or any)?"""
    with _LOCK:
        if signum is not None:
            return (token, signum) in _INSTALLED
        return any(tok == token for tok, _ in _INSTALLED)


def uninstall(token):
    """Restore the pre-install handler for every signum owned by
    ``token`` — but only where our handler is still the active one (a
    later installer chained on top of us keeps its chain intact)."""
    main = on_main_thread()
    with _LOCK:
        for (tok, signum), (handler, prev) in list(_INSTALLED.items()):
            if tok != token:
                continue
            if main and _signal.getsignal(signum) is handler:
                _signal.signal(signum, prev)
            del _INSTALLED[(tok, signum)]


def chain_previous(prev, signum, frame, redeliver=True):
    """Invoke the handler that was active before ours.

    Callable → call it.  ``SIG_IGN`` → nothing.  Default/None →
    ``redeliver=True`` restores ``SIG_DFL`` and re-raises the signal so
    the process still dies from a SIGTERM it was sent (the checkpoint
    flush path); ``redeliver=False`` swallows it so a graceful-drain
    handler can let the application exit on its own schedule."""
    if prev is _signal.SIG_IGN:
        return
    if callable(prev):
        prev(signum, frame)
        return
    if redeliver:
        _signal.signal(signum, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)
