"""Op registry: op type -> JAX implementation.

Replaces the reference's per-device OpKernel registry
(paddle/fluid/framework/op_registry.h, op_info.cc).  Each op registers ONE
pure-JAX impl used for (a) build-time shape inference via jax.eval_shape and
(b) whole-block lowering to a single XLA computation.  There is no per-op
kernel dispatch at runtime — XLA fuses across op boundaries.

Impl signature::

    @register('my_op')
    def my_op(ctx, ins, attrs):
        x = ins['X']              # array, or list of arrays for list slots
        return {'Out': ...}

`ctx.rng()` returns a fresh PRNG key (derived from the run seed and the op's
position in the block, so every op — and every run — gets distinct streams).

An op may ALSO carry an **emit rule** (`register_emit`): a raw-`lax`
fast path the direct Program→jaxpr emitter (core/emit) uses instead of
tracing the kernel when building its memoized per-signature functions.
The kernel stays the semantic reference — tests/test_emitter.py sweeps
every emit rule against its kernel for bitwise parity.
"""
import jax

_REGISTRY = {}

__all__ = ['register', 'register_emit', 'has_op', 'get_op', 'op_names',
           'OpDef', 'InferCtx', 'ExecCtx']


class OpDef(object):
    def __init__(self, name, impl):
        self.name = name
        self.impl = impl
        # optional raw-lax emit rule (same (ctx, ins, attrs) signature);
        # None means the emitter traces the kernel impl instead
        self.emit = None


def register(name):
    def deco(fn):
        if name in _REGISTRY:
            raise ValueError('op %s already registered' % name)
        _REGISTRY[name] = OpDef(name, fn)
        return fn
    return deco


def register_emit(name):
    """Attach a direct-emit rule to an already-registered op.  Rules are
    a perf overlay: they must be bitwise-identical to the kernel (the
    emitter's coverage set distinguishes rule vs kernel emission in the
    AOT fingerprint, so editing one invalidates only its own entries)."""
    def deco(fn):
        od = _REGISTRY.get(name)
        if od is None:
            raise ValueError('emit rule for unregistered op %s' % name)
        if od.emit is not None:
            raise ValueError('emit rule for %s already registered' % name)
        od.emit = fn
        return fn
    return deco


def has_op(name):
    _ensure_ops_loaded()
    return name in _REGISTRY


def get_op(name):
    _ensure_ops_loaded()
    if name not in _REGISTRY:
        raise KeyError('no JAX impl registered for op "%s"' % name)
    return _REGISTRY[name]


def op_names():
    """All registered op types (sorted) — the analysis package uses this
    for coverage checks and did-you-mean suggestions on unknown ops."""
    _ensure_ops_loaded()
    return sorted(_REGISTRY)


_ops_loaded = [False]


def _ensure_ops_loaded():
    # op impl modules register themselves on import; loaded lazily to avoid
    # import cycles (framework -> registry -> ops -> framework)
    if not _ops_loaded[0]:
        _ops_loaded[0] = True
        from .. import ops as _ops  # noqa: F401


class InferCtx(object):
    """Context used during build-time shape inference (abstract eval)."""

    is_infer = True
    mesh = None
    amp = False
    forensic = None

    def __init__(self, op=None):
        self.op = op
        self._key = jax.random.key(0)

    def rng(self, n=0):
        return jax.random.fold_in(self._key, n)


class ExecCtx(object):
    """Per-run context shared by all ops in one lowered block.  `mesh` is
    the executor's device mesh (None single-chip): mesh-aware ops like
    ring_attention pick their collective strategy from it.  `amp` is the
    program's bf16 mixed-precision flag — the fused_elementwise kernel
    replays the executor's per-op AMP policy and needs it in-band.
    `forensic` (default None) is a ForensicProbes collector attached by a
    PT_FORENSIC lowering — op impls that hide internal structure (the
    fused_elementwise replay) probe their sub-ops through it."""

    is_infer = False
    forensic = None

    def __init__(self, base_key, mesh=None, amp=False):
        self.base_key = base_key
        self.mesh = mesh
        self.amp = amp

    def for_op(self, op_index, op):
        return OpCtx(self, op_index, op)


class _SubOpShim(object):
    """Op stand-in for one serialized sub-op of a fused_elementwise op —
    just enough surface (type, attrs) for OpCtx to derive RNG streams."""
    __slots__ = ('type', 'attrs')

    def __init__(self, type, attrs):
        self.type = type
        self.attrs = attrs


class OpCtx(object):
    is_infer = False

    def __init__(self, exec_ctx, op_index, op):
        self._exec = exec_ctx
        self.op_index = op_index
        self.op = op

    @property
    def mesh(self):
        return self._exec.mesh

    @property
    def amp(self):
        return self._exec.amp

    @property
    def forensic(self):
        return getattr(self._exec, 'forensic', None)

    def rng(self, n=0):
        # op streams are 1-based: stream 0 off the run key is reserved for
        # the executor itself (the run key is already one fold deep — the
        # run counter is folded into the program key — so op draws must
        # never collide with a bare counter fold).  An optimized program
        # pins each op's ORIGINAL position in an `rng_stream` attr (see
        # core/passes) so rewrites never shift RNG streams.
        idx = self.op.attrs.get('rng_stream')
        if idx is None:
            idx = self.op_index
        return jax.random.fold_in(self._exec.base_key,
                                  (idx + 1) * 1009 + n)

    def sub_ctx(self, sub_desc):
        """Context for one replayed sub-op of a fused_elementwise op."""
        return OpCtx(self._exec, self.op_index,
                     _SubOpShim(sub_desc['type'], sub_desc['attrs']))
