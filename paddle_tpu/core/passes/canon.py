"""Attr canonicalization: make the program desc say what the lowering
will actually do.

(1) 64-bit dtype attrs narrow to their 32-bit twins when jax runs with
x64 disabled (the default) — the kernels already materialize through
``dtypes.jax_dtype``, which truncates identically, so this is purely
descriptive: it removes the D004 lint hazard and makes the desc
fingerprint match runtime semantics.  With x64 enabled nothing narrows.

(2) Initializer dedup across blocks: a sub-block ``fill_constant`` /
``fill_zeros_like``-free constant identical to one already produced in
an ancestor block (same attrs, producer not rebound) rewrites to an
``assign`` of the ancestor's var — the constant materializes once per
program instead of once per control-flow body, and `assign` traces to
nothing.
"""
import json

import numpy as np

__all__ = ['run']

_DTYPE_ATTRS = ('dtype', 'in_dtype', 'out_dtype')
_NARROW = {'int64': 'int32', 'uint64': 'uint32', 'float64': 'float32',
           'complex128': 'complex64'}


def _narrow_attrs(program, stats):
    import jax
    if jax.config.jax_enable_x64:
        return
    for block in program.blocks:
        for op in block.ops:
            attr_dicts = [op.attrs]
            # fused sub-programs carry their own attr dicts
            for sub in op.attrs.get('sub_ops') or ():
                attr_dicts.append(sub['attrs'])
            for attrs in attr_dicts:
                for key in _DTYPE_ATTRS:
                    v = attrs.get(key)
                    name = v if isinstance(v, str) else (
                        np.dtype(v).name if v is not None else None)
                    if name in _NARROW:
                        attrs[key] = _NARROW[name]
                        stats['attrs_narrowed'] += 1
                        program._bump()
                # np scalar attrs: normalize so desc json and attr
                # hashing are width-stable
                for k, v in list(attrs.items()):
                    if isinstance(v, np.integer):
                        attrs[k] = int(v)
                    elif isinstance(v, np.floating):
                        attrs[k] = float(v)


def _const_key(op):
    if op.type != 'fill_constant' or op.inputs:
        return None
    return json.dumps(
        {k: v for k, v in op.attrs.items()
         if k in ('shape', 'value', 'dtype')},
        sort_keys=True, default=str)


def _root_owner_index(program):
    """block idx -> index (in the ROOT block) of the op whose sub-block
    tree contains it; None for the root block or unowned blocks."""
    owner = {}  # sub idx -> (owning block idx, op index)
    for b in program.blocks:
        for i, op in enumerate(b.ops):
            sub = op.attrs.get('sub_block')
            if sub is not None:
                owner[sub] = (b.idx, i)
    result = {}
    for idx in range(1, len(program.blocks)):
        cur, hops = idx, 0
        while cur in owner and owner[cur][0] != 0 and hops < 64:
            cur = owner[cur][0]
            hops += 1
        result[idx] = owner[cur][1] if cur in owner else None
    return result


def _dedupe_initializers(program, ctx, stats):
    if len(program.blocks) < 2:
        return
    root = program.blocks[0]
    root_owner = _root_owner_index(program)
    # root-block constants, keyed by attrs, with their producer position:
    # a sub-block may only reuse a constant produced BEFORE its owning op
    by_key = {}
    for i, op in enumerate(root.ops):
        key = _const_key(op)
        if key is None:
            continue
        out = op.output_names()
        if len(out) == 1 and out[0] not in ctx.multi_written and \
                out[0] not in ctx.persistable:
            by_key.setdefault(key, (i, out[0]))
    if not by_key:
        return
    for block in program.blocks[1:]:
        limit = root_owner.get(block.idx)
        if limit is None:
            continue
        for op in block.ops:
            key = _const_key(op)
            if key is None:
                continue
            out = op.output_names()
            if len(out) != 1 or out[0] in ctx.multi_written or \
                    out[0] in ctx.persistable or out[0] in ctx.cf_pinned:
                continue
            hit = by_key.get(key)
            if hit is None or hit[0] >= limit or hit[1] == out[0]:
                continue
            src = hit[1]
            op.type = 'assign'
            op.inputs = {'X': [src]}
            op.input_is_list = {'X': False}
            op.attrs = {k: op.attrs[k] for k in ('op_role', 'rng_stream',
                                                 'recompute_id')
                        if k in op.attrs}
            stats['initializers_deduped'] += 1
            program._bump()


def run(program, ctx):
    stats = {'attrs_narrowed': 0, 'initializers_deduped': 0}
    _narrow_attrs(program, stats)
    _dedupe_initializers(program, ctx, stats)
    return stats
