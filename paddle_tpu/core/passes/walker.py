"""Shared Program-IR reachability machinery.

One liveness walker serves two consumers with different stakes:

  * the analysis D005/D006 pass (analysis/passes/liveness.py) REPORTS
    dead ops and must match what a user reading the program would call
    dead (no overwrite-kill subtlety: a duplicate writer is D009's
    domain, not D005's), and
  * the DCE rewrite pass (core/passes/dce.py) REMOVES ops and wants the
    sharper classic-liveness rule: a write that is overwritten before
    any read is dead even though the name itself is live downstream.

Both walk the same sub-block read closure — control-flow bodies read
outer vars straight from the lowering env, not through the owning op's
input slots, so those names count as escaping uses — and pin the same
side-effect op set.  `kill_overwrites` selects the rule.
"""

__all__ = ['SIDE_EFFECT_OPS', 'sub_block_reads', 'persistable_names',
           'block_live_mask', 'control_flow_pinned', 'block_last_reads']

# ops that are alive regardless of dataflow (observable effects)
SIDE_EFFECT_OPS = {'print', 'py_func', '__backward__', 'write_to_array'}


def control_flow_pinned(program):
    """Names the control-flow lowerer pattern-matches on, closed over
    their producer chains.

    control_flow_exec reads the IR structurally: `_static_bound` walks
    ``cond.op`` expecting a literal ``less_than(i, fill_constant)``
    chain, and while/recurrent bodies exchange values with the parent by
    NAME through attr lists (update_vars, out_vars, Condition, ...).  A
    rewrite that hides any of these producers inside a fused op (or
    rebinds/merges them) breaks loop lowering — so every rewrite pass
    leaves ops producing pinned names exactly as they are.

    Seeds: all inputs of native control-flow ops plus every string (or
    list-of-strings) attr they carry — attr values that aren't var names
    pin nothing and cost nothing.  The closure then walks producers
    backward so e.g. the fill_constant feeding a loop-bound less_than
    stays visible too.
    """
    from ..control_flow_exec import NATIVE_OPS
    pinned = set()
    for b in program.blocks:
        for op in b.ops:
            if op.type not in NATIVE_OPS and \
                    op.attrs.get('sub_block') is None:
                continue
            pinned |= set(op.input_names())
            for v in op.attrs.values():
                if isinstance(v, str):
                    pinned.add(v)
                elif isinstance(v, (list, tuple)):
                    pinned |= {e for e in v if isinstance(e, str)}
    if not pinned:
        return pinned
    changed = True
    while changed:
        changed = False
        for b in program.blocks:
            for op in reversed(b.ops):
                if set(op.output_names()) & pinned:
                    ins = set(op.input_names())
                    if not ins <= pinned:
                        pinned |= ins
                        changed = True
    return pinned


def sub_block_reads(program, block_idx, seen=None):
    """All var names read anywhere inside a sub-block tree, including
    `__backward__` differentiation targets (attrs['params'])."""
    seen = set() if seen is None else seen
    if block_idx in seen:
        return set()
    seen.add(block_idx)
    reads = set()
    for op in program.block(block_idx).ops:
        reads |= set(op.input_names())
        reads |= set(op.attrs.get('params', ()))
        sub = op.attrs.get('sub_block')
        if sub is not None:
            reads |= sub_block_reads(program, sub, seen)
    return reads


def persistable_names(program):
    """Every persistable (incl. Parameter) name, program-wide."""
    from ..framework import Parameter
    names = set()
    for b in program.blocks:
        names |= {n for n, v in b.vars.items()
                  if v.persistable or isinstance(v, Parameter)}
    return names


def block_last_reads(program, block):
    """Name -> index of the LAST op in `block` that reads it, with reads
    inside a sub-block tree attributed to the op that owns the sub_block
    (the whole body runs while that op runs).  The liveness half of the
    static memory planner (analysis/passes/memplan.py): an activation's
    buffer dies after its last read."""
    last = {}
    for i, op in enumerate(block.ops):
        reads = set(op.input_names())
        if op.type == '__backward__':
            reads |= set(op.attrs.get('params', ()))
        sub = op.attrs.get('sub_block')
        if sub is not None:
            reads |= sub_block_reads(program, sub)
        for n in reads:
            last[n] = i
    return last


def block_live_mask(program, block, root_names, persistable=None,
                    kill_overwrites=False):
    """Reverse liveness walk over one block's ops.

    Returns a list of booleans parallel to ``block.ops``: True = alive.
    An op is alive when any output (transitively) reaches a root name, a
    persistable write, a sub-block boundary, or a side-effecting op.

    kill_overwrites=False (analysis reporting): a name stays needed even
    across an intervening full write, so every writer of a downstream-
    read name counts as alive.
    kill_overwrites=True (DCE rewriting): a write KILLS the need above
    it — an earlier write that is overwritten before any read is dead.
    """
    if persistable is None:
        persistable = persistable_names(program)
    needed = set(root_names)
    alive = [False] * len(block.ops)
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        outs = set(op.output_names())
        is_alive = (bool(outs & needed) or
                    bool(outs & persistable) or
                    op.type in SIDE_EFFECT_OPS or
                    op.attrs.get('sub_block') is not None)
        if is_alive:
            alive[i] = True
            if kill_overwrites:
                needed -= outs
            needed |= set(op.input_names())
            if op.type == '__backward__':
                needed |= set(op.attrs.get('params', ()))
            sub = op.attrs.get('sub_block')
            if sub is not None:
                needed |= sub_block_reads(program, sub)
    return alive
