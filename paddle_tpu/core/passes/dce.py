"""Dead-op / dead-var elimination — the executable twin of the analysis
D005/D006 liveness pass (same walker, sharper kill-on-overwrite rule).

Liveness roots: the fetch set, persistable writes (the scope writeback),
side-effect ops, and sub-block boundaries.  Sub-blocks are rewritten too,
with every name declared OUTSIDE the block added to the roots — control-
flow bodies write loop carries straight into the lowering env, so any
outer-visible write must survive.

Removed ops are gone from the traced program (one fewer Python dispatch
and jaxpr contribution each); removed vars keep the block description in
step with the op list.  Feed vars (``is_data``) and ``@``-companion
plumbing (@LENGTH / @GRAD / counters) are never dropped: the executor's
feed validation and LoD synthesis look them up by name.
"""
from . import walker

__all__ = ['run', 'sweep_dead']


def _block_roots(program, block, fetch_names, pinned):
    """Names whose writes must survive in `block`."""
    roots = set(fetch_names) | pinned
    if block.idx != 0:
        # outer-visible names escape through the control-flow env
        b = block.parent
        while b is not None:
            roots |= set(b.vars)
            b = b.parent
    return roots


def sweep_dead(program, fetch_names, stats=None, pinned=None):
    """One DCE sweep over every block; returns ops_removed count."""
    persistable = walker.persistable_names(program)
    if pinned is None:
        pinned = walker.control_flow_pinned(program)
    removed = 0
    for block in program.blocks:
        alive = walker.block_live_mask(
            program, block,
            _block_roots(program, block, fetch_names, pinned),
            persistable=persistable, kill_overwrites=True)
        if all(alive):
            continue
        removed += alive.count(False)
        block.ops = [op for op, a in zip(block.ops, alive) if a]
    if removed:
        program._bump()
    if stats is not None:
        stats['ops_removed'] = stats.get('ops_removed', 0) + removed
    return removed


def _sweep_dead_vars(program, fetch_names):
    """Drop block-local var descriptions nothing references any more."""
    from ..framework import Parameter
    used = set(fetch_names) | walker.control_flow_pinned(program)
    for b in program.blocks:
        for op in b.ops:
            used.update(op.input_names())
            used.update(op.output_names())
            used.update(op.attrs.get('params', ()))
            for sub in op.attrs.get('sub_ops') or ():
                # fused runs reference their internal names through the
                # serialized sub-program, not through input slots
                for ns in sub['inputs'].values():
                    used.update(ns)
                for ns in sub['outputs'].values():
                    used.update(ns)
    removed = 0
    for b in program.blocks:
        keep = {}
        for name, v in b.vars.items():
            if (name in used or '@' in name or v.persistable or
                    v.is_data or isinstance(v, Parameter)):
                keep[name] = v
            else:
                removed += 1
        b.vars = keep
    return removed


def run(program, ctx):
    stats = {'ops_removed': 0, 'vars_removed': 0}
    # cascade: removing an op can orphan its producers
    while sweep_dead(program, ctx.fetch_names, stats,
                     pinned=ctx.cf_pinned):
        pass
    stats['vars_removed'] = _sweep_dead_vars(program, ctx.fetch_names)
    return stats
