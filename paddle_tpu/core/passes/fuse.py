"""Elementwise-chain fusion: collapse maximal consecutive runs of
elementwise/glue ops into one ``fused_elementwise`` op.

Tensor Processing Primitives (arxiv 2104.05755) argues the backend
should see few, large primitives instead of long scalar-op chains; under
whole-block tracing the cost of a K-op glue chain is K Python dispatches
through the executor loop and K env-dict rebinds per trace.  A fused op
carries the run as a serialized sub-program in its attrs and replays it
inside ONE registered impl (ops/fused.py), so the chain costs one
dispatch — and one op in every program-wide walk (lint, fingerprint,
desc serialization).

The run is a DAG, not just a linear chain: K consecutive fusable ops
fuse regardless of internal wiring (158 independent per-param `adam`
updates collapse to one op just like a scale->relu->cast chain).  A name
written inside the run ESCAPES — and becomes a fused-op output — when it
is persistable, fetched, read outside the run (including sub-block env
reads), or also written outside the run.  Everything else stays internal
to the replayed sub-program.

Bitwise parity with the unfused program is preserved by construction:
  * sub-ops replay through their own registered kernels in original
    order (identical jaxpr);
  * RNG streams are pinned by the pipeline's `rng_stream` stamping, so
    dropout masks don't shift when op indices change;
  * per-output `stop_gradient` and the executor's AMP elementwise-match
    policy are recorded/replayed inside the fused impl.
"""
import numpy as np

__all__ = ['run', 'FUSABLE_OPS', 'FUSED_OP', 'KERNEL_TIER_OPS']

FUSED_OP = 'fused_elementwise'

# reduction/attention ops the kernelgen tier lowers through DEDICATED
# generated kernels (row reductions, flash attention — KERNEL_RULES
# kinds 'row'/'attention').  They fuse like any elementwise op, and
# unlike pure glue they justify a fused group even as a SINGLETON run:
# a lone softmax between two matmuls must still reach the kernel tier.
KERNEL_TIER_OPS = {'softmax', 'layer_norm', 'flash_attention'}

# unary/binary elementwise math + zero-flop glue + per-param optimizer
# updates (elementwise over the param): anything whose kernel is pure,
# rng-stable (via rng_stream), and — KERNEL_TIER_OPS excepted — free of
# cross-element reductions
FUSABLE_OPS = {
    # elementwise binary
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_pow', 'elementwise_max',
    'elementwise_min', 'elementwise_mod', 'elementwise_floordiv',
    # elementwise unary / activations
    'scale', 'cast', 'clip', 'relu', 'relu6', 'sigmoid', 'tanh', 'exp',
    'log', 'sqrt', 'rsqrt', 'abs', 'square', 'sign', 'floor', 'ceil',
    'round', 'reciprocal', 'pow', 'leaky_relu', 'elu', 'selu',
    'softplus', 'softsign', 'brelu', 'hard_sigmoid', 'swish', 'stanh',
    'logsigmoid', 'soft_relu', 'hard_shrink', 'softshrink',
    'tanh_shrink', 'thresholded_relu', 'erf', 'sin', 'cos', 'increment',
    'label_smooth',
    # comparisons / logicals (elementwise)
    'equal', 'not_equal', 'less_than', 'less_equal', 'greater_than',
    'greater_equal', 'logical_and', 'logical_or', 'logical_not',
    'logical_xor',
    # constants / identities / layout glue (zero-flop)
    'fill_constant', 'fill_zeros_like', 'fill_constant_batch_size_like',
    'assign', 'reshape', 'transpose', 'unsqueeze', 'squeeze', 'flatten',
    # rng glue (streams pinned via rng_stream)
    'dropout', 'uniform_random', 'gaussian_random',
    'truncated_gaussian_random',
    # per-param optimizer updates
    'sgd', 'momentum', 'adam', 'adamax', 'adagrad', 'decayed_adagrad',
    'adadelta', 'rmsprop', 'ftrl',
} | KERNEL_TIER_OPS

# never nest: keeps the pipeline idempotent and the impl non-recursive
assert FUSED_OP not in FUSABLE_OPS


def _plain_attrs(attrs):
    """JSON-safe copy of sub-op attrs (io.py only normalizes np scalars
    at the TOP attr level, not inside nested sub_ops).  Returns None when
    an attr can't be made plain — the op then simply doesn't fuse."""
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.integer):
            v = int(v)
        elif isinstance(v, np.floating):
            v = float(v)
        elif isinstance(v, np.bool_):
            v = bool(v)
        elif isinstance(v, tuple):
            v = list(v)
        if not isinstance(v, (str, int, float, bool, list, type(None))):
            return None
        if isinstance(v, list) and not all(
                isinstance(e, (str, int, float, bool)) for e in v):
            return None
        out[k] = v
    return out


def _fusable(op, block, ctx):
    if op.type not in FUSABLE_OPS or op.attrs.get('sub_block') is not None:
        return None
    # control-flow-pinned producers stay visible: the loop lowerer
    # pattern-matches them by op type (see walker.control_flow_pinned)
    if any(n in ctx.cf_pinned for n in op.output_names()):
        return None
    attrs = _plain_attrs(op.attrs)
    if attrs is None:
        return None
    stop_grad = []
    for n in op.output_names():
        v = block._find_var_recursive(n)
        if v is not None and v.stop_gradient:
            stop_grad.append(n)
    return {'type': op.type,
            'inputs': {s: list(ns) for s, ns in op.inputs.items()},
            'outputs': {s: list(ns) for s, ns in op.outputs.items()},
            'input_is_list': dict(op.input_is_list),
            'output_is_list': dict(op.output_is_list),
            'attrs': attrs,
            'stop_grad': stop_grad}


def _fuse_run(block, start, run, readers_outside, ctx):
    """Replace block.ops[start:start+len(run)] with one fused op.
    `run` is [(op, sub_desc)]."""
    from ..framework import Operator
    produced = set()
    ext_in, arg_names = [], set()
    for op, _ in run:
        for n in op.input_names():
            if n not in produced and n not in arg_names:
                arg_names.add(n)
                ext_in.append(n)
        produced.update(op.output_names())
    out_names = []
    for op, _ in run:
        for n in op.output_names():
            if n in out_names:
                continue
            if (n in ctx.persistable or n in ctx.fetch_names or
                    n in readers_outside or n in ctx.multi_written):
                out_names.append(n)
    if not out_names:
        # a run computing nothing observable is DCE's business, not ours
        return None
    first_op = run[0][0]
    fused = Operator(
        block, FUSED_OP,
        inputs={'X': list(ext_in)},
        outputs={'Out': list(out_names)},
        attrs={'sub_ops': [d for _, d in run],
               'arg_names': list(ext_in),
               'out_names': list(out_names),
               'fused_count': len(run),
               # sub-ops draw from their own pinned streams; the op-level
               # stream is inherited so re-stamping on a second pipeline
               # application is a no-op (idempotence)
               'rng_stream': first_op.attrs.get('rng_stream', start),
               'op_role': first_op.attrs.get('op_role', 'forward')})
    rid = first_op.attrs.get('recompute_id')
    if rid is not None:
        fused.attrs['recompute_id'] = rid
    fused.source_loc = first_op.source_loc
    block.ops[start:start + len(run)] = [fused]
    for n in out_names:
        v = block._find_var_recursive(n)
        if v is not None:
            v.op = fused
    return fused


def run(program, ctx):
    stats = {'ops_fused': 0, 'chains': 0, 'max_chain': 0}
    for block in program.blocks:
        # readers by name, positions within THIS block; plus names read
        # from other blocks / sub-block envs / __backward__ params
        pos_readers = {}
        for i, op in enumerate(block.ops):
            for n in set(op.input_names()) | set(
                    op.attrs.get('params', ())):
                pos_readers.setdefault(n, []).append(i)
        # reads from OTHER blocks (control-flow bodies read parent names
        # straight from the env, parents read body results after the
        # loop); a block's own reads are position-tracked in pos_readers
        foreign_reads = set()
        for b in program.blocks:
            if b.idx == block.idx:
                continue
            for op in b.ops:
                foreign_reads |= set(op.input_names())
                foreign_reads |= set(op.attrs.get('params', ()))
        if block.idx != 0:
            # control-flow bodies: writes to outer-visible names are loop
            # carries read by name from the lowering env — always escape
            b = block.parent
            while b is not None:
                foreign_reads |= set(b.vars)
                b = b.parent
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            desc = _fusable(op, block, ctx)
            if desc is None:
                i += 1
                continue
            rid = op.attrs.get('recompute_id')
            run_ops = [(op, desc)]
            j = i + 1
            while j < len(block.ops):
                nxt = block.ops[j]
                if nxt.attrs.get('recompute_id') != rid:
                    break
                ndesc = _fusable(nxt, block, ctx)
                if ndesc is None:
                    break
                run_ops.append((nxt, ndesc))
                j += 1
            if len(run_ops) < 2 and not any(
                    o.type in KERNEL_TIER_OPS for o, _ in run_ops):
                i = j
                continue
            lo, hi = i, j  # [lo, hi) is the run
            readers_outside = set()
            for op_k, _ in run_ops:
                for n in op_k.output_names():
                    if any(p < lo or p >= hi
                           for p in pos_readers.get(n, ())):
                        readers_outside.add(n)
                    if n in foreign_reads:
                        readers_outside.add(n)
            fused = _fuse_run(block, lo, run_ops, readers_outside, ctx)
            if fused is None:
                i = j
                continue
            stats['ops_fused'] += len(run_ops)
            stats['chains'] += 1
            stats['max_chain'] = max(stats['max_chain'], len(run_ops))
            program._bump()
            # positions shifted: rebuild the reader index
            pos_readers = {}
            for k, op_k in enumerate(block.ops):
                for n in set(op_k.input_names()) | set(
                        op_k.attrs.get('params', ())):
                    pos_readers.setdefault(n, []).append(k)
            i = lo + 1
    return stats
