"""Common-subexpression elimination within a block.

Two ops compute the same value when they have the same type, the same
canonical inputs (after upstream CSE rebinding), and the same attrs —
modulo bookkeeping attrs (`op_role`, `rng_stream`, `recompute_id`) that
don't change the math.  The duplicate is dropped and every later read of
its outputs rebinds to the first op's outputs.

Skipped, conservatively:
  * RNG ops — two dropout ops are two DIFFERENT draws;
  * side-effect / control-flow / `__backward__` ops;
  * ops writing persistables or fetched names (the binding itself is the
    contract with the scope writeback / fetch list);
  * any name written more than once program-wide (names are rebindable
    in this IR, so textually equal inputs may be different values);
  * outputs read inside sub-blocks (those reads bypass input slots).
"""
import json

from . import walker

__all__ = ['run', 'RNG_OPS']

# ops drawing from ctx.rng(): never merged, never folded
RNG_OPS = {
    'dropout', 'uniform_random', 'gaussian_random',
    'truncated_gaussian_random', 'uniform_random_batch_size_like',
    'gaussian_random_batch_size_like', 'sampling_id', 'random_crop',
    'nce',
}

_IGNORED_ATTRS = ('op_role', 'rng_stream', 'recompute_id')


def _attr_key(attrs):
    pruned = {k: v for k, v in attrs.items() if k not in _IGNORED_ATTRS}
    return json.dumps(pruned, sort_keys=True, default=str)


def run(program, ctx):
    stats = {'ops_removed': 0}
    fetch = set(ctx.fetch_names)
    sub_reads = set()
    for b in program.blocks:
        for op in b.ops:
            sub = op.attrs.get('sub_block')
            if sub is not None:
                sub_reads |= walker.sub_block_reads(program, sub)
    for block in program.blocks:
        seen = {}     # key -> canonical op
        rename = {}   # dup output name -> canonical output name
        kept = []
        block_removed = 0
        for op in block.ops:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [rename.get(n, n) for n in names]
            mergeable = (
                op.type not in RNG_OPS and
                op.type not in walker.SIDE_EFFECT_OPS and
                op.attrs.get('sub_block') is None and
                op.output_names() and
                not any(n in ctx.persistable or n in fetch or
                        n in sub_reads or n in ctx.multi_written or
                        n in ctx.cf_pinned
                        for n in op.output_names()) and
                not any(n in ctx.multi_written for n in op.input_names()))
            if not mergeable:
                kept.append(op)
                continue
            key = (op.type,
                   tuple(sorted((s, tuple(ns))
                                for s, ns in op.inputs.items())),
                   _attr_key(op.attrs))
            first = seen.get(key)
            if first is None:
                seen[key] = op
                kept.append(op)
                continue
            # same computation: rebind this op's outputs to the first's
            ok = True
            pairs = []
            for slot, names in op.outputs.items():
                fnames = first.outputs.get(slot, [])
                if len(fnames) != len(names):
                    ok = False
                    break
                pairs.extend(zip(names, fnames))
            if not ok:
                kept.append(op)
                continue
            rename.update(dict(pairs))
            block_removed += 1
        if block_removed:
            block.ops = kept
            stats['ops_removed'] += block_removed
            program._bump()
    return stats
