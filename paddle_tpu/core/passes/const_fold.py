"""Constant folding: collapse compile-time-constant op chains into single
``fill_constant`` ops.

`fill_constant` (and `assign` of one) seeds the constant environment;
whitelisted pure elementwise ops whose inputs are ALL known constants are
evaluated at optimize time **via the op's own registered kernel** — the
same jnp code the trace would run, on the same backend, so the folded
value is dtype-exact (fill_constant materializes through
``dtypes.jax_dtype``, exactly like the runtime does).  Only uniform
results fold (a fill_constant can represent nothing else); elementwise
ops of uniform inputs are uniform by construction, the check is a
belt-and-braces guard.

The classic win is LR-schedule and loss-scaling glue built from Python
scalars: ``fill_constant -> scale -> elementwise_pow`` chains become one
op, and the orphaned producers are swept by the DCE helper.
"""
import numpy as np

from . import dce

__all__ = ['run', 'FOLDABLE_OPS']

# pure ops safe to evaluate on host at optimize time (no rng, no shape
# surprises, uniform-in -> uniform-out)
FOLDABLE_OPS = {
    'scale', 'cast', 'elementwise_add', 'elementwise_sub',
    'elementwise_mul', 'elementwise_div', 'elementwise_pow',
    'elementwise_max', 'elementwise_min', 'sqrt', 'rsqrt', 'abs',
    'square', 'sign', 'floor', 'ceil', 'round', 'reciprocal', 'exp',
    'log', 'clip', 'pow', 'sigmoid', 'tanh', 'relu',
}

# don't materialize huge arrays on host just to prove them uniform
_MAX_FOLD_ELEMS = 1 << 16


class _FoldCtx(object):
    """Minimal exec ctx for host evaluation: foldable ops use no rng."""
    is_infer = False
    mesh = None
    amp = False


def _const_value(op):
    """(value, shape, dtype) when `op` is a representable constant."""
    if op.type != 'fill_constant':
        return None
    shape = [int(d) for d in op.attrs.get('shape', [])]
    if any(d < 0 for d in shape):
        return None
    return (op.attrs.get('value', 0.0), tuple(shape),
            op.attrs.get('dtype', 'float32'))


def _materialize(const):
    import jax.numpy as jnp
    from ..dtypes import jax_dtype
    value, shape, dtype = const
    return jnp.full(shape, value, dtype=jax_dtype(dtype))


def _eval_op(op, const_env):
    """Run the op's kernel on the materialized constant inputs; returns
    the folded (value, shape, dtype) or None when the result can't be a
    fill_constant."""
    from .. import registry
    impl = registry.get_op(op.type).impl
    ins = {}
    for slot, names in op.inputs.items():
        vals = [_materialize(const_env[n]) for n in names]
        if any(np.prod(v.shape or (1,)) > _MAX_FOLD_ELEMS for v in vals):
            return None
        ins[slot] = vals if op.input_is_list[slot] else vals[0]
    try:
        outs = impl(_FoldCtx(), ins, op.attrs)
    except Exception:  # noqa: BLE001 - give up, leave the op in place
        return None
    out = outs.get('Out')
    if out is None or isinstance(out, (list, tuple)):
        return None
    arr = np.asarray(out)
    if arr.size == 0 or arr.size > _MAX_FOLD_ELEMS:
        return None
    first = arr.ravel()[0]
    if not np.all(arr == first):  # NaN never folds (NaN != NaN): fine
        return None
    return (first.item(), tuple(int(d) for d in arr.shape),
            str(arr.dtype) if arr.dtype.names is None else None)


def run(program, ctx):
    from .. import registry
    stats = {'ops_folded': 0, 'ops_removed': 0}
    multi = ctx.multi_written
    for block in program.blocks:
        const_env = {}
        for op in block.ops:
            outs = op.output_names()
            if any(n in multi for n in outs) or \
                    any(n in multi for n in op.input_names()):
                continue
            if any(n in ctx.persistable or n in ctx.cf_pinned
                   for n in outs):
                continue
            c = _const_value(op)
            if c is not None:
                const_env[outs[0]] = c
                continue
            if op.type == 'assign' and op.input_names() and \
                    op.input_names()[0] in const_env and len(outs) == 1:
                folded = const_env[op.input_names()[0]]
            elif (op.type in FOLDABLE_OPS and len(outs) == 1 and
                    registry.has_op(op.type) and op.input_names() and
                    all(n in const_env for n in op.input_names())):
                folded = _eval_op(op, const_env)
            else:
                continue
            if folded is None or folded[2] is None:
                continue
            value, shape, dtype = folded
            # rewrite IN PLACE into the single equivalent fill_constant;
            # source_loc and the output binding survive untouched
            op.type = 'fill_constant'
            op.inputs = {}
            op.input_is_list = {}
            keep = {k: op.attrs[k] for k in ('op_role', 'recompute_id',
                                             'rng_stream')
                    if k in op.attrs}
            op.attrs = dict(keep, shape=list(shape), value=value,
                            dtype=dtype)
            const_env[outs[0]] = (value, tuple(shape), dtype)
            stats['ops_folded'] += 1
            program._bump()
    if stats['ops_folded']:
        # producers a folded chain no longer reads are now dead
        dce.sweep_dead(program, ctx.fetch_names, stats,
                       pinned=ctx.cf_pinned)
    return stats
